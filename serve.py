"""Decode-server driver: continuous batching over the paged KV cache.

The serving counterpart of `train_lm.py` — builds a transformer LM
(seeded init or random-weights demo; real deployments load a
checkpoint via `--ckpt`), then serves a stream of requests through
`shallowspeed_tpu.serving.ServingEngine`: requests join and leave the
running decode batch between ticks (no recompiles after warmup), long
prompts prefill in chunks interleaved with decode ticks, and every
completion stamps a schema-v6 `"request"` SLO record (ttft/tpot/queue
depth/preemptions) into the metrics JSONL that
`python -m shallowspeed_tpu.telemetry --goodput` reduces to p50/p95.

Requests arrive as JSONL (`--requests FILE`, `-` = stdin), one object
per line:

    {"id": "r0", "prompt": [17, 3, 92], "max_new": 24}
    {"id": "r1", "prompt_len": 512, "prompt_seed": 7, "max_new": 16,
     "temperature": 1.0, "seed": 5, "at": 0.25}

`prompt` is explicit token ids; `prompt_len`(+`prompt_seed`) draws a
random prompt — the tokenizer-free demo path. `at` is the submission
offset in seconds from run start (default 0: submit immediately), so
a request file doubles as an offered-load trace.

Each completion prints one `{"event": "result", ...}` JSONL line to
stdout; the run ends with the request-latency summary
(`telemetry/report.request_summary`).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    m = p.add_argument_group("model")
    m.add_argument("--vocab", type=int, default=256)
    m.add_argument("--d-model", type=int, default=64)
    m.add_argument("--n-heads", type=int, default=4)
    m.add_argument("--n-layers", type=int, default=2)
    m.add_argument("--max-seq", type=int, default=512)
    m.add_argument("--rope", action="store_true")
    m.add_argument("--init-seed", type=int, default=0,
                   help="weight-init seed for the demo model")
    m.add_argument("--ckpt", default=None,
                   help="checkpoint dir to load params from "
                        "(shallowspeed_tpu.checkpoint layout)")
    s = p.add_argument_group("serving")
    s.add_argument("--n-blocks", type=int, default=128)
    s.add_argument("--block-size", type=int, default=16)
    s.add_argument("--slots", type=int, default=4,
                   help="decode-slot capacity (the compiled tick's "
                        "fixed row count)")
    s.add_argument("--prefill-chunk", type=int, default=64)
    s.add_argument("--table-bucket", type=int, default=4)
    s.add_argument("--kv-quant", default="", choices=["", "int8"])
    s.add_argument("--weight-quant", default="",
                   choices=["", "int8", "fp8"],
                   help="quantized weight storage with fused dequant "
                        "(per-out-channel f32 scales; halves the "
                        "param sweep behind every decode tick)")
    s.add_argument("--attn-impl", default="gather",
                   choices=["gather", "flash"],
                   help="decode-tick attention: 'gather' = the XLA "
                        "reference (gather_table + masked_attention), "
                        "'flash' = the paged Pallas flash-decode "
                        "kernel (grid over the block table, no "
                        "gathered copy)")
    s.add_argument("--spec-k", type=int, default=0,
                   help="speculative decoding: up to K self-drafted "
                        "(n-gram prompt-lookup) tokens per decoding "
                        "request per tick, verified in the same "
                        "compiled tick's free rows; 0 = off. Output "
                        "streams are token-identical to spec-off")
    s.add_argument("--spec-ngram", type=int, default=3,
                   help="longest n-gram the draft proposer matches")
    s.add_argument("--top-k", type=int, default=0)
    s.add_argument("--top-p", type=float, default=0.0)
    s.add_argument("--prefix-cache", default="on",
                   choices=["off", "on"],
                   help="content-addressed prefix caching: requests "
                        "sharing block-aligned prompt prefixes map the "
                        "shared KV blocks straight into their tables "
                        "(refcounted, copy-on-write at the tail) and "
                        "skip that prefill. Streams are token-identical "
                        "to off — off is the parity oracle bench uses")
    p.add_argument("--requests", default="-",
                   help="JSONL request file, or - for stdin (ignored "
                        "under --serve unless explicitly set)")
    p.add_argument("--serve", action="store_true",
                   help="replica mode: stay up and accept requests "
                        "over HTTP (POST /submit, GET /requests, "
                        "POST /drain on the monitor endpoint — "
                        "--monitor-port defaults to 0) until a drain "
                        "completes; the surface a fleet router "
                        "(router.py) drives")
    p.add_argument("--max-queue", type=int, default=256,
                   help="with --serve: typed EngineOverloaded "
                        "rejection past this many queued+running "
                        "requests (backpressure, not silent growth)")
    p.add_argument("--heartbeat-file", default=None,
                   help="liveness+health beat file (written ~5 Hz by "
                        "the serve loop; a chaos freeze fault stops "
                        "it) — the router's hang detection reads its "
                        "mtime, like the elastic supervisor's")
    p.add_argument("--log-file", default=None,
                   help="metrics JSONL (request/generate events)")
    p.add_argument("--log-every", type=int, default=16,
                   help="decode ticks between 'generate' stat lines")
    c = p.add_argument_group("chaos (shallowspeed_tpu.chaos)")
    c.add_argument("--chaos", default="",
                   help="tick-indexed fault plan for THIS server "
                        "(chaos DSL, e.g. 'stall@4:0.5,kill@9'; step "
                        "faults index engine ticks) — serving drills "
                        "of the recovery/observability stack")
    c.add_argument("--chaos-state", default="",
                   help="fired-fault marker dir (must survive "
                        "restarts under a supervisor)")
    c.add_argument("--chaos-seed", type=int, default=0)
    o = p.add_argument_group("live monitoring (telemetry/monitor)")
    o.add_argument("--replica", default=None,
                   help="replica label for fleet views: stamped on "
                        "the run_start line and served from "
                        "/status.json, so a FleetCollector names this "
                        "process in per-replica breakdowns and "
                        "straggler events")
    o.add_argument("--fleet-register", default=None, metavar="URL",
                   help="announce this replica's own monitor endpoint "
                        "to a fleet collector (POST URL/register; "
                        "needs --monitor-port)")
    o.add_argument("--monitor-port", type=int, default=None,
                   help="serve /status.json + /metrics (Prometheus "
                        "text) on 127.0.0.1:PORT while the run is "
                        "live (0 = pick a free port, printed at start)")
    o.add_argument("--slo", default="",
                   help="declarative SLOs evaluated over dual burn-"
                        "rate windows, e.g. "
                        "'ttft_p95_ms<500,availability>0.99'; state "
                        "transitions land as schema-v7 'alert' events")
    o.add_argument("--flight-recorder", type=int, default=0,
                   help="keep the last N metrics/span records in a "
                        "ring and dump flightrec_<step>.json on an "
                        "anomaly verdict, chaos fault, or SLO alert "
                        "(0 = off)")
    o.add_argument("--shed-load", action="store_true",
                   help="wire SLO alerts into Engine.on_alert: pause "
                        "admission while a critical burn persists "
                        "(default: alerts are telemetry-only)")
    o.add_argument("--profile", default="off",
                   choices=["off", "host", "host+device"],
                   help="continuous profiling plane (telemetry/"
                        "profiler): 'host' runs the always-on stack "
                        "sampler (schema-v12 'profile' events in the "
                        "metrics JSONL, /profile.json on the monitor "
                        "endpoint) and arms burn/fault/anomaly-"
                        "triggered capture windows (profcap_*.json); "
                        "'host+device' additionally wraps each "
                        "capture in a bounded jax.profiler device "
                        "trace")
    o.add_argument("--profile-hz", type=float, default=None,
                   help="host sampler rate (default 67 Hz — off the "
                        "100/50 Hz scheduler beats)")
    p.add_argument("--platform", default=None,
                   help="jax platform override (e.g. cpu)")
    return p.parse_args(argv)


def load_requests(path: str, vocab: int) -> list[dict]:
    import numpy as np

    raw = (sys.stdin.read() if path == "-"
           else Path(path).read_text())
    reqs = []
    for i, line in enumerate(raw.splitlines()):
        if not line.strip():
            continue
        rec = json.loads(line)
        rec.setdefault("id", f"r{i}")
        if "prompt" in rec:
            # explicit token ids are the caller's exact prompt — an
            # out-of-vocab id is an error, never a silent remap
            rec["prompt"] = np.asarray(rec["prompt"], np.int32)
            if rec["prompt"].size and (
                    int(rec["prompt"].min()) < 0
                    or int(rec["prompt"].max()) >= vocab):
                raise ValueError(
                    f"request {rec['id']!r}: prompt token ids must be "
                    f"in [0, {vocab}); got range "
                    f"[{int(rec['prompt'].min())}, "
                    f"{int(rec['prompt'].max())}]")
        else:
            # the tokenizer-free demo path draws in-vocab ids directly
            rng = np.random.default_rng(rec.get("prompt_seed", i))
            rec["prompt"] = rng.integers(
                0, vocab, rec["prompt_len"]).astype(np.int32)
        rec.setdefault("at", 0.0)
        reqs.append(rec)
    reqs.sort(key=lambda r: r["at"])
    return reqs


def main(argv=None) -> int:
    args = parse_args(argv)
    if args.platform:
        import jax

        jax.config.update("jax_platforms", args.platform)
    import jax
    import numpy as np

    from shallowspeed_tpu.elastic import install_sigterm_exit
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving import ServingEngine
    from shallowspeed_tpu.telemetry.report import request_summary

    # supervisor kill path (same contract as the train drivers):
    # SIGTERM becomes SystemExit so the finally block below flushes
    # the request/ledger tail and the final summary line before the
    # supervisor's SIGKILL deadline — a killed server must leave a
    # reducible metrics file, not a truncated one
    install_sigterm_exit()

    cfg = T.TransformerConfig(
        vocab=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
        n_layers=args.n_layers, max_seq=args.max_seq, rope=args.rope)
    if args.ckpt:
        from shallowspeed_tpu import checkpoint

        params = checkpoint.restore(args.ckpt)["params"]
    else:
        params = jax.device_put(T.init(cfg, seed=args.init_seed))
    # replica mode: requests arrive over HTTP; the default "-" must
    # not block on a subprocess's empty stdin
    reqs = ([] if args.serve and args.requests == "-"
            else load_requests(args.requests, cfg.vocab))
    if args.serve and args.monitor_port is None:
        args.monitor_port = 0
    run_info = dict(kind="serve", vocab=cfg.vocab,
                    d_model=cfg.d_model, n_layers=cfg.n_layers,
                    n_blocks=args.n_blocks, block_size=args.block_size,
                    slots=args.slots, prefill_chunk=args.prefill_chunk,
                    kv_quant=args.kv_quant,
                    weight_quant=args.weight_quant,
                    attn_impl=args.attn_impl, spec_k=args.spec_k,
                    prefix_cache=args.prefix_cache)
    if args.replica:
        run_info["replica"] = args.replica
    metrics = MetricsLogger(args.log_file, **run_info)
    # chaos (serving drills): tick-indexed faults through the same
    # plan machinery the train drivers use; fault stamps land in this
    # replica's metrics JSONL so fleet views see what was injected
    from shallowspeed_tpu import chaos

    chaos.setup(args.chaos, seed=args.chaos_seed,
                state_dir=args.chaos_state or None,
                log_file=args.log_file)
    eng = ServingEngine(
        params, cfg, n_blocks=args.n_blocks,
        block_size=args.block_size, max_slots=args.slots,
        prefill_chunk=args.prefill_chunk,
        table_bucket=args.table_bucket, kv_quant=args.kv_quant,
        weight_quant=args.weight_quant, attn_impl=args.attn_impl,
        spec_k=args.spec_k, spec_ngram=args.spec_ngram,
        top_k=args.top_k, top_p=args.top_p, metrics=metrics,
        log_every=args.log_every,
        prefix_cache=(args.prefix_cache == "on"))

    # live telemetry plane: /status.json + /metrics endpoint, SLO
    # burn-rate alerts (optionally shedding load via Engine.on_alert),
    # anomaly flight recorder — all fed by the same metrics lines the
    # JSONL gets (MetricsLogger.monitor). In --serve mode the request
    # gateway is grafted onto the SAME endpoint (POST /submit, GET
    # /requests, POST /drain), so one registered URL serves both the
    # fleet's observation polls and the router's dispatch.
    from shallowspeed_tpu.telemetry.monitor import (close_monitor,
                                                    from_args)

    gateway = None
    if args.serve:
        from shallowspeed_tpu.serving.router import RequestGateway

        gateway = RequestGateway(max_queue=args.max_queue)
    mon, server = from_args(args, metrics, extra=gateway)
    if server is not None:
        print(json.dumps({"event": "monitor_listening",
                          "url": server.url("/status.json")}),
              flush=True)
    if mon is not None and args.shed_load:
        mon.alert_listeners.append(eng.on_alert)
    if mon is not None:
        # memory observatory (round 20): block exhaustion trips a full
        # forensic flight dump — per-owner HBM bytes, top arrays, the
        # allocator snapshot, block-table widths, the in-flight set.
        # The listener fires BEFORE the engine stamps its oom ledger
        # line, so this rich payload wins the flight recorder's
        # (reason="oom", step=tick) dedup over the bare ledger trigger.
        eng.oom_listeners.append(
            lambda en, exc: mon.memory_flight_dump(
                en.oom_forensics(exc), step=en.counters["ticks"]))
    # continuous profiling plane (round 17): the always-on host stack
    # sampler streams schema-v12 "profile" snapshots into the same
    # metrics JSONL, and critical SLO burns / chaos fault stamps /
    # anomaly verdicts arm bounded high-rate capture windows
    # (profcap_<step>.json next to the flight-recorder dumps)
    from shallowspeed_tpu.telemetry import profiler as profiler_mod

    plane = profiler_mod.from_args(args, metrics)
    if plane is not None:
        chaos.add_observer(plane.on_fault)
        if mon is not None:
            mon.profiler = plane
            mon.alert_listeners.append(plane.on_alert)
    phase_tag = profiler_mod.tag     # no-op context when plane is off
    if args.fleet_register:
        # announce this replica to a fleet collector (best effort —
        # the fleet may come up after us and poll-register instead)
        if server is None:
            p_err = ("--fleet-register needs --monitor-port (the "
                     "fleet polls our endpoint)")
            raise SystemExit(p_err)
        import urllib.request

        body = json.dumps({
            "url": server.url("/status.json"),
            "name": args.replica or f"pid{__import__('os').getpid()}",
        }).encode()
        try:
            urllib.request.urlopen(urllib.request.Request(
                args.fleet_register.rstrip("/") + "/register",
                data=body,
                headers={"Content-Type": "application/json"}),
                timeout=5).read()
        except Exception as e:
            print(json.dumps({"event": "error",
                              "error": f"fleet register failed: "
                                       f"{type(e).__name__}: {e}"}),
                  flush=True)

    t0 = time.time()
    i = 0
    reported: set[str] = set()
    drained_clean = False
    last_hb = 0.0
    try:
        while True:
            now = time.time() - t0
            if args.heartbeat_file and time.time() - last_hb > 0.2 \
                    and not chaos.heartbeat_frozen():
                # liveness + health beat (~5 Hz between engine steps):
                # the router's hang detection reads the mtime exactly
                # like the elastic supervisor's — a chaos freeze fault
                # stops the beats while the loop keeps serving, which
                # is the hang drill
                from shallowspeed_tpu.elastic import write_heartbeat

                try:
                    write_heartbeat(args.heartbeat_file, "ok")
                except OSError:
                    pass
                last_hb = time.time()
            while i < len(reqs) and reqs[i]["at"] <= now:
                r = reqs[i]
                i += 1
                try:
                    # a request line may carry its own trace id (an
                    # upstream edge's context); absent, the engine
                    # mints one so standalone lifecycle streams still
                    # stitch (schema v11)
                    eng.submit(r["prompt"], r["max_new"],
                               temperature=r.get("temperature", 0.0),
                               seed=r.get("seed", 0), rid=r["id"],
                               trace=r.get("trace"))
                except (KeyError, TypeError, ValueError) as e:
                    # one bad request (too long for max_seq/pool,
                    # duplicate id, missing/mistyped fields) must not
                    # kill the server — report it and keep draining
                    print(json.dumps(
                        {"event": "error", "id": r["id"],
                         "error": f"{type(e).__name__}: {e}"}))
            if gateway is not None:
                with phase_tag("gateway"):
                    gateway.pump(eng)
            if eng.pending():
                eng.step()
            elif i < len(reqs):
                time.sleep(min(0.05, max(0.0, reqs[i]["at"] - now)))
            elif gateway is not None \
                    and not gateway.drain_requested:
                time.sleep(0.02)        # idle replica: await HTTP work
            if gateway is not None:
                with phase_tag("gateway"):
                    gateway.publish(eng)
            for rec in eng.request_records[len(reported):]:
                reported.add(rec["id"])
                print(json.dumps({
                    "event": "result", "id": rec["id"],
                    "tokens": [int(t) for t in eng.results[rec["id"]]],
                    "ttft_ms": rec["ttft_ms"],
                    "tpot_ms": rec.get("tpot_ms")}), flush=True)
            if gateway is not None:
                if gateway.drain_requested and gateway.idle() \
                        and eng.drain():
                    drained_clean = True
                    break
            elif i >= len(reqs) and not eng.pending():
                break
        if drained_clean and args.fleet_register and server is not None:
            # clean drain completes with DEREGISTRATION — a drained
            # replica must not linger in the fleet as "unreachable",
            # burning availability forever (the old one-way register)
            import urllib.request

            try:
                urllib.request.urlopen(urllib.request.Request(
                    args.fleet_register.rstrip("/") + "/deregister",
                    data=json.dumps({
                        "url": server.url("/status.json"),
                        "name": args.replica or None}).encode(),
                    headers={"Content-Type": "application/json"}),
                    timeout=5).read()
            except Exception as e:
                print(json.dumps({"event": "error",
                                  "error": f"fleet deregister failed: "
                                           f"{type(e).__name__}: {e}"}),
                      flush=True)
    finally:
        # reached on clean drain AND on the SIGTERM SystemExit: the
        # summary line + the monitor's final sketch snapshot land in
        # the outputs either way, so a supervisor-killed server still
        # reduces (--goodput) and merges (schema-v7 monitor events)
        wall = time.time() - t0
        summary = request_summary(eng.request_records) or {}
        summary.update({
            "wall_s": round(wall, 3),
            "tok_per_sec": round(
                sum(r["tokens_out"] for r in eng.request_records)
                / max(wall, 1e-9), 2),
            "ticks": eng.counters["ticks"],
            "prefill_chunks": eng.counters["prefill_chunks"],
            "preemptions": eng.counters["preempted"],
            "shed_toggles": eng.counters["shed_toggles"],
            "spec_drafted": eng.counters["spec_drafted"],
            "spec_accepted": eng.counters["spec_accepted"],
            "pending_at_exit": eng.pending(),
            "drained": drained_clean,
            "executables": eng.executable_counts(),
            "blocks_free_at_drain":
                f"{eng.alloc.n_free}/{eng.alloc.n_usable}",
        })
        print(json.dumps({"event": "summary", **summary}), flush=True)
        if plane is not None:
            chaos.remove_observer(plane.on_fault)
            plane.close()
        close_monitor(mon, server)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
