"""Prepare the MNIST-784 dataset under data/mnist_784/.

Same entrypoint role as the reference's `download_dataset.py`; falls back to a
deterministic synthetic MNIST-784 in air-gapped environments (see
`shallowspeed_tpu/data/mnist.py`).
"""

import argparse

from shallowspeed_tpu.data.mnist import prepare_mnist

if __name__ == "__main__":
    p = argparse.ArgumentParser()
    p.add_argument("--save-dir", default="data/mnist_784")
    p.add_argument("--synthetic", action="store_true",
                   help="skip the OpenML fetch and generate synthetic data")
    args = p.parse_args()
    out = prepare_mnist(args.save_dir, synthetic=True if args.synthetic else None)
    print(f"dataset ready at {out}")
