"""Fleet serving driver: an SLO-aware, fault-tolerant router over N
`serve.py --serve` replica processes.

The serving counterpart of `python -m shallowspeed_tpu.elastic`: where
the elastic supervisor restarts ONE training job from checkpoint, this
drives a FLEET of decode replicas and makes replica failure invisible
to clients — requests that were mid-decode on a killed replica
re-dispatch (seeded, idempotent) to a surviving one and their streams
continue token-identical to the solo `generate()` oracle. Pieces (all
in `shallowspeed_tpu/serving/router.py`):

- a `FleetCollector` + fleet `/status.json` endpoint the replicas
  self-register with (`--monitor-port`, default 0 = free port) — also
  the router's admission-weight source;
- per-replica circuit breakers, per-request deadlines/timeouts with
  failover, fleet-edge backpressure (typed reject + retry-after);
- classified respawn with per-class backoff (elastic.RestartPolicy),
  hang detection off each replica's heartbeat file;
- burn-driven autoscaling (`--autoscale`): sustained critical ttft
  burn spawns a replica, sustained idle drains one gracefully
  (deregistration included).

Requests use serve.py's JSONL format (ids, prompts or `prompt_len`
demos, per-request sampler/seed, `at` arrival offsets). Every routing
decision lands in `--log-file` (schema v10: "route"/"failover"/
"scale" events, breaker + restart_downtime ledger stamps, fleet-edge
"request" records), so

    python -m shallowspeed_tpu.telemetry --goodput run/router.jsonl

reports request percentiles, per-replica MTTR, and fleet availability
from the router log alone — and, with the per-replica logs appended,
the per-request latency waterfall block (schema v11 trace context).
The whole fleet's logs stitch onto one skew-corrected timeline:

    python -m shallowspeed_tpu.telemetry --trace-stitch \
        run/router.jsonl run/replica_r*.jsonl --out trace.json

(Perfetto-loadable; every failover visible as a gap on the failed-over
request's journey track.) Fleet chaos drills: `--chaos-fleet
'r0=kill@6;r1=stall@4:0.5' --chaos-state DIR` hands each named
replica its own seeded fault plan.

    python router.py --replicas 3 --requests reqs.jsonl \
        --log-file run/router.jsonl --slo 'ttft_p95_ms<500' \
        --autoscale --max-replicas 4 --hang-timeout 10
"""

from __future__ import annotations

import argparse
import json
import shlex
import sys
import time
from collections import deque
from pathlib import Path


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    m = p.add_argument_group("model (forwarded to every replica)")
    m.add_argument("--vocab", type=int, default=256)
    m.add_argument("--d-model", type=int, default=64)
    m.add_argument("--n-heads", type=int, default=4)
    m.add_argument("--n-layers", type=int, default=2)
    m.add_argument("--max-seq", type=int, default=512)
    m.add_argument("--rope", action="store_true")
    m.add_argument("--init-seed", type=int, default=0)
    m.add_argument("--ckpt", default=None)
    s = p.add_argument_group("serving (forwarded to every replica)")
    s.add_argument("--n-blocks", type=int, default=128)
    s.add_argument("--block-size", type=int, default=16)
    s.add_argument("--slots", type=int, default=4)
    s.add_argument("--prefill-chunk", type=int, default=64)
    s.add_argument("--prefix-cache", default="on",
                   choices=["off", "on"],
                   help="prefix caching on every replica (serve.py "
                        "--prefix-cache) AND sticky prefix-affinity "
                        "routing router-side: prompts fingerprint by "
                        "their leading aligned chunks and a replica "
                        "that already served a prefix earns a bounded "
                        "dispatch bonus, so shared-prompt traffic "
                        "lands where its KV blocks already live")
    s.add_argument("--replica-args", default="",
                   help="extra raw serve.py args appended to every "
                        "replica's command (shlex-split), e.g. "
                        "'--weight-quant int8 --spec-k 4'")
    s.add_argument("--profile", default="off",
                   choices=["off", "host", "host+device"],
                   help="continuous profiling plane (telemetry/"
                        "profiler), forwarded to every replica "
                        "(serve.py --profile) AND run router-side: "
                        "each replica streams schema-v12 'profile' "
                        "events the FleetCollector merges into a "
                        "replica-labelled fleet flamegraph "
                        "(/profile.json on the fleet endpoint), the "
                        "router samples its own dispatch loop into "
                        "--log-file, and a firing straggler event "
                        "arms a router-side capture window")
    f = p.add_argument_group("fleet")
    f.add_argument("--replicas", type=int, default=2,
                   help="initial replica count")
    f.add_argument("--min-replicas", type=int, default=1)
    f.add_argument("--max-replicas", type=int, default=4)
    f.add_argument("--autoscale", action="store_true",
                   help="close the loop: sustained critical SLO burn "
                        "spawns a replica, sustained idle drains one "
                        "(graceful, deregistered, zero drops)")
    f.add_argument("--slo", default="",
                   help="fleet-edge SLOs over the router's own "
                        "observations (monitor DSL, e.g. "
                        "'ttft_p95_ms<500,availability>0.99') — also "
                        "the autoscale burn signal")
    f.add_argument("--scale-hold", type=float, default=5.0,
                   help="seconds a critical burn must persist before "
                        "a scale-up")
    f.add_argument("--idle-drain", type=float, default=30.0,
                   help="seconds of fleet idle before a scale-down "
                        "drain")
    f.add_argument("--scale-cooldown", type=float, default=10.0)
    r = p.add_argument_group("router")
    r.add_argument("--monitor-port", type=int, default=0,
                   help="the fleet endpoint (collector /status.json + "
                        "/metrics + POST /register|/deregister); "
                        "replicas self-register here. 0 = free port, "
                        "printed at start")
    r.add_argument("--log-file", default=None,
                   help="router metrics JSONL (schema v10 route/"
                        "failover/scale events + ledger stamps + "
                        "fleet-edge request records)")
    r.add_argument("--requests", default="-",
                   help="JSONL request file (serve.py format), or - "
                        "for stdin")
    r.add_argument("--request-timeout", type=float, default=30.0,
                   help="seconds without new tokens before a request "
                        "fails over to another replica")
    r.add_argument("--deadline", type=float, default=None,
                   help="default per-request e2e deadline in seconds "
                        "(typed failure past it); per-request "
                        "'deadline' fields in the JSONL override")
    r.add_argument("--queue-budget", type=int, default=256,
                   help="router pending-queue budget; past it submit "
                        "rejects typed with retry-after")
    e = p.add_argument_group("supervision (elastic taxonomy)")
    e.add_argument("--hang-timeout", type=float, default=None,
                   help="kill+respawn a replica whose heartbeat goes "
                        "stale this long")
    e.add_argument("--term-grace", type=float, default=5.0)
    e.add_argument("--max-restarts", type=int, default=3,
                   help="per-replica restart budget (per-class "
                        "jittered backoff, elastic.RestartPolicy)")
    e.add_argument("--backoff", type=float, default=1.0)
    c = p.add_argument_group("chaos (fleet drills)")
    c.add_argument("--chaos-fleet", default="",
                   help="per-replica fault plans: "
                        "'r0=kill@6;r1=stall@4:0.5' — each named "
                        "replica runs its own seeded plan "
                        "(serve.py --chaos); faults index engine "
                        "ticks")
    c.add_argument("--chaos-state", default="",
                   help="fired-marker base dir (per-replica subdirs; "
                        "MUST survive respawns — required with "
                        "--chaos-fleet)")
    c.add_argument("--chaos-seed", type=int, default=0)
    p.add_argument("--run-dir", default=None,
                   help="replica logs + heartbeat files land here "
                        "(default: the --log-file's directory, else "
                        "a tempdir)")
    p.add_argument("--platform", default=None,
                   help="jax platform override forwarded to replicas "
                        "(e.g. cpu)")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    import tempfile

    from shallowspeed_tpu import chaos
    from shallowspeed_tpu.metrics import MetricsLogger
    from shallowspeed_tpu.serving.router import (FleetOverloaded,
                                                 ReplicaProc, Router)
    from shallowspeed_tpu.telemetry.fleet import FleetCollector
    from shallowspeed_tpu.telemetry.monitor import StatusServer
    from shallowspeed_tpu.telemetry.report import request_summary

    from serve import load_requests

    chaos_map = {}
    if args.chaos_fleet:
        if not args.chaos_state:
            raise SystemExit("--chaos-fleet needs --chaos-state "
                             "(fired-fault markers must survive "
                             "respawns, or every respawned replica "
                             "re-fires every fault)")
        chaos_map = chaos.parse_fleet_spec(args.chaos_fleet)
    run_dir = Path(args.run_dir) if args.run_dir else (
        Path(args.log_file).parent if args.log_file
        else Path(tempfile.mkdtemp(prefix="router_")))
    run_dir.mkdir(parents=True, exist_ok=True)
    reqs = ([] if args.requests == "-" and sys.stdin.isatty()
            else load_requests(args.requests, args.vocab))

    metrics = MetricsLogger(args.log_file, kind="router",
                            replicas=args.replicas, slo=args.slo,
                            autoscale=args.autoscale)
    collector = FleetCollector()
    fleet_srv = StatusServer(collector, port=args.monitor_port)
    fleet_url = f"http://{fleet_srv.host}:{fleet_srv.port}"
    print(json.dumps({"event": "fleet_listening",
                      "url": fleet_srv.url("/status.json")}),
          flush=True)
    collector.start(poll=0.5)

    serve_py = str(Path(__file__).resolve().parent / "serve.py")
    model_args = ["--vocab", str(args.vocab),
                  "--d-model", str(args.d_model),
                  "--n-heads", str(args.n_heads),
                  "--n-layers", str(args.n_layers),
                  "--max-seq", str(args.max_seq),
                  "--init-seed", str(args.init_seed),
                  "--n-blocks", str(args.n_blocks),
                  "--block-size", str(args.block_size),
                  "--slots", str(args.slots),
                  "--prefill-chunk", str(args.prefill_chunk),
                  "--prefix-cache", args.prefix_cache]
    if args.rope:
        model_args.append("--rope")
    if args.ckpt:
        model_args += ["--ckpt", args.ckpt]
    if args.platform:
        model_args += ["--platform", args.platform]
    if args.profile != "off":
        model_args += ["--profile", args.profile]
    model_args += shlex.split(args.replica_args)

    # router-side profiling plane (round 17): the router's own host
    # sampler (dispatch loop, progress polls) streams into --log-file;
    # a firing straggler event arms a bounded capture window next to it
    from shallowspeed_tpu.telemetry import profiler as profiler_mod

    plane = profiler_mod.from_args(args, metrics, out_dir=run_dir)
    if plane is not None:
        collector.straggler_listeners.append(plane.on_straggler)

    def spawn(name: str) -> ReplicaProc:
        hb = str(run_dir / f"hb_{name}")
        child_argv = [sys.executable, serve_py, "--serve",
                      "--monitor-port", "0",
                      "--fleet-register", fleet_url,
                      "--replica", name,
                      "--log-file", str(run_dir / f"replica_{name}"
                                                  ".jsonl"),
                      "--heartbeat-file", hb] + model_args
        if name in chaos_map:
            child_argv += ["--chaos", chaos_map[name],
                           "--chaos-state",
                           str(Path(args.chaos_state) / name),
                           "--chaos-seed", str(args.chaos_seed)]
        return ReplicaProc(name, child_argv, collector,
                           heartbeat_file=hb,
                           hang_timeout=args.hang_timeout,
                           term_grace=args.term_grace,
                           stdout_path=str(run_dir
                                           / f"replica_{name}.out"))

    router = Router(
        spawn, n_replicas=args.replicas, collector=collector,
        metrics=metrics, slos=args.slo,
        queue_budget=args.queue_budget,
        request_timeout=args.request_timeout,
        default_deadline_s=args.deadline,
        progress_interval=0.2,
        policy_kw=dict(max_restarts=args.max_restarts,
                       backoff=args.backoff, jitter=0.1),
        autoscale=args.autoscale, min_replicas=args.min_replicas,
        max_replicas=args.max_replicas,
        scale_hold_s=args.scale_hold, idle_drain_s=args.idle_drain,
        scale_cooldown_s=args.scale_cooldown,
        sticky=(args.prefix_cache == "on"),
        sticky_block=args.block_size)

    t0 = time.time()
    i = 0
    retry: deque = deque()        # (ready_at, request) after overload
    reported = 0
    try:
        while i < len(reqs) or retry or router.unfinished():
            now = time.time() - t0

            def _offer(r):
                nonlocal retry
                try:
                    router.submit(
                        r["prompt"], r["max_new"],
                        temperature=r.get("temperature", 0.0),
                        seed=r.get("seed", 0), rid=r["id"],
                        deadline_s=r.get("deadline", None))
                except FleetOverloaded as e:
                    # fleet-edge backpressure: honor retry-after
                    retry.append((now + e.retry_after, r))
                except (KeyError, TypeError, ValueError) as e:
                    print(json.dumps(
                        {"event": "error", "id": r.get("id"),
                         "error": f"{type(e).__name__}: {e}"}),
                        flush=True)

            while i < len(reqs) and reqs[i]["at"] <= now:
                _offer(reqs[i])
                i += 1
            # entries are NOT ready_at-ordered (retry_after varies per
            # rejection) — scan the whole deque, not head-until-stuck
            for _ in range(len(retry)):
                ready_at, r = retry.popleft()
                if ready_at <= now:
                    _offer(r)
                else:
                    retry.append((ready_at, r))
            if not router.step():
                time.sleep(0.02)
            if not router.replica_names():
                # every replica retired (restart budgets exhausted):
                # nothing can ever become routable again — fail
                # EVERYTHING that remains (not-yet-offered arrivals,
                # the retry deque, and the router's own pending +
                # in-flight queues) instead of spinning forever;
                # every submitted id gets a terminal record
                dead = "fleet dead: every replica retired"
                for r in ([reqs[j] for j in range(i, len(reqs))]
                          + [r for _, r in retry]):
                    print(json.dumps(
                        {"event": "error", "id": r.get("id"),
                         "error": dead}), flush=True)
                retry.clear()
                i = len(reqs)
                router.fail_unfinished(dead)
                # fall through: the record loop below prints the
                # failed results, then the loop condition drains
            for rec in router.records[reported:]:
                reported += 1
                out = {"event": "result", **rec}
                if rec["status"] == "done":
                    out["tokens"] = [int(t) for t
                                     in router.results[rec["id"]]]
                print(json.dumps(out), flush=True)
    finally:
        wall = time.time() - t0
        done = [r for r in router.records if r["status"] == "done"]
        summary = request_summary(
            [r for r in done if "ttft_ms" in r]) or {}
        summary.update({
            "wall_s": round(wall, 3),
            "replicas": router.replica_names(),
            "counters": dict(router.counters),
        })
        print(json.dumps({"event": "summary", **summary}),
              flush=True)
        router.shutdown()
        if plane is not None:
            plane.close()
        collector.stop()
        fleet_srv.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
