"""Fused training engines — the compiled fast path.

The pipeline VM (`parallel/worker.py`) interprets instruction streams with one
dispatch per instruction, mirroring the reference's executor
(`/root/reference/shallowspeed/pipe.py:434-466`). For dp×1 topologies the
whole batch step can instead be **one** jitted XLA program: `lax.scan` over
the microbatch stack (grad accumulation, `layers.py:135-136` semantics),
`lax.psum` of the accumulated grads over the 'dp' mesh axis (replacing the
interleaved `Iallreduce`/`Waitall`, `pipe.py:302-327` — XLA's latency-hiding
scheduler overlaps the collective with compute), and the optimizer update —
zero Python dispatch inside the step, which is what the TPU wants.

Sequential training (`--dp 1 --pp 1`, reference `train.py:62-155` with no
flags) is the dp=1 special case.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from shallowspeed_tpu.models.mlp import MLPStage, accumulate_grads, zero_grads_like

tree_map = jax.tree_util.tree_map


class FusedDPEngine:
    """One-executable data-parallel trainer over the 'dp' axis of the mesh.

    Equivalent semantics to `PipelineExecutor` with pp=1 and any schedule
    (they all reduce to: zero, k x (fwd, bwd-acc), allreduce, step on a
    single stage) — verified against the VM in tests.
    """

    def __init__(self, stage: MLPStage, optimizer, mesh: Mesh):
        assert stage.n_stages == 1
        self.stage = stage
        self.optimizer = optimizer
        # accept a (dp, 1) 2-D mesh or a 1-D ('dp',) mesh
        if mesh.axis_names != ("dp",):
            devs = mesh.devices.reshape(-1)
            mesh = Mesh(devs, ("dp",))
        self.mesh = mesh
        self.dp = mesh.devices.size
        self.rep = NamedSharding(mesh, P())
        self.shard4 = NamedSharding(mesh, P("dp"))  # (dp, n_mu, mubs, d)

        self.params = jax.device_put(stage.init(), self.rep)
        self.opt_state = jax.device_put(optimizer.init(self.params), self.rep)

        stage_ref = self.stage
        opt_ref = self.optimizer

        @partial(jax.jit, donate_argnums=(0, 1))
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P(), P("dp"), P("dp")),
                 out_specs=(P(), P()))
        def _step(params, opt_state, xs, ys):
            xs, ys = xs[0], ys[0]  # strip the per-device dp block axis

            def body(acc, xy):
                x, y = xy
                _, stash = stage_ref.forward(params, x)
                _, grads = stage_ref.backward(params, stash, y)
                return accumulate_grads(acc, grads), None

            # the zero init is axis-invariant but the accumulated grads vary
            # per dp shard — cast the carry to varying for shard_map's typing
            acc0 = jax.lax.pcast(zero_grads_like(params), ("dp",), to="varying")
            acc, _ = jax.lax.scan(body, acc0, (xs, ys))
            total = tree_map(lambda g: jax.lax.psum(g, "dp"), acc)
            return opt_ref.step(params, total, opt_state)

        @partial(jax.jit)
        @partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                 out_specs=P("dp"))
        def _infer(params, x):
            return stage_ref.infer(params, x)

        self._step = _step
        self._infer = _infer

    # ------------------------------------------------------------- steps

    def train_batch(self, batch_id: int, datasets):
        """datasets: dp per-rank Dataset shards; assembles the
        (dp, n_mu, mubs, d) stacks and runs the fused step."""
        stacks = [ds.load_mubatch_stack(batch_id) for ds in datasets]
        xs = np.stack([s[0] for s in stacks])
        ys = np.stack([s[1] for s in stacks])
        xs = jax.device_put(xs, self.shard4)
        ys = jax.device_put(ys, self.shard4)
        self.params, self.opt_state = self._step(
            self.params, self.opt_state, xs, ys)

    def infer(self, x: np.ndarray) -> jax.Array:
        """Forward on a (rows, 784) batch sharded over dp (rows % dp == 0)."""
        x = jax.device_put(x, NamedSharding(self.mesh, P("dp")))
        return self._infer(self.params, x)
