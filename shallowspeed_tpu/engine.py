"""Fused training engines — the compiled fast path.

The pipeline VM (`parallel/worker.py`) interprets instruction streams with one
dispatch per instruction, mirroring the reference's executor
(`/root/reference/shallowspeed/pipe.py:434-466`). For dp×1 topologies the
whole batch step can instead be **one** jitted XLA program: `lax.scan` over
the microbatch stack (grad accumulation, `layers.py:135-136` semantics), the
DP reduction over the 'dp' mesh axis, and the optimizer update — zero Python
dispatch inside the step, which is what the TPU wants.

The DP reduction has two modes. The default (the oracle) is the bulk
reduction: per-leaf `lax.psum` of the fully accumulated grads AFTER the
microbatch scan — and because the scan is a single dataflow node, every
byte of that reduction is *exposed* (there is no independent compute
left for XLA's latency-hiding scheduler to hide it under). With
`overlap=OverlapConfig(...)` the engine instead peels the last
microbatch out of the scan and interleaves size-targeted bucket psums
into its hand-written layer-by-layer backward
(`parallel/overlap.bucketed_stage_backward`) — the compiled equivalent
of the reference's per-parameter `Iallreduce` hooks interleaving
reduction of layer i with the backward of layer i-1
(`pipe.py:302-327`). Same math, same wire bytes, strictly lower
exposed-communication fraction (telemetry's `exposed_comm_frac`).

Sequential training (`--dp 1 --pp 1`, reference `train.py:62-155` with no
flags) is the dp=1 special case.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# version-compat shard_map (utils.py): VMA jax as-is; pre-VMA jax
# with the legacy replication rewriter disabled
from shallowspeed_tpu.utils import shard_map

from shallowspeed_tpu.models.mlp import MLPStage, accumulate_grads, zero_grads_like
from shallowspeed_tpu.utils import pvary_over as _pvary

tree_map = jax.tree_util.tree_map


def _note_step(engine, pack):
    # health.note_step, imported lazily (telemetry stays off the module
    # import path): stores last_health + device-side cumulative counters
    from shallowspeed_tpu.telemetry.health import note_step

    note_step(engine, pack)



class FusedDPEngine:
    """One-executable data-parallel trainer over the 'dp' axis of the mesh.

    Equivalent semantics to `PipelineExecutor` with pp=1 and any schedule
    (they all reduce to: zero, k x (fwd, bwd-acc), allreduce, step on a
    single stage) — verified against the VM in tests.
    """

    def __init__(self, stage: MLPStage, optimizer, mesh: Mesh,
                 health: str = "off", overlap=None):
        from shallowspeed_tpu.telemetry.health import MODES

        assert stage.n_stages == 1
        assert health in MODES, health
        self.health = health
        self.last_health = None
        self.overlap = overlap  # parallel.overlap.OverlapConfig | None
        self.stage = stage
        self.optimizer = optimizer
        # accept a (dp, 1) 2-D mesh or a 1-D ('dp',) mesh
        if mesh.axis_names != ("dp",):
            devs = mesh.devices.reshape(-1)
            mesh = Mesh(devs, ("dp",))
        self.mesh = mesh
        self.dp = mesh.devices.size
        self.rep = NamedSharding(mesh, P())
        self.shard4 = NamedSharding(mesh, P("dp"))  # (dp, n_mu, mubs, d)

        self.params = jax.device_put(stage.init(), self.rep)
        self.opt_state = jax.device_put(optimizer.init(self.params), self.rep)

        stage_ref = self.stage
        opt_ref = self.optimizer

        # bucket plan for the overlapped reduction: the stage's leaves
        # in backward-finalization order, partitioned by target bytes
        if overlap is not None:
            from shallowspeed_tpu.parallel import overlap as OV

            order = OV.mlp_leaf_order(self.params)
            raw = OV.plan_buckets([l for _, l in order],
                                  overlap.bucket_bytes)
            ov_plan = [[order[j][0] for j in b] for b in raw]
            leaf_by_id = dict(order)
            self._bucket_sigs = [
                OV.bucket_signature([leaf_by_id[i] for i in b])
                for b in ov_plan]
        else:
            ov_plan = None
            self._bucket_sigs = []

        def batch_grads(params, x_mu, y_mu):
            """The ONE encoding of the per-device gradient computation
            on (n_mu, mubs, d) microbatch stacks: grad-accumulating
            scan over microbatches (`layers.py:135-136` semantics),
            then the DP reduction — per-leaf bulk psums after the scan
            (the oracle), or, with `overlap`, bucket psums interleaved
            into the peeled last microbatch's layer-by-layer backward
            (`pipe.py:302-327` equivalent). Shared by the plain and
            health-instrumented steps so the two can never train
            differently."""

            def mu_body(acc, xy):
                x, y = xy
                _, stash = stage_ref.forward(params, x)
                _, grads = stage_ref.backward(params, stash, y)
                return accumulate_grads(acc, grads), None

            # the zero init is axis-invariant but the accumulated grads vary
            # per dp shard — cast the carry to varying for shard_map's typing
            acc0 = _pvary(zero_grads_like(params), ("dp",))
            if ov_plan is None:
                acc, _ = jax.lax.scan(mu_body, acc0, (x_mu, y_mu))
                return tree_map(lambda g: jax.lax.psum(g, "dp"), acc)
            from shallowspeed_tpu.parallel.overlap import (
                bucketed_stage_backward)

            # peel the last microbatch: the first n_mu-1 accumulate in
            # the scan (unreduced); the peeled backward finalizes each
            # leaf's total and psums each bucket as soon as its leaves
            # are final — interleaved with the remaining backward
            acc, _ = jax.lax.scan(mu_body, acc0,
                                  (x_mu[:-1], y_mu[:-1]))
            _, stash = stage_ref.forward(params, x_mu[-1])
            return bucketed_stage_backward(
                stage_ref, params, stash, y_mu[-1], acc, ov_plan,
                ("dp",))

        def local_step(params, opt_state, x_mu, y_mu):
            """batch_grads + optimizer update (the _epoch/_run body)."""
            return opt_ref.step(params, batch_grads(params, x_mu, y_mu),
                                opt_state)

        health_mode = health

        def step_with_health(params, opt_state, x_mu, y_mu):
            """local_step + the fused health pack (telemetry/health.py):
            grads after the dp psum are replicated, so the pack needs no
            further reductions; under "guard" the update is gated on the
            nonfinite sentinel (optim.guarded_step — a skipped step is
            bit-identical to never having run)."""
            from shallowspeed_tpu.telemetry.health import (grad_health,
                                                           update_health)

            total = batch_grads(params, x_mu, y_mu)
            pack = grad_health(params, total)
            if health_mode == "guard":
                ok = pack["nonfinite"] == 0
                new_p, new_s = opt_ref.guarded_step(params, total,
                                                    opt_state, ok)
                pack = update_health(pack, params, new_p,
                                     skipped=1 - ok)
            else:
                new_p, new_s = opt_ref.step(params, total, opt_state)
                pack = update_health(pack, params, new_p)
            return new_p, new_s, pack

        step_out = ((P(), P()) if health == "off" else (P(), P(), P()))

        @partial(jax.jit, donate_argnums=(0, 1))
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P(), P("dp"), P("dp")),
                 out_specs=step_out)
        def _step(params, opt_state, xs, ys):
            if health_mode == "off":
                return local_step(params, opt_state, xs[0], ys[0])
            return step_with_health(params, opt_state, xs[0], ys[0])

        @partial(jax.jit)
        @partial(shard_map, mesh=mesh, in_specs=(P(), P("dp")),
                 out_specs=P("dp"))
        def _infer(params, x):
            return stage_ref.infer(params, x)

        def _make_run(n_epochs: int):
            @partial(jax.jit, donate_argnums=(0, 1))
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P(), P(None, "dp"), P(None, "dp")),
                     out_specs=(P(), P()))
            def _run(params, opt_state, xs, ys):
                # xs: (n_batches, dp, n_mu, mubs, d) — whole run device-
                # resident, ONE XLA dispatch: scan over epochs of (scan over
                # batches of (scan over microbatches)). HBM-residency and
                # fused dispatch are the TPU answer to the reference's
                # per-microbatch host loads (`dataset.py:66-80`).
                def batch_body(carry, xy):
                    p, o = carry
                    x, y = xy
                    return local_step(p, o, x[0], y[0]), None

                def epoch_body(carry, _):
                    carry, _ = jax.lax.scan(batch_body, carry, (xs, ys))
                    return carry, None

                (params, opt_state), _ = jax.lax.scan(
                    epoch_body, (params, opt_state), None, length=n_epochs)
                return params, opt_state

            if overlap is not None:
                from shallowspeed_tpu.parallel import overlap as OV

                OV.register_program(_run, "dp", self._bucket_sigs,
                                    engine="FusedDPEngine")
            return _run

        self._step = _step
        self._infer = _infer
        self._make_run = _make_run
        self._run_cache: dict[int, Any] = {}
        if overlap is not None:
            from shallowspeed_tpu.parallel import overlap as OV

            OV.register_program(_step, "dp", self._bucket_sigs,
                                engine="FusedDPEngine")

    # ------------------------------------------------------------- steps

    def train_batch(self, batch_id: int, datasets):
        """datasets: dp per-rank Dataset shards; assembles the
        (dp, n_mu, mubs, d) stacks and runs the fused step."""
        from shallowspeed_tpu.telemetry import tracer

        stacks = [ds.load_mubatch_stack(batch_id) for ds in datasets]
        xs = np.stack([s[0] for s in stacks])
        ys = np.stack([s[1] for s in stacks])
        with tracer().span("step", batch=batch_id) as sp:
            xs = jax.device_put(xs, self.shard4)
            ys = jax.device_put(ys, self.shard4)
            if self._telemetry_eps is None and tracer().level != "off":
                self._record_entrypoints(xs, ys)
            out = self._step(self.params, self.opt_state, xs, ys)
            self.params, self.opt_state = out[0], out[1]
            if self.health != "off":
                _note_step(self, out[2])
            sp.fence(self.params[0]["b"])

    def infer(self, x: np.ndarray) -> jax.Array:
        """Forward on a (rows, 784) batch sharded over dp (rows % dp == 0)."""
        x = jax.device_put(x, NamedSharding(self.mesh, P("dp")))
        return self._infer(self.params, x)

    # ------------------------------------------------------ epoch staging

    def stage_epoch(self, datasets, n_batches: int | None = None):
        """Device-put the whole epoch once: returns (xs, ys) of shape
        (n_batches, dp, n_mu, mubs, d), sharded over 'dp' on axis 1."""
        from shallowspeed_tpu.data.dataset import stack_epoch

        xs, ys = stack_epoch(datasets, n_batches)
        shard = NamedSharding(self.mesh, P(None, "dp"))
        return jax.device_put(xs, shard), jax.device_put(ys, shard)

    def train_epoch(self, staged):
        """One dispatch for a full epoch over pre-staged device data."""
        self.train_run(staged, 1)

    def train_run(self, staged, n_epochs: int):
        """One dispatch for a full n_epochs training run over pre-staged
        device data (same epoch data each epoch, as the reference has no
        shuffling — `dataset.py:66-80` indexes deterministically)."""
        from shallowspeed_tpu.telemetry import tracer

        xs, ys = staged
        run = self._run_cache.get(n_epochs)
        if run is None:
            run = self._run_cache[n_epochs] = self._make_run(n_epochs)
        with tracer().span("run", n_epochs=n_epochs) as sp:
            self.params, self.opt_state = run(self.params,
                                              self.opt_state, xs, ys)
            sp.fence(self.params[0]["b"])

    # ----------------------------------------------- telemetry surface

    _telemetry_eps = None

    def _record_entrypoints(self, xs, ys):
        from shallowspeed_tpu.telemetry.report import (
            record_engine_entrypoints)

        self._telemetry_eps = record_engine_entrypoints(
            self, xs, ys, step_arg=False)

    def telemetry_entrypoints(self) -> list:
        """(name, fn, SDS args) for telemetry's static accounting
        (report.py); empty before the first traced `train_batch`."""
        return list(self._telemetry_eps or ())

    def health_snapshot(self) -> dict | None:
        """The last train_batch's health pack as a host dict (one
        device_get); None before the first step or with health='off'.
        The fused train_epoch/train_run paths do not carry the pack —
        drivers step per-batch when health is on."""
        from shallowspeed_tpu.telemetry.health import engine_snapshot

        return engine_snapshot(self)

    # -------------------------------------------------- checkpoint interface

    # the pp=1 layout IS canonical, so moments interchange as-is
    canonical_opt_identity = True

    def get_canonical_params(self):
        """pp=1 params ARE the canonical flat layer list; host conversion
        happens once in checkpoint.save_pytree."""
        return self.params

    def set_canonical_params(self, layers):
        self.params = jax.device_put(
            [{k: np.asarray(v) for k, v in layer.items()} for layer in layers],
            self.rep)

    def set_opt_state(self, state):
        self.opt_state = jax.device_put(state, self.rep)
