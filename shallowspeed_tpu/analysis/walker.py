"""Generic jaxpr walker — the traversal every rule shares.

`iter_eqns` yields every equation of a (closed) jaxpr depth-first,
recursing through EVERY higher-order primitive's sub-jaxprs — pjit,
shard_map, scan, while, cond (all branches), remat2/checkpoint,
custom_vjp/jvp calls — without a per-primitive table: any eqn param that
IS (or contains) a Jaxpr/ClosedJaxpr is a sub-jaxpr. Each yield carries

- the equation,
- its provenance path (the chain of enclosing primitive names), and
- the axis environment: mesh axis name -> size for every axis bound by
  an enclosing `shard_map` (read off the eqn's `mesh` param), which is
  what the collective rule checks psum/ppermute axes against.

Also home to the byte accounting (`aval_bytes`) and the static
live-buffer high-water estimator (`peak_bytes`) the memory rule uses.
"""

from __future__ import annotations

from typing import Iterator

import jax
import numpy as np

ClosedJaxpr = jax.core.ClosedJaxpr
Jaxpr = jax.core.Jaxpr


def _as_jaxpr(obj):
    """The plain Jaxpr inside `obj` if it is one (closed or not)."""
    if isinstance(obj, ClosedJaxpr):
        return obj.jaxpr
    if isinstance(obj, Jaxpr):
        return obj
    return None


def sub_jaxprs(eqn) -> list:
    """Every sub-jaxpr in this equation's params (cond's `branches`
    tuple, scan/pjit/shard_map's `jaxpr`, while's cond/body, ...)."""
    out = []
    for v in eqn.params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        for item in items:
            j = _as_jaxpr(item)
            if j is not None:
                out.append(j)
    return out


def _bound_axes(eqn, axis_env: dict) -> dict:
    """The axis environment a `shard_map` eqn's body executes under."""
    mesh = eqn.params.get("mesh")
    if mesh is None:
        return axis_env
    new = dict(axis_env)
    auto = eqn.params.get("auto", frozenset()) or frozenset()
    for name in mesh.axis_names:  # Mesh.shape: OrderedDict name -> size
        if name not in auto:
            new[name] = int(mesh.shape[name])
    return new


def iter_eqns(jaxpr, path: tuple = (),
              axis_env: dict | None = None) -> Iterator[tuple]:
    """Yield (eqn, path, axis_env) for every equation, depth-first.
    `axis_env` maps bound mesh-axis names to sizes at that eqn."""
    j = _as_jaxpr(jaxpr)
    assert j is not None, f"not a jaxpr: {type(jaxpr)}"
    env = dict(axis_env or {})
    for eqn in j.eqns:
        yield eqn, path, env
        child_env = (_bound_axes(eqn, env)
                     if eqn.primitive.name == "shard_map" else env)
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub, path + (eqn.primitive.name,),
                                 child_env)


# ------------------------------------------------------------------ bytes


def dot_flops(eqn) -> int:
    """Matmul FLOPs of one `dot_general` equation (2*batch*M*N*K from
    its dimension numbers; 0 for every other primitive). The telemetry
    attribution layer prices these at the MXU peak and everything else
    at the HBM roofline — the same per-op walk the lint rules ride."""
    if eqn.primitive.name != "dot_general":
        return 0
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    batch = int(np.prod([lhs[i] for i in lb], dtype=np.int64))
    k = int(np.prod([lhs[i] for i in lc], dtype=np.int64))
    m = int(np.prod([d for i, d in enumerate(lhs)
                     if i not in lc and i not in lb], dtype=np.int64))
    n = int(np.prod([d for i, d in enumerate(rhs)
                     if i not in rc and i not in rb], dtype=np.int64))
    return 2 * batch * m * n * k


def eqn_bytes(eqn) -> int:
    """HBM traffic upper bound of one leaf equation: operand + output
    bytes (what an unfused execution would move — real fused time is
    lower, so pricing this at the HBM roofline over-explains, never
    under-explains, a measured step)."""
    ins = sum(aval_bytes(v.aval) for v in eqn.invars
              if not isinstance(v, jax.core.Literal))
    outs = sum(aval_bytes(v.aval) for v in eqn.outvars)
    return ins + outs


def aval_bytes(aval) -> int:
    """On-device bytes of one abstract value (0 for non-array avals)."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        item = np.dtype(dtype).itemsize
    except TypeError:
        # jax extended dtypes (typed PRNG keys, `key<fry>`) are not
        # numpy dtypes but expose their physical payload size — the
        # serving decode tick's per-row samplers put them in scope
        item = int(getattr(dtype, "itemsize", 0) or 0)
    return int(np.prod(shape, dtype=np.int64)) * item


def _inner_extra(eqn) -> int | None:
    """EXTRA transient bytes an eqn's sub-jaxprs allocate beyond the
    operands the caller already holds live (max over branches — only
    one cond branch runs; scan iterations reuse one body's
    transients). Subtracting the sub-jaxpr's own inputs is what keeps
    nesting from re-counting the same buffers at every level (pjit ->
    shard_map -> scan would otherwise multiply params+opt_state by the
    nesting depth). None when the eqn has no sub-jaxprs."""
    subs = sub_jaxprs(eqn)
    if not subs:
        return None
    extra = 0
    for s in subs:
        j = _as_jaxpr(s)
        inputs = sum(aval_bytes(v.aval)
                     for v in (*j.invars, *j.constvars))
        extra = max(extra, peak_bytes(s) - inputs)
    return max(extra, 0)


def peak_bytes(jaxpr) -> int:
    """Static live-buffer high-water estimate for one jaxpr, in bytes.

    Liveness walk in program order: a var becomes live when defined
    (inputs/consts at entry) and dies after its last textual use; each
    eqn's transient peak is the live set plus its outputs plus the
    deepest sub-jaxpr's own peak. This is an ESTIMATE of what XLA's
    buffer assignment must accommodate, not a simulation of it — no
    fusion, rematerialization, or aliasing — so it upper-bounds
    same-shape executions and is stable across compiler versions, which
    is exactly what a budget gate wants. Donated-input reuse is likewise
    ignored (conservative)."""
    j = _as_jaxpr(jaxpr)
    last_use: dict = {}
    for i, eqn in enumerate(j.eqns):
        for v in eqn.invars:
            if not isinstance(v, jax.core.Literal):
                last_use[v] = i
    for v in j.outvars:
        if not isinstance(v, jax.core.Literal):
            last_use[v] = len(j.eqns)

    live = sum(aval_bytes(v.aval) for v in (*j.invars, *j.constvars))
    peak = live
    for i, eqn in enumerate(j.eqns):
        out_b = sum(aval_bytes(v.aval) for v in eqn.outvars)
        extra = _inner_extra(eqn)
        if extra is None:  # leaf eqn: just its outputs
            peak = max(peak, live + out_b)
        elif eqn.primitive.name in ("scan", "while"):
            # stacked/loop outputs accumulate ACROSS iterations while
            # one iteration's body transients are live — additive
            peak = max(peak, live + out_b + extra)
        else:
            # call-like (pjit/shard_map/cond/remat): the call's outputs
            # materialize INSIDE the sub-jaxpr, already in its peak
            peak = max(peak, live + max(out_b, extra))
        live += out_b
        # a var dies at its last textual use; outvars never read again
        # (incl. DropVars) die immediately — default their last use to i
        for v in set(v for v in (*eqn.invars, *eqn.outvars)
                     if not isinstance(v, jax.core.Literal)):
            if last_use.get(v, i) == i:
                live -= aval_bytes(v.aval)
    return peak
