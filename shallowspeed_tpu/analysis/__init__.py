"""Static TPU-cleanliness analysis of the compiled train steps.

`parallel/verify.py` proves SCHEDULE-level invariants by simulation
(deadlock-freedom, FIFO channels, stash bounds); this package is the
same discipline one layer down, at what XLA is actually handed: a jaxpr
walker over the real train-step closures plus a rule registry that
statically proves each compiled step is TPU-clean —

- no f32 leaks on declared-bf16 compute paths (``dtype-promotion``),
- params/opt-state buffers donated by every step (``donation``),
- every collective's axes bound by its constructing mesh, pipeline
  ppermutes a single ring cycle (``collective``),
- one executable per entrypoint for the test suite's shape set
  (``retrace``),
- static live-buffer high-water inside the HBM budget
  (``memory-highwater``),

and — via the precision-flow prover (`provenance.flow_entrypoint`, an
abstract interpretation of the jaxpr tracking per-value storage dtype,
rounding history, quantization-scale identity, and absmax intervals) —

- no value rounded twice without an intervening rescale
  (``fp8-double-rounding``),
- every dot_general and scan-carried accumulator provably accumulates
  at the widest participating dtype (``accumulation-dtype``),
- no gradient-sized cross-replica reduction at bf16/fp8
  (``reduction-precision``),
- every quantized tensor consumed together with its scale, exactly
  once, applied on the accumulator — including the transpose/VJP side
  (``scale-consistency``),
- interval propagation proves exp/log/softmax/rsqrt inputs and
  narrowing converts in range (``range-safety``).

Intentional deviations are suppressed INLINE at the code that causes
them (`findings.suppress`, mandatory reason string), so the analyzer's
report doubles as documentation of every deliberate exception — and
`analyze` audits the registry each run: a suppression that no longer
matches any finding becomes a MEDIUM ``stale-suppression`` finding so
dead registrations cannot linger and swallow future regressions.

Usage:
    python -m shallowspeed_tpu.analysis --target all        # CLI gate
    from shallowspeed_tpu import analysis
    findings = analysis.analyze("pipeline_lm:1f1b")

The tier-1 test `tests/test_analysis.py` pins the shipped train steps
to ZERO unsuppressed high-severity findings.
"""

from __future__ import annotations

# findings is deliberately stdlib-only and imported EAGERLY: engine/ops
# modules register inline suppressions at import time, and importing a
# submodule executes this package __init__ first — everything jax-heavy
# below stays behind the PEP 562 lazy hook so those modules' import
# cost (and backend-initialization hygiene, see ops/attention.py) is
# unchanged.
from shallowspeed_tpu.analysis.findings import (Finding, Severity,  # noqa: F401
                                                apply_suppressions,
                                                gate_count,
                                                stale_suppressions,
                                                suppress)

_EXPORTS = {
    "RULES": "shallowspeed_tpu.analysis.rules",
    "rule": "shallowspeed_tpu.analysis.rules",
    "run_rules": "shallowspeed_tpu.analysis.rules",
    "TARGET_BUILDERS": "shallowspeed_tpu.analysis.targets",
    "TARGET_GROUPS": "shallowspeed_tpu.analysis.targets",
    "EntryPoint": "shallowspeed_tpu.analysis.targets",
    "TargetProbe": "shallowspeed_tpu.analysis.targets",
    "resolve_targets": "shallowspeed_tpu.analysis.targets",
    "aval_bytes": "shallowspeed_tpu.analysis.walker",
    "iter_eqns": "shallowspeed_tpu.analysis.walker",
    "peak_bytes": "shallowspeed_tpu.analysis.walker",
    "FlowResult": "shallowspeed_tpu.analysis.provenance",
    "flow_entrypoint": "shallowspeed_tpu.analysis.provenance",
}

__all__ = sorted((
    "Finding", "Severity", "suppress", "apply_suppressions",
    "gate_count", "stale_suppressions", "analyze", *_EXPORTS))


def __getattr__(name):  # PEP 562 lazy re-exports (jax-heavy modules)
    target = _EXPORTS.get(name)
    if target is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    import importlib

    value = getattr(importlib.import_module(target), name)
    globals()[name] = value
    return value


def __dir__():
    return __all__


def analyze(target: str = "all", budget: int | None = None,
            only: tuple = (), audit: bool = True) -> dict:
    """Build and lint `target` (a probe name or group alias). Returns
    {probe name: [Finding, ...]}; `gate_count` over the concatenation
    is the CI gate. With `audit` (the default), registered suppressions
    that matched nothing in this run are reported as MEDIUM
    ``stale-suppression`` findings on the probe their glob matches —
    only on a FULL sweep with the full rule set (a suppression can't be
    proven stale when the probe or rule it covers didn't run)."""
    from shallowspeed_tpu.analysis.rules import RULES, run_rules
    from shallowspeed_tpu.analysis.targets import (DEFAULT_BUDGET,
                                                   TARGET_BUILDERS,
                                                   resolve_targets)

    out = {}
    for name in resolve_targets(target):
        probe = TARGET_BUILDERS[name](budget=budget or DEFAULT_BUDGET)
        out[probe.name] = run_rules(probe, only=only)
    if audit and not only and set(out) >= set(TARGET_BUILDERS):
        for f in stale_suppressions(out, ran_rules=tuple(RULES)):
            out[f.target].append(f)
    return out
