"""The rule registry and the shipped lint rules.

Each rule is a pure function `(probe) -> list[Finding]` over a
`TargetProbe` (`targets.py`): the probe holds the real train-step
entrypoints, their traced jaxprs, the constructing mesh, and the
declared compute dtype. Rules never execute device code except where
the check IS behavioral (the retrace audit reads compilation-cache
sizes after the probe exercised each entrypoint with the test suite's
shape/dtype set). The precision rules additionally share ONE
abstract-interpretation pass per entrypoint (`provenance.py`, cached
by `TargetProbe.flow`) carrying per-value dtype/rounding/scale/range
provenance.

Shipped rules:

- ``dtype-promotion``  f32 leaking onto declared-bf16 compute paths:
  matmuls with mixed bf16/f32 operands (weak-type promotion) or fed by
  an explicit bf16->f32 upcast, and round-trip convert chains.
- ``donation``         step-like entrypoints whose params/opt-state
  buffers are not donated (an extra HBM copy of the model per step).
- ``collective``       psum/ppermute/all_gather/... axis names checked
  against the axes bound by the enclosing shard_map's mesh (and that
  mesh against the probe's); ppermute permutations must be valid and —
  on the 'pp' pipeline axis — a single cycle, the shape every schedule
  here is built on.
- ``retrace``          >1 compilation per entrypoint after the probe
  ran the test-suite shape/dtype set through it (retrace storms).
- ``memory-highwater`` static live-buffer byte estimate per entrypoint
  jaxpr vs the probe's HBM budget.
- ``overlap-bucket``   registered-overlap programs: every grad-sized
  dp reduction is a planned bucket with compute in its scope.
- ``dequant-fusion``   quantized weights dequantize INTO the matmul,
  never into a materialized full-size buffer.
- ``fp8-double-rounding`` / ``accumulation-dtype`` /
  ``reduction-precision`` / ``scale-consistency`` / ``range-safety``
  — the precision-flow prover (see each rule's docstring): statically
  certifies the quantized training step's numerics.
"""

from __future__ import annotations

from typing import Callable

import jax
import numpy as np

from shallowspeed_tpu.analysis.findings import (Finding, Severity,
                                                apply_suppressions)
from shallowspeed_tpu.analysis.walker import aval_bytes, peak_bytes

RULES: dict[str, Callable] = {}

# collectives whose eqn params name mesh axes, with the param key
_COLLECTIVES = {
    "psum": "axes", "pmin": "axes", "pmax": "axes",
    "ppermute": "axis_name", "pbroadcast": "axis_name",
    "all_gather": "axis_name", "reduce_scatter": "axis_name",
    "psum_scatter": "axis_name", "all_to_all": "axis_name",
    "axis_index": "axis_name", "pgather": "axes",
}


def rule(name: str):
    def register(fn):
        RULES[name] = fn
        fn.rule_name = name
        return fn
    return register


def run_rules(probe, only: tuple = ()) -> list:
    """All findings for one probe: identical findings deduplicated with
    a count (a rule firing on 4 layers x 4 matmuls is ONE fact),
    suppressions applied, HIGH first."""
    findings: list[Finding] = []
    for name, fn in RULES.items():
        if only and name not in only:
            continue
        findings.extend(fn(probe))
    grouped: dict[tuple, Finding] = {}
    counts: dict[tuple, int] = {}
    for f in findings:
        key = (f.rule, f.severity, f.target, f.site, f.path, f.message)
        counts[key] = counts.get(key, 0) + 1
        grouped.setdefault(key, f)
    deduped = []
    for key, f in grouped.items():
        if counts[key] > 1:
            f.message += f" (x{counts[key]})"
        deduped.append(f)
    apply_suppressions(deduped)
    deduped.sort(key=lambda f: (-int(f.severity), f.rule, f.site))
    return deduped


def _axis_names(axes) -> tuple:
    """Normalize an eqn's axis param to a tuple of names (drops
    positional ints, which cannot mismatch a mesh)."""
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


# ------------------------------------------------------- dtype promotion


def _f32_origin(var, made_by, bf16, f32, budget: int = 128) -> str:
    """Classify where a mixed-matmul's f32 operand comes from, walking
    its producer chain within the scope:

    - "accum": the chain roots in a dot_general with bf16 input(s) —
      a deliberate `preferred_element_type=f32` accumulation (or its
      transpose); f32 here is the documented score-path numerics.
    - "cast": the chain crosses a bf16->f32 convert — the data WAS
      bf16; in a backward jaxpr this is the transpose of an intended
      downcast (cotangents of `.astype(bf16)` arrive f32). Pays f32
      rate for this matmul but is structurally forced by the primal's
      cast placement.
    - "local": the chain resolves fully in-scope with NO bf16 origin
      anywhere (f32 constants / scalars) — a genuine weak-type
      promotion: bf16 data was meant to flow here and never did.
    - "unknown": the chain leaves the scope (scan carries, stashed
      residuals) or exceeds the walk budget.
    """
    seen: set = set()
    frontier = [var]
    fully_resolved = True
    has_accum = has_cast = False
    while frontier and budget > 0:
        v = frontier.pop()
        if id(v) in seen:
            continue
        seen.add(id(v))
        eqn = made_by.get(v)
        if eqn is None:  # scope input / const — producer invisible
            fully_resolved = False
            continue
        budget -= 1
        name = eqn.primitive.name
        if name == "dot_general":
            in_dts = {np.dtype(iv.aval.dtype) for iv in eqn.invars[:2]
                      if hasattr(iv.aval, "dtype")}
            if bf16 in in_dts:
                has_accum = True
                continue
        if (name == "convert_element_type"
                and getattr(eqn.invars[0].aval, "dtype", None) is not None
                and np.dtype(eqn.invars[0].aval.dtype) == bf16):
            has_cast = True
            continue
        for iv in eqn.invars:
            if (not isinstance(iv, jax.core.Literal)
                    and getattr(iv.aval, "dtype", None) is not None
                    and np.dtype(iv.aval.dtype) == f32):
                frontier.append(iv)
    if has_accum:
        return "accum"
    if has_cast:
        return "cast"
    if budget <= 0 or frontier or not fully_resolved:
        return "unknown"
    return "local"


@rule("dtype-promotion")
def dtype_promotion(probe) -> list:
    """f32 on a declared-bf16 compute path. Three shapes:

    - a dot_general with MIXED float operand dtypes — jax promoted one
      side (classic weak-type accident); HIGH.
    - a dot_general whose f32 operand is directly the output of a
      bf16->f32 `convert_element_type` — the matmul was meant to run on
      the MXU in bf16 and someone upcast its input; HIGH. (bf16-in,
      f32-accumulate matmuls — `preferred_element_type` — are the
      CORRECT pattern and never flagged.)
    - convert round trips a->b->a (any target): dead casts that cost a
      pass over the array each way; MEDIUM.
    """
    out = []
    bf16 = np.dtype(jax.numpy.bfloat16)
    f32 = np.dtype(np.float32)
    declared = (np.dtype(probe.compute_dtype)
                if probe.compute_dtype is not None else None)

    def dt(v):
        d = getattr(v.aval, "dtype", None)
        return None if d is None else np.dtype(d)

    for ep in probe.entrypoints:
        for jaxpr, path in probe.jaxpr_scopes(ep):
            made_by = {}
            for eqn in jaxpr.eqns:
                for v in eqn.outvars:
                    made_by[v] = eqn
                name = eqn.primitive.name
                if name == "convert_element_type":
                    src = eqn.invars[0]
                    prev = made_by.get(src)
                    if (prev is not None
                            and prev.primitive.name
                            == "convert_element_type"
                            and dt(prev.invars[0]) == dt(eqn.outvars[0])
                            and dt(src) != dt(eqn.outvars[0])):
                        # rank in the message anchors suppressions to a
                        # value CLASS (rank-1 norm scales vs rank-5
                        # attention probabilities), so suppressing one
                        # cannot mask regressions of the other
                        rank = len(getattr(eqn.outvars[0].aval,
                                           "shape", ()))
                        out.append(Finding(
                            "dtype-promotion", Severity.MEDIUM,
                            probe.name, ep.name, path,
                            f"round-trip convert chain "
                            f"{dt(prev.invars[0])}->{dt(src)}->"
                            f"{dt(eqn.outvars[0])} on a rank-{rank} "
                            f"intermediate — two dead passes over the "
                            f"array"))
                if name != "dot_general" or declared != bf16:
                    continue
                lhs, rhs = eqn.invars[:2]
                dts = {dt(lhs), dt(rhs)}
                if dts == {bf16, f32}:
                    opnd = lhs if dt(lhs) == f32 else rhs
                    origin = _f32_origin(opnd, made_by, bf16, f32)
                    if origin in ("accum", "cast"):
                        # the f32 side is (the transpose of) a matmul
                        # that deliberately accumulates in f32
                        # (`preferred_element_type`), or of an intended
                        # downcast (`.astype(bf16)`) whose cotangent is
                        # structurally f32 — the score path's documented
                        # numerics; not an accident
                        out.append(Finding(
                            "dtype-promotion", Severity.LOW, probe.name,
                            ep.name, path,
                            f"mixed bf16/f32 dot_general on the f32 "
                            f"accumulation path ({origin}: score-path "
                            f"numerics / cast transpose) — intended, "
                            f"costs f32-rate MXU for this matmul"))
                    else:
                        sev = (Severity.MEDIUM if origin == "unknown"
                               else Severity.HIGH)
                        out.append(Finding(
                            "dtype-promotion", sev, probe.name,
                            ep.name, path,
                            "dot_general with mixed bf16/f32 operands "
                            "on a declared-bf16 path — weak-type "
                            "promotion runs this matmul in f32 (half "
                            "MXU rate, 2x operand bytes)"
                            + (" [f32 operand's producer is outside "
                               "this scope]" if origin == "unknown"
                               else "")))
                    continue
                if dts == {f32}:
                    for opnd in (lhs, rhs):
                        src = made_by.get(opnd)
                        if (src is not None
                                and src.primitive.name
                                == "convert_element_type"
                                and dt(src.invars[0]) == bf16):
                            out.append(Finding(
                                "dtype-promotion", Severity.HIGH,
                                probe.name, ep.name, path,
                                "f32 dot_general fed by a bf16->f32 "
                                "upcast on a declared-bf16 path — the "
                                "matmul should take bf16 operands "
                                "(accumulate in f32 via "
                                "preferred_element_type instead)"))
                            break
    return out


# --------------------------------------------------------------- donation


@rule("donation")
def donation(probe) -> list:
    """Step-like entrypoints must donate their params/opt-state args:
    without `donate_argnums` XLA keeps input AND output copies of the
    model live across the step — an extra params+moments of HBM that
    the biggest configs cannot spare."""
    out = []
    for ep in probe.entrypoints:
        if not ep.donate:
            continue
        pjit_eqn = probe.top_pjit(ep)
        if pjit_eqn is None:
            out.append(Finding(
                "donation", Severity.HIGH, probe.name, ep.name,
                (), "step-like entrypoint is not jitted — every call "
                    "pays Python dispatch and nothing can be donated"))
            continue
        donated = pjit_eqn.params.get("donated_invars", ())
        # flat invars are the flattened args in order; map each arg
        # index to its leaf range
        sizes = [len(jax.tree_util.tree_leaves(a)) for a in ep.args]
        starts = np.cumsum([0] + sizes)
        n_flat = len(donated)
        for argi in ep.donate:
            lo, hi = int(starts[argi]), int(starts[argi + 1])
            if hi > n_flat or not all(donated[lo:hi]):
                missing = ([] if hi > n_flat else
                           [i for i in range(lo, hi) if not donated[i]])
                out.append(Finding(
                    "donation", Severity.HIGH, probe.name, ep.name,
                    ("pjit",),
                    f"argument {argi} ({ep.arg_names[argi]}) is not "
                    f"donated ({len(missing) or hi - lo} of "
                    f"{hi - lo} leaves un-aliased) — the step keeps a "
                    f"second copy of those buffers live in HBM"))
    return out


# ------------------------------------------------------------- collective


def _cycle_count(perm) -> int:
    """Number of cycles in a permutation given as (src, dst) pairs."""
    nxt = {int(s): int(d) for s, d in perm}
    seen, cycles = set(), 0
    for start in nxt:
        if start in seen:
            continue
        cycles += 1
        cur = start
        while cur not in seen:
            seen.add(cur)
            cur = nxt.get(cur, cur)
    return cycles


@rule("collective")
def collective(probe) -> list:
    """Mesh-axis hygiene for every collective eqn: axis names must be
    bound by an enclosing shard_map whose mesh matches the probe's; a
    ppermute's permutation must be a bijection over in-range sources/
    destinations, and on the pipeline ('pp') axis a SINGLE cycle —
    stage hops here are rings, and a multi-cycle permutation would
    partition the stages into disconnected sub-pipelines."""
    out = []
    probe_axes = set(probe.mesh.axis_names) if probe.mesh else set()
    for ep in probe.entrypoints:
        for eqn, path, env in probe.walk(ep):
            name = eqn.primitive.name
            if name == "shard_map" and probe.mesh is not None:
                mesh = eqn.params.get("mesh")
                if mesh is not None and not set(
                        mesh.axis_names) <= probe_axes:
                    out.append(Finding(
                        "collective", Severity.HIGH, probe.name,
                        ep.name, path,
                        f"shard_map over mesh axes "
                        f"{tuple(mesh.axis_names)} inside a program "
                        f"whose constructing mesh has "
                        f"{tuple(probe.mesh.axis_names)}"))
                continue
            key = _COLLECTIVES.get(name)
            if key is None:
                continue
            axes = _axis_names(eqn.params.get(key))
            unbound = [a for a in axes if a not in env]
            if unbound:
                out.append(Finding(
                    "collective", Severity.HIGH, probe.name, ep.name,
                    path,
                    f"{name} over axis {unbound} not bound by any "
                    f"enclosing shard_map (bound: "
                    f"{sorted(env) or 'none'})"))
                continue
            if name != "ppermute":
                continue
            perm = tuple(eqn.params.get("perm", ()))
            ax = axes[0] if axes else None
            size = env.get(ax)
            srcs = [int(s) for s, _ in perm]
            dsts = [int(d) for _, d in perm]
            if (len(set(srcs)) != len(srcs)
                    or len(set(dsts)) != len(dsts)
                    or (size is not None and any(
                        not (0 <= x < size) for x in srcs + dsts))):
                out.append(Finding(
                    "collective", Severity.HIGH, probe.name, ep.name,
                    path,
                    f"ppermute over '{ax}' (size {size}) with an "
                    f"invalid permutation {perm}: duplicate or "
                    f"out-of-range sources/destinations"))
                continue
            if ax == "pp" and perm and (
                    len(perm) != size or _cycle_count(perm) != 1):
                out.append(Finding(
                    "collective", Severity.HIGH, probe.name, ep.name,
                    path,
                    f"ppermute over 'pp' is not a single "
                    f"{size}-cycle ({perm}): pipeline stage hops "
                    f"must form one ring, or stages de-sync into "
                    f"disconnected sub-pipelines"))
    return out


# ---------------------------------------------------------------- retrace


@rule("retrace")
def retrace(probe) -> list:
    """>1 compilation per entrypoint after the probe exercised it with
    the shape/dtype set the test suite uses. Every extra executable is
    seconds of XLA compile time and a sign the cache key is unstable
    (python scalars re-traced as weak types, shifting shapes, ...)."""
    out = []
    for ep in probe.entrypoints:
        # read the snapshot TargetProbe.seal() took right after the
        # exercise calls — not the live cache, which later rules'
        # make_jaxpr tracing could perturb on some jax versions
        n = ep.observed_compiles
        if n is None or ep.calls == 0:
            continue
        if n > ep.n_compiles_expected:
            out.append(Finding(
                "retrace", Severity.HIGH, probe.name, ep.name, (),
                f"{n} compilations after {ep.calls} same-shaped calls "
                f"(expected {ep.n_compiles_expected}) — the jit cache "
                f"key is unstable for this entrypoint"))
    return out


# ---------------------------------------------------------- overlap-bucket


@rule("overlap-bucket")
def overlap_bucket(probe) -> list:
    """Comm/compute-interleaving hygiene for programs registered as
    overlapped (`parallel/overlap.register_program`):

    - every grad-sized reduction (`psum`/`psum_scatter`/
      `reduce_scatter`) over the registered data axis must match one of
      the registered bucket signatures — a stray dp psum outside the
      plan means some gradient bypasses the bucketed reduction (HIGH);
    - every registered bucket must actually appear (MEDIUM — the plan
      and the program drifted);
    - the interleaving dataflow must exist: at least one collective on
      the registered axis with independent MXU-heavy compute in its
      scope, which is what XLA's latency-hiding scheduler needs to
      overlap it (HIGH otherwise — the reduction is fully exposed and
      "overlap" is a lie).

    Sub-KiB reductions (health-pack statistics, loss means) are not
    gradient traffic and are exempt. Unregistered programs are skipped
    — the bulk reduction is the documented oracle, not a defect."""
    from collections import Counter

    from shallowspeed_tpu.parallel import overlap as OV

    out = []
    for ep in probe.entrypoints:
        info = OV.registered(ep.fn)
        if info is None:
            continue
        axis = info["axis"]
        expected = Counter(info["buckets"])
        seen: Counter = Counter()
        for eqn, path, env in probe.walk(ep):
            name = eqn.primitive.name
            if name not in OV.REDUCE_PRIMS:
                continue
            if axis not in OV.eqn_axes(eqn):
                continue
            operands = [v for v in eqn.invars
                        if not isinstance(v, jax.core.Literal)]
            nbytes = sum(aval_bytes(v.aval) for v in operands)
            sig = OV.bucket_signature([v.aval for v in operands])
            if seen[sig] < expected[sig]:
                seen[sig] += 1
            elif nbytes < 1024:
                continue  # unmatched scalar statistics (health pack,
                #           loss means), not gradient payload
            else:
                out.append(Finding(
                    "overlap-bucket", Severity.HIGH, probe.name,
                    ep.name, path,
                    f"{name} over '{axis}' ({nbytes} B, "
                    f"{len(operands)} operand(s)) is not a registered "
                    f"reduction bucket — this gradient bypasses the "
                    f"bucketed overlapped reduction"))
        missing = expected - seen
        if missing:
            out.append(Finding(
                "overlap-bucket", Severity.MEDIUM, probe.name, ep.name,
                (),
                f"{sum(missing.values())} registered bucket(s) never "
                f"appeared in the traced program — the bucket plan and "
                f"the compiled reduction drifted"))
        expo = OV.collective_exposure(probe.jaxpr_of(ep), axes=(axis,))
        if expo["n_collectives"] and not expo["n_overlapped"]:
            out.append(Finding(
                "overlap-bucket", Severity.HIGH, probe.name, ep.name,
                (),
                f"no '{axis}' collective in this registered-overlapped "
                f"program has independent compute in its scope — every "
                f"reduction is a dataflow barrier and nothing can "
                f"overlap"))
    return out


# --------------------------------------------------------- dequant fusion

# quantized-storage dtypes the serving decode path reads (int8 weights
# and KV blocks; fp8-e4m3 weights where the build ships it)
_QUANT_DTYPES = {"int8", "uint8", "float8_e4m3fn", "float8_e5m2"}

# shape-preserving primitives a weight buffer may pass through between
# its upcast and its consumer without changing what's materialized
_PASSTHROUGH = {"reshape", "transpose", "broadcast_in_dim", "squeeze",
                "copy"}


def _is_quant(var) -> bool:
    dt = getattr(var.aval, "dtype", None)
    return (dt is not None and str(np.dtype(dt)) in _QUANT_DTYPES
            and len(getattr(var.aval, "shape", ())) >= 2)


@rule("dequant-fusion")
def dequant_fusion(probe) -> list:
    """Quantized weights must dequantize INTO the matmul, never into a
    buffer. The whole point of int8/fp8 weight storage is reading one
    byte per element from HBM; the classic way to lose it is

        (wq.astype(f32) * scale) @ x     # a full (K, N) dequant copy

    where the scale multiply (or any other elementwise op) materializes
    a full-weight-size floating buffer between the upcast and the dot.
    The FUSED form (`ops.matmul.dequant_matmul`) upcasts the values
    directly into the dot operand — XLA folds that convert into the
    operand load — and applies the scale to the f32 ACCUMULATOR.

    Mechanically: for every `convert_element_type` whose input chains
    back (through shape-preserving ops only — a gather breaks the
    chain, so gathered int8 KV *views* are exempt) to an int8/fp8
    buffer of rank >= 2, every consumer of the upcast value must be a
    `dot_general` (possibly through more shape-preserving ops). Any
    elementwise consumer producing a full-weight-size floating output
    is a materialized dequantized copy: HIGH."""
    out = []
    for ep in probe.entrypoints:
        for jaxpr, path in probe.jaxpr_scopes(ep):
            made_by = {}
            consumers: dict = {}
            for eqn in jaxpr.eqns:
                for v in eqn.outvars:
                    made_by[v] = eqn
                for v in eqn.invars:
                    if not isinstance(v, jax.core.Literal):
                        consumers.setdefault(v, []).append(eqn)

            def root_of(var):
                seen = 0
                while seen < 32:
                    eqn = made_by.get(var)
                    if eqn is None \
                            or eqn.primitive.name not in _PASSTHROUGH:
                        return var
                    var = eqn.invars[0]
                    seen += 1
                return var

            def check_uses(var, size, depth=0):
                """Every (transitive, through passthrough) use of the
                upcast buffer must be a dot; return the offending eqn
                otherwise."""
                for use in consumers.get(var, ()):
                    name = use.primitive.name
                    if name == "dot_general":
                        continue
                    if name in _PASSTHROUGH and depth < 8:
                        bad = check_uses(use.outvars[0], size, depth + 1)
                        if bad is not None:
                            return bad
                        continue
                    out_avals = [o.aval for o in use.outvars]
                    # jnp.issubdtype, not np: bf16/fp8 are ml_dtypes
                    # extensions numpy does not class as floating
                    if any(int(np.prod(getattr(a, "shape", ()),
                                       dtype=np.int64)) == size
                           and jax.numpy.issubdtype(
                               getattr(a, "dtype", np.int32),
                               jax.numpy.floating)
                           for a in out_avals):
                        return use
                return None

            for eqn in jaxpr.eqns:
                if eqn.primitive.name != "convert_element_type":
                    continue
                src = root_of(eqn.invars[0])
                if isinstance(src, jax.core.Literal) \
                        or not _is_quant(src):
                    continue
                o = eqn.outvars[0]
                odt = getattr(o.aval, "dtype", None)
                if odt is None or not jax.numpy.issubdtype(
                        odt, jax.numpy.floating):
                    continue
                size = int(np.prod(o.aval.shape, dtype=np.int64))
                if size != int(np.prod(src.aval.shape,
                                       dtype=np.int64)):
                    continue   # the upcast is of a slice, not the weight
                bad = check_uses(o, size)
                if bad is not None:
                    out.append(Finding(
                        "dequant-fusion", Severity.HIGH, probe.name,
                        ep.name, path,
                        f"{str(np.dtype(src.aval.dtype))} weight "
                        f"{tuple(src.aval.shape)} upcast to "
                        f"{np.dtype(odt)} is consumed by "
                        f"'{bad.primitive.name}' at full weight size — "
                        f"a materialized dequantized copy; apply the "
                        f"scale to the f32 accumulator instead "
                        f"(ops.matmul.dequant_matmul)"))
    return out


# --------------------------------------------- precision-flow rules
#
# The five quantized-training rules ride ONE shared abstract-
# interpretation pass (`provenance.py`, cached per entrypoint by
# `TargetProbe.flow`): per-value storage-dtype lineage, rounding
# state, quantization-scale pairing, and calibration-seeded absmax
# intervals. They are the static gate for ROADMAP item 5 — the
# fp8_train probe must come out clean before a long quantized run is
# worth burning.


@rule("fp8-double-rounding")
def fp8_double_rounding(probe) -> list:
    """A value that crossed one narrowing float convert and crosses a
    SECOND — to a strictly narrower format, or back into quantized
    storage — without an intervening rescale (f32->bf16->fp8, or fp8
    re-quantized straight). Stacking roundings of decreasing width
    compounds error beyond the target format's half-ulp and is never
    intended — correct requantization rescales (divides by a fresh
    scale) first, which resets the rounding state. Re-rounding at the
    SAME width (bf16 -> f32 arithmetic -> bf16, the standard mixed-
    precision pattern) is one rounding of a new value and is exempt."""
    out = []
    for ep in probe.entrypoints:
        for ev in probe.flow(ep).events:
            if ev.kind != "double-round":
                continue
            d = ev.data
            out.append(Finding(
                "fp8-double-rounding", Severity.HIGH, probe.name,
                ep.name, ev.path,
                f"value already rounded to {d['first']} is rounded "
                f"again to {d['dst']} (shape {d['shape']}) with no "
                f"intervening rescale — double rounding compounds "
                f"quantization error; rescale (x / s) before the "
                f"second convert"))
    return out


@rule("accumulation-dtype")
def accumulation_dtype(probe) -> list:
    """Every contraction and loop-carried sum must prove widest-type
    accumulation:

    - a dot_general with QUANTIZED-lineage operands (int8/fp8 storage,
      however upcast) must emit f32 (`preferred_element_type`) — the
      whole point of quantized storage is 1-byte reads into a wide
      accumulator, and a narrow output rounds K products away (HIGH);
    - a scan/while carry that is an accumulator (carry + independent
      contribution per iteration) must carry f32 — the peeled-
      microbatch grad sums re-round every add otherwise (HIGH);
    - a plain narrow-float dot with a narrow output is informational
      (LOW): the MXU accumulates f32 internally and rounds once at the
      output, which is the documented activation-path numerics, but
      long contractions feeding accumulators deserve an explicit
      `preferred_element_type=f32`."""
    out = []
    wide = ("float32", "float64")
    for ep in probe.entrypoints:
        flow = probe.flow(ep)
        for ev in flow.events:
            if ev.kind == "carry-accum":
                d = ev.data
                out.append(Finding(
                    "accumulation-dtype", Severity.HIGH, probe.name,
                    ep.name, ev.path,
                    f"{d['prim']}-carried accumulator (shape "
                    f"{d['shape']}) accumulates in {d['dtype']} — "
                    f"every iteration re-rounds the running sum; "
                    f"carry f32 and cast once at the end"))
            if ev.kind != "dot":
                continue
            d = ev.data
            odt = d["out_dtype"]
            if odt in wide or odt is None:
                continue
            floats = [t for t in d["in_dtypes"]
                      if t and (t.startswith("float")
                                or t.startswith("bfloat"))]
            if not floats:
                continue
            if d["quant"]:
                out.append(Finding(
                    "accumulation-dtype", Severity.HIGH, probe.name,
                    ep.name, ev.path,
                    f"dot_general over quantized-storage operands "
                    f"{d['in_dtypes']} emits {odt} (K={d['k']}) — "
                    f"quantized matmuls must accumulate f32 "
                    f"(preferred_element_type) with the scale applied "
                    f"to the accumulator"))
            elif all(t not in wide for t in floats):
                out.append(Finding(
                    "accumulation-dtype", Severity.LOW, probe.name,
                    ep.name, ev.path,
                    f"narrow dot_general {d['in_dtypes']}->{odt}: "
                    f"MXU accumulates f32 internally and rounds once "
                    f"at the output (standard activation numerics); "
                    f"prefer preferred_element_type=f32 where the "
                    f"result feeds an accumulator"))
    return out


# collectives that REDUCE (sum) across devices — the precision-
# sensitive subset of _COLLECTIVES (gather/permute move bits verbatim)
_REDUCE_COLLECTIVES = ("psum", "psum_scatter", "reduce_scatter")


@rule("reduction-precision")
def reduction_precision(probe) -> list:
    """Grad-sized cross-device reductions must run in f32: a bf16/fp8
    `psum` rounds at every hop of the reduction tree, and a gradient
    reduced wrong is unrecoverable after the optimizer step. Operands
    whose chain proves f32 (the repo's grads — cast transposes emit
    f32 cotangents) pass by construction since the operand DTYPE is
    f32. Sub-KiB reductions (health-pack statistics, loss means) are
    exempt, matching the `overlap-bucket` rule's threshold."""
    out = []
    for ep in probe.entrypoints:
        for eqn, path, env in probe.walk(ep):
            name = eqn.primitive.name
            if name not in _REDUCE_COLLECTIVES:
                continue
            for v in eqn.invars:
                if isinstance(v, jax.core.Literal):
                    continue
                dt = getattr(v.aval, "dtype", None)
                if dt is None or not jax.numpy.issubdtype(
                        dt, jax.numpy.floating):
                    continue
                if np.dtype(dt).itemsize >= 4:
                    continue
                nbytes = aval_bytes(v.aval)
                if nbytes < 1024:
                    continue  # scalar statistics, not gradient payload
                key = _COLLECTIVES.get(name)
                axes = _axis_names(eqn.params.get(key)) if key else ()
                out.append(Finding(
                    "reduction-precision", Severity.HIGH, probe.name,
                    ep.name, path,
                    f"{name} over {axes or '?'} reduces a "
                    f"{np.dtype(dt)} operand of {nbytes} B — every "
                    f"hop of the reduction tree re-rounds; upcast the "
                    f"operand to f32 (or prove the chain f32) before "
                    f"grad-sized cross-device sums"))
    return out


@rule("scale-consistency")
def scale_consistency(probe) -> list:
    """Every quantized leaf consumed by a matmul must see its paired
    scale EXACTLY once, applied to the accumulator (or riding the
    cotangent on the transpose/VJP side). A forgotten scale silently
    mis-scales activations or gradients by orders of magnitude; a
    doubled one squares it. Pairing comes from the param layout
    (Wq/Ws dicts) or from in-program quantization (x/s followed by a
    narrowing convert to quantized storage)."""
    out = []
    for ep in probe.entrypoints:
        flow = probe.flow(ep)
        for use in flow.dot_uses:
            if use.resolved:
                continue
            out.append(Finding(
                "scale-consistency", Severity.HIGH, probe.name,
                ep.name, use.path,
                f"quantized leaf {use.label} (shape {use.shape}) is "
                f"consumed by a dot_general but its scale is never "
                f"applied to the result — the output is mis-scaled "
                f"by the quantization factor (forgotten Ws / "
                f"delayed-scaling factor)"))
        for ev in flow.events:
            if ev.kind != "double-scale":
                continue
            out.append(Finding(
                "scale-consistency", Severity.HIGH, probe.name,
                ep.name, ev.path,
                f"quantization scale of {ev.data.get('labels')} is "
                f"applied TWICE on the same value lineage — the "
                f"output is scaled by the square of the factor"))
    return out


@rule("range-safety")
def range_safety(probe) -> list:
    """Interval propagation over the calibration-seeded bounds: fires
    only on PROVABLE dtype-range violations — an exp whose input's
    lower bound already overflows the storage dtype, a narrowing
    convert whose operand provably exceeds the target's max (e.g. f32
    values in [0, 1000] cast to e4m3 with max 448 and no saturating
    clamp), or a log/rsqrt over a provably non-positive range. The
    pass understands the softmax shift (x - max(x) <= 0) and
    saturation clamps, so the standard guarded patterns stay clean."""
    out = []
    for ep in probe.entrypoints:
        for ev in probe.flow(ep).events:
            if ev.kind != "range":
                continue
            d = ev.data
            lo, hi = d["itv"]
            itv = f"[{lo:.3g}, {hi:.3g}]"
            if d["problem"] == "overflow":
                msg = (f"{d['op']} with provable input range {itv} "
                       f"overflows {d['dst']} (max "
                       f"{d['bound']:.3g}) — saturate (clamp) or "
                       f"rescale before the narrowing")
            elif d["problem"] == "underflow":
                msg = (f"{d['op']} with provable input range {itv} "
                       f"underflows {d['dst']} entirely (min normal "
                       f"{d['bound']:.3g}) — the result is all "
                       f"zeros/denormals")
            else:
                msg = (f"{d['op']} over a provably non-positive "
                       f"range {itv} — the result is NaN/inf for "
                       f"the whole array; add the guard epsilon")
            out.append(Finding(
                "range-safety", Severity.HIGH, probe.name, ep.name,
                ev.path, msg))
    return out


# ------------------------------------------------------- memory highwater


@rule("memory-highwater")
def memory_highwater(probe) -> list:
    """Static live-buffer high-water per entrypoint jaxpr vs the
    probe's budget. Always emits one LOW informational finding per
    entrypoint (the number lands in the report snapshot); HIGH when the
    estimate exceeds the budget."""
    out = []
    for ep in probe.entrypoints:
        jaxpr = probe.jaxpr_of(ep)
        if jaxpr is None:
            continue
        est = peak_bytes(jaxpr.jaxpr)
        args_b = sum(aval_bytes(v.aval) for v in jaxpr.jaxpr.invars)
        mib = est / (1 << 20)
        if est > probe.hbm_budget:
            out.append(Finding(
                "memory-highwater", Severity.HIGH, probe.name, ep.name,
                (),
                f"estimated live-buffer peak {mib:.1f} MiB exceeds the "
                f"{probe.hbm_budget / (1 << 20):.0f} MiB budget "
                f"(inputs alone: {args_b / (1 << 20):.1f} MiB)"))
        else:
            out.append(Finding(
                "memory-highwater", Severity.LOW, probe.name, ep.name,
                (),
                f"estimated live-buffer peak {mib:.2f} MiB "
                f"(budget {probe.hbm_budget / (1 << 20):.0f} MiB)"))
    return out
