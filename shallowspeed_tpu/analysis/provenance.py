"""Precision-flow abstract interpretation over traced jaxprs.

`walker.iter_eqns` answers "what equations exist"; this module answers
"what does each VALUE carry" — the per-value provenance the quantized-
training rules need. One forward pass over an entrypoint's jaxpr
(recursing through pjit / shard_map / scan / while / cond / remat /
custom-vjp sub-jaxprs with explicit environment mapping) assigns every
intermediate a `VInfo`:

- ``round_m``    mantissa width of the narrowest float convert the
  value has crossed since its last rescale (None = never rounded). A
  later narrowing convert COMPOUNDS error only when it drops strictly
  below this width (f32->bf16->fp8), or re-enters a quantized storage
  dtype without a fresh rescale — re-rounding at the same width (the
  ubiquitous bf16 -> f32-arithmetic -> bf16 mixed-precision pattern)
  is a single rounding of a new value and stays silent.
- ``qid``        quantized-storage lineage: which int8/fp8 leaf (input
  param or in-program quantization) these bits come from. Survives
  upcasts and shape ops, breaks at gathers/slices — the same chain
  discipline as the `dequant-fusion` rule.
- ``sids``       scale lineage: which quantization scales this value IS
  (a `Ws` input leaf, a delayed-scaling factor, or a product of them).
- ``applied``    scales already multiplied onto this value's lineage —
  a second application is a double-scaled output.
- ``itv``        a conservative absmax interval (lo, hi), seeded from
  the probe's init/calibration stats (`EntryPoint.ranges`) and scalar
  literals, propagated through interval arithmetic. Only PROVABLE
  violations fire: the pass special-cases `x - max(x)` so softmax's
  shifted exponent is known non-positive.

The quantization-scale pairing has two sources:

1. input leaves: any dict with both ``Wq`` and ``Ws`` keys (the
   `models.transformer.quantize_weights` layout) pairs the quantized
   leaf with its scale leaf;
2. in-program quantization: ``(x / s)`` (s scale-like: rank <= 1 or
   broadcast-inflated) followed by a narrowing convert to an int8/fp8
   dtype creates a fresh quantized lineage paired to ``s``.

Every `dot_general` consuming a paired quantized lineage must see its
scale exactly once — pre-applied on the operand, riding the OTHER
operand (the transpose/VJP form: cotangent scaled before the dot), or
multiplied onto the accumulator afterwards. Unresolved or doubled
applications surface as `DotUse`/events for the scale-consistency rule.

The pass is deliberately conservative: unknown primitives produce
unknown `VInfo`s, loop carries drop their intervals (no fixpoint), and
call-like primitives whose invar layout the pass cannot map seed an
empty environment — rules built on top only fire on facts the flow
actually proved.
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass, field, replace

import jax
import numpy as np

from shallowspeed_tpu.analysis.walker import _as_jaxpr

# quantized-storage dtypes (same set the dequant-fusion rule uses)
QUANT_DTYPES = {"int8", "uint8", "float8_e4m3fn", "float8_e5m2"}

# shape ops that preserve the full value set (lineage AND interval)
_SHAPE_OPS = {"reshape", "transpose", "broadcast_in_dim", "squeeze",
              "copy", "stop_gradient", "rev", "expand_dims"}

# ops that SELECT a subset of elements: interval/rounded survive,
# quant/scale lineage breaks (matching dequant-fusion's gather rule)
_SELECT_OPS = {"gather", "slice", "dynamic_slice", "take",
               "dynamic_update_slice", "scatter", "concatenate",
               "select_n", "pad"}

_MANTISSA = {"float64": 52, "float32": 23, "bfloat16": 7, "float16": 10,
             "float8_e4m3fn": 3, "float8_e5m2": 2}
_M2DT = {m: dt for dt, m in _MANTISSA.items()}


def _min_rm(*infos):
    """Combine rounding states: the result may carry any operand's
    rounding, so keep the narrowest (min mantissa) that is set."""
    rms = [i.round_m for i in infos if i.round_m is not None]
    return min(rms) if rms else None


def _dt(x) -> str | None:
    d = getattr(getattr(x, "aval", x), "dtype", None)
    if d is None:
        return None
    try:
        return str(np.dtype(d))
    except TypeError:
        # jax extended dtypes (typed PRNG keys, `key<fry>`)
        return str(d)


def _is_float(dt: str | None) -> bool:
    return dt is not None and (dt.startswith("float")
                               or dt.startswith("bfloat"))


def _narrowing(src: str | None, dst: str | None) -> bool:
    """float->float convert that DROPS mantissa bits (a rounding)."""
    return (src in _MANTISSA and dst in _MANTISSA
            and _MANTISSA[dst] < _MANTISSA[src])


def finfo_max(dt: str) -> float:
    import ml_dtypes
    try:
        return float(ml_dtypes.finfo(dt).max)
    except Exception:
        return math.inf


def _size(v) -> int:
    shape = getattr(getattr(v, "aval", v), "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape, dtype=np.int64))


def _scale_shape(v) -> bool:
    """Structurally a scale: at most one non-1 dim ((,), (N,), (1, N),
    (N, 1), ...) — checked at USE time so a scale stays a scale no
    matter which reductions/clamps produced it."""
    shape = getattr(getattr(v, "aval", v), "shape", None)
    if shape is None:
        return False
    return sum(1 for d in shape if d != 1) <= 1


@dataclass(frozen=True)
class VInfo:
    """Abstract value: everything the precision rules need to know
    about one jaxpr var. Frozen — propagation builds new ones."""
    dtype: str | None = None
    round_m: int | None = None        # mantissa of narrowest rounding
    qid: int | None = None            # quantized-storage lineage
    sids: frozenset = frozenset()     # scale identities this value IS
    applied: frozenset = frozenset()  # scale ids applied on this lineage
    pending: frozenset = frozenset()  # DotUse indices awaiting a scale
    itv: tuple | None = None          # (lo, hi) proven element bounds
    scale_like: bool = False          # rank<=1 or broadcast-inflated
    maxof: object = None              # var this is the reduce_max of
    div_sid: int | None = None        # scale id of the last rescale div


_UNKNOWN = VInfo()


@dataclass
class QuantLeaf:
    qid: int
    label: str          # human name: arg/leaf path or trace site
    sid: int | None     # the paired scale identity (None = unpaired)
    dtype: str = ""
    shape: tuple = ()


@dataclass
class DotUse:
    """One dot_general consuming a paired quantized lineage."""
    qid: int
    label: str
    path: tuple
    shape: tuple
    resolved: bool = False
    how: str = ""       # pre-applied | cotangent-scaled | accumulator


@dataclass
class Event:
    kind: str           # double-round | dot | carry-accum | range | ...
    path: tuple
    data: dict = field(default_factory=dict)


@dataclass
class FlowResult:
    events: list = field(default_factory=list)
    dot_uses: list = field(default_factory=list)
    quants: dict = field(default_factory=dict)   # qid -> QuantLeaf


# ------------------------------------------------------------- intervals


def _itv_add(a, b):
    return None if a is None or b is None else (a[0] + b[0], a[1] + b[1])


def _itv_sub(a, b):
    return None if a is None or b is None else (a[0] - b[1], a[1] - b[0])


def _itv_mul(a, b):
    if a is None or b is None:
        return None
    with np.errstate(invalid="ignore"):
        ps = [a[i] * b[j] for i in (0, 1) for j in (0, 1)]
    ps = [0.0 if p != p else p for p in ps]  # 0*inf -> treat as 0
    return (min(ps), max(ps))


def _itv_div(a, b):
    if a is None or b is None or (b[0] <= 0.0 <= b[1]):
        return None
    return _itv_mul(a, (1.0 / b[1], 1.0 / b[0]))


def _itv_join(a, b):
    return None if a is None or b is None else (min(a[0], b[0]),
                                                max(a[1], b[1]))


def _amax(itv) -> float:
    return max(abs(itv[0]), abs(itv[1]))


def _mono(fn, itv):
    """Interval image of a monotone-increasing scalar fn, inf-safe."""
    def safe(x):
        try:
            return fn(x)
        except OverflowError:
            return math.inf
        except ValueError:
            return -math.inf
    return (safe(itv[0]), safe(itv[1]))


# -------------------------------------------------------------- the pass


class _Flow:
    def __init__(self):
        self.res = FlowResult()
        self._qids = itertools.count()
        self._sids = itertools.count()
        self._budget = 200_000  # eqn visits; huge jaxprs stay linear
        self._made_by: dict = {}  # var -> producing eqn (all scopes)

    # -- identity allocation ------------------------------------------

    def new_quant(self, label, sid, dtype="", shape=()) -> int:
        qid = next(self._qids)
        self.res.quants[qid] = QuantLeaf(qid, label, sid, dtype, shape)
        return qid

    def new_sid(self) -> int:
        return next(self._sids)

    def event(self, kind, path, **data):
        self.res.events.append(Event(kind, path, data))

    # -- environment helpers ------------------------------------------

    def info_of(self, env, atom) -> VInfo:
        if isinstance(atom, jax.core.Literal):
            val = atom.val
            itv = None
            if np.ndim(val) == 0 and _is_float(_dt(atom)):
                f = float(val)
                if math.isfinite(f):
                    itv = (f, f)
            return VInfo(dtype=_dt(atom), itv=itv,
                         scale_like=np.ndim(val) == 0)
        got = env.get(atom)
        if got is not None:
            return got
        rank = len(getattr(atom.aval, "shape", ()))
        return VInfo(dtype=_dt(atom), scale_like=rank <= 1)

    def default_out(self, v) -> VInfo:
        rank = len(getattr(v.aval, "shape", ()))
        return VInfo(dtype=_dt(v), scale_like=rank <= 1)

    # -- sub-jaxpr mapping --------------------------------------------

    def _drop_loopy(self, info: VInfo) -> VInfo:
        """A loop-carried value's interval/max-tag is only valid for
        iteration 0 — drop what grows, keep storage lineage."""
        return replace(info, itv=None, maxof=None,
                       pending=frozenset())

    def run_call(self, eqn, env, path, axis_env):
        """Generic call-like primitive: map infos 1:1 when the invar
        layouts line up, interpret, map outs back. Anything the pass
        cannot map (pallas_call grids, scatter-prefetch layouts) is
        interpreted with an EMPTY seed — events still surface, lineage
        doesn't cross the boundary."""
        name = eqn.primitive.name
        subs = [s for s in (_as_jaxpr(p) for p in _sub_params(eqn))
                if s is not None]
        if not subs:
            return False
        in_infos = [self.info_of(env, v) for v in eqn.invars]

        if name == "scan":
            body = subs[0]
            nc = eqn.params.get("num_consts", 0)
            ncar = eqn.params.get("num_carry", 0)
            seeds = list(in_infos)
            for i in range(nc, nc + ncar):
                seeds[i] = self._drop_loopy(seeds[i])
            sub_env = dict(zip(body.invars, seeds))
            self.interp(body, sub_env, path + (name,), axis_env)
            self._check_carries(body, sub_env,
                                body.invars[nc:nc + ncar],
                                body.outvars[:ncar], path, "scan")
            outs = [self.info_of(sub_env, v) for v in body.outvars]
            for i in range(min(ncar, len(outs))):
                outs[i] = self._drop_loopy(outs[i])
            for v, info in zip(eqn.outvars, outs):
                env[v] = replace(info, dtype=_dt(v))
            return True

        if name == "while":
            cn = eqn.params.get("cond_nconsts", 0)
            bn = eqn.params.get("body_nconsts", 0)
            cond_j = _as_jaxpr(eqn.params.get("cond_jaxpr"))
            body_j = _as_jaxpr(eqn.params.get("body_jaxpr"))
            carry = [self._drop_loopy(i)
                     for i in in_infos[cn + bn:]]
            if cond_j is not None:
                self.interp(cond_j, dict(zip(
                    cond_j.invars, in_infos[:cn] + carry)),
                    path + (name,), axis_env)
            if body_j is not None:
                sub_env = dict(zip(
                    body_j.invars, in_infos[cn:cn + bn] + carry))
                self.interp(body_j, sub_env, path + (name,), axis_env)
                self._check_carries(
                    body_j, sub_env, body_j.invars[bn:],
                    body_j.outvars, path, "while")
                outs = [self._drop_loopy(self.info_of(sub_env, v))
                        for v in body_j.outvars]
                for v, info in zip(eqn.outvars, outs):
                    env[v] = replace(info, dtype=_dt(v))
            return True

        if name == "cond":
            branch_outs = []
            for b in subs:
                seeds = in_infos[1:]
                if len(b.invars) != len(seeds):
                    seeds = [_UNKNOWN] * len(b.invars)
                sub_env = dict(zip(b.invars, seeds))
                self.interp(b, sub_env, path + (name,), axis_env)
                branch_outs.append(
                    [self.info_of(sub_env, v) for v in b.outvars])
            for i, v in enumerate(eqn.outvars):
                infos = [bo[i] for bo in branch_outs if i < len(bo)]
                env[v] = _join_infos(infos, _dt(v))
            return True

        # pjit / closed_call / remat2 / custom_jvp_call /
        # custom_vjp_call(_jaxpr) / shard_map / ...: 1:1 when mappable
        new_axes = dict(axis_env)
        if name == "shard_map":
            mesh = eqn.params.get("mesh")
            auto = eqn.params.get("auto", frozenset()) or frozenset()
            if mesh is not None:
                for ax in mesh.axis_names:
                    if ax not in auto:
                        new_axes[ax] = int(mesh.shape[ax])
        body = subs[0]
        seeds = (in_infos if len(body.invars) == len(eqn.invars)
                 else [_UNKNOWN] * len(body.invars))
        sub_env = dict(zip(body.invars, seeds))
        self.interp(body, sub_env, path + (name,), new_axes)
        if len(body.outvars) == len(eqn.outvars):
            for v, bv in zip(eqn.outvars, body.outvars):
                env[v] = replace(self.info_of(sub_env, bv),
                                 dtype=_dt(v))
        else:
            for v in eqn.outvars:
                env[v] = self.default_out(v)
        # remaining subs (cond already handled): events only
        for extra in subs[1:]:
            self.interp(extra, {}, path + (name,), new_axes)
        return True

    def _check_carries(self, body, sub_env, carry_in, carry_out,
                       path, prim):
        """A loop carry whose out is `carry_in + contribution`, with
        the contribution NOT derived from the carry, is an ACCUMULATOR
        — it must carry f32 (the accumulation-dtype rule's loop half;
        the peeled-microbatch grad sums live here). The independence
        check is what keeps bf16 residual streams (`x + f(x)`, where
        f(x) depends on the carry) from being misread as accumulators:
        those re-round every iteration by construction and are the
        documented mixed-precision activation path, not a sum."""
        made_by = {}
        for eqn in body.eqns:
            for v in eqn.outvars:
                made_by[v] = eqn
        # forward dependency sweep: which carries does each var depend on
        deps: dict = {id(ci): {i} for i, ci in enumerate(carry_in)}
        for eqn in body.eqns:
            d: set = set()
            for v in eqn.invars:
                if not isinstance(v, jax.core.Literal):
                    d |= deps.get(id(v), set())
            for v in eqn.outvars:
                deps[id(v)] = d
        for i, (ci, co) in enumerate(zip(carry_in, carry_out)):
            dt = _dt(co)
            if not _is_float(dt) or dt in ("float32", "float64"):
                continue
            eqn = made_by.get(co)
            # look through a trailing convert
            if eqn is not None and eqn.primitive.name \
                    == "convert_element_type":
                eqn = made_by.get(eqn.invars[0])
            if eqn is None or eqn.primitive.name not in ("add",
                                                         "add_any"):
                continue
            sides = eqn.invars
            direct = [v for v in sides
                      if _strips_to(v, ci, made_by)]
            others = [v for v in sides if v not in direct]
            if not direct or any(
                    i in deps.get(id(v), set()) for v in others):
                continue
            self.event("carry-accum", path, prim=prim, dtype=dt,
                       shape=tuple(getattr(co.aval, "shape", ())))

    # -- equation dispatch --------------------------------------------

    def interp(self, jaxpr, env, path=(), axis_env=None):
        j = _as_jaxpr(jaxpr)
        axis_env = axis_env or {}
        for cv in getattr(j, "constvars", ()):
            env.setdefault(cv, self.default_out(cv))
        for eqn in j.eqns:
            if self._budget <= 0:
                return
            self._budget -= 1
            for v in eqn.outvars:
                self._made_by[v] = eqn
            if self.run_call(eqn, env, path, axis_env):
                continue
            self.eqn(eqn, env, path, axis_env)

    def eqn(self, eqn, env, path, axis_env):
        name = eqn.primitive.name
        ins = [self.info_of(env, v) for v in eqn.invars]
        out = eqn.outvars[0] if eqn.outvars else None

        def put(info: VInfo):
            if out is not None:
                env[out] = replace(info, dtype=_dt(out))
            for extra in eqn.outvars[1:]:
                env[extra] = self.default_out(extra)

        if name == "convert_element_type":
            put(self.convert(eqn, ins[0], path))
            return
        if name in _SHAPE_OPS:
            info = ins[0]
            if name == "broadcast_in_dim" and out is not None:
                if _size(eqn.invars[0]) * 8 <= _size(out) \
                        or _size(eqn.invars[0]) <= 1:
                    info = replace(info, scale_like=True)
            put(info)
            return
        if name in _SELECT_OPS:
            if name in ("select_n", "concatenate",
                        "dynamic_update_slice", "scatter", "pad"):
                lo_i = 1 if name == "select_n" else 0
                vals = [i for i, v in zip(ins[lo_i:],
                                          eqn.invars[lo_i:])
                        if _is_float(_dt(v))]
                itv = vals[0].itv if vals else None
                for i in vals:
                    itv = _itv_join(itv, i.itv)
                put(VInfo(round_m=_min_rm(*vals), itv=itv))
            else:
                put(replace(ins[0], qid=None, sids=frozenset(),
                            applied=frozenset(), pending=frozenset(),
                            maxof=None))
            return
        if name == "clamp":
            lo, x, hi = ins[0], ins[1], ins[2]
            itv = x.itv
            if lo.itv is not None and hi.itv is not None:
                itv = (lo.itv[0],
                       hi.itv[1]) if itv is None else (
                    max(itv[0], lo.itv[0]), min(itv[1], hi.itv[1]))
            put(replace(x, itv=itv, maxof=None))
            return
        if name == "dot_general":
            put(self.dot(eqn, ins[0], ins[1], path))
            return
        if name in ("add", "add_any", "sub"):
            put(self.addsub(eqn, name, ins, path))
            return
        if name in ("mul", "div"):
            put(self.muldiv(eqn, name, ins, path, env))
            return
        if name in ("max", "min"):
            a, b = ins[0], ins[1]
            carrier = a if _size(eqn.invars[0]) >= _size(
                eqn.invars[1]) else b
            itv = None
            if a.itv and b.itv:
                f = max if name == "max" else min
                itv = (f(a.itv[0], b.itv[0]), f(a.itv[1], b.itv[1]))
            elif name == "max" and (a.itv or b.itv):
                # max(x, c) is bounded below by c even if x is unknown
                known = a.itv or b.itv
                itv = (known[0], math.inf)
                itv = None if not math.isfinite(known[0]) else itv
            # `max` can only RAISE the subtrahend, so the x - max(x)
            # <= 0 proof survives a floor (softmax's `max -inf m`);
            # `min` could lower it, which would break the bound
            tag = (a.maxof or b.maxof) if name == "max" else None
            put(replace(carrier, itv=itv, maxof=tag))
            return
        if name in ("reduce_sum", "cumsum"):
            n = max(_size(eqn.invars[0]) // max(_size(out), 1), 1)
            itv = (_itv_mul(ins[0].itv, (n, n))
                   if ins[0].itv else None)
            put(VInfo(itv=itv))
            return
        if name in ("reduce_max", "reduce_min", "argmax", "argmin",
                    "cummax", "cummin"):
            tag = eqn.invars[0] if name == "reduce_max" else None
            put(VInfo(itv=ins[0].itv, maxof=tag,
                      round_m=ins[0].round_m))
            return
        if name in ("psum", "psum_scatter", "reduce_scatter"):
            axes = eqn.params.get("axes") or eqn.params.get("axis_name")
            if not isinstance(axes, (tuple, list)):
                axes = (axes,)
            n = 1
            for ax in axes:
                n *= axis_env.get(ax, 1) if isinstance(ax, str) else 1
            put(VInfo(itv=_itv_mul(ins[0].itv, (n, n))
                      if ins[0].itv else None,
                      round_m=ins[0].round_m))
            return
        if name in _UNARY_ITV:
            put(self.unary(eqn, name, ins[0], path))
            return
        if name == "integer_pow":
            y = eqn.params.get("y", 2)
            itv = None
            src = ins[0].itv
            if src is not None and (
                    y >= 0 or not src[0] <= 0.0 <= src[1]):
                try:
                    cands = [float(src[0]) ** y, float(src[1]) ** y]
                except (OverflowError, ZeroDivisionError):
                    cands = None
                if cands is not None:
                    if y % 2 == 0 and src[0] <= 0 <= src[1]:
                        cands.append(0.0)
                    itv = (min(cands), max(cands))
            put(VInfo(itv=itv, sids=ins[0].sids,
                      scale_like=ins[0].scale_like))
            return
        if out is not None:
            put(self.default_out(out))
        for extra in eqn.outvars[1:]:
            env[extra] = self.default_out(extra)

    # -- primitive semantics ------------------------------------------

    def convert(self, eqn, src: VInfo, path) -> VInfo:
        sdt, ddt = _dt(eqn.invars[0]), _dt(eqn.outvars[0])
        info = src
        if _narrowing(sdt, ddt):
            m = _MANTISSA[ddt]
            # compounds only when this rounding drops STRICTLY below
            # the value's previous rounding width, or re-enters a
            # quantized storage dtype with no fresh rescale; re-rounding
            # at the same width (bf16 -> f32 arithmetic -> bf16) is one
            # rounding of a new value
            if src.round_m is not None and (
                    m < src.round_m or ddt in QUANT_DTYPES):
                self.event(
                    "double-round", path, src=sdt, dst=ddt,
                    first=_M2DT.get(src.round_m, str(src.round_m)),
                    shape=tuple(getattr(eqn.outvars[0].aval, "shape",
                                        ())),
                    origin=("storage" if src.qid is not None
                            else "compute"))
            if src.itv is not None:
                lim = finfo_max(ddt)
                if _amax(src.itv) > lim:
                    self.event(
                        "range", path, op="convert", dst=ddt,
                        itv=src.itv, bound=lim,
                        problem="overflow",
                        shape=tuple(getattr(eqn.outvars[0].aval,
                                            "shape", ())))
            info = replace(info, round_m=m if src.round_m is None
                           else min(m, src.round_m))
        if ddt in QUANT_DTYPES and src.div_sid is not None:
            # in-program quantization: (x / s) rounded into quantized
            # storage — fresh lineage paired to s
            qid = self.new_quant(
                f"traced quant @{'/'.join(path) or 'top'}",
                src.div_sid, ddt,
                tuple(getattr(eqn.outvars[0].aval, "shape", ())))
            info = replace(info, qid=qid,
                           round_m=_MANTISSA.get(ddt, 0))
        return replace(info, div_sid=src.div_sid)

    def addsub(self, eqn, name, ins, path) -> VInfo:
        a, b = ins[0], ins[1]
        if name == "sub" and b.maxof is not None \
                and b.maxof is eqn.invars[0]:
            # x - max(x) (possibly floored): provably <= 0 — the
            # softmax/logsumexp shift. Only the upper bound is claimed;
            # a floor on the max makes the result MORE negative.
            return VInfo(round_m=a.round_m, itv=(-math.inf, 0.0))
        itv = _itv_add(a.itv, b.itv) if name != "sub" \
            else _itv_sub(a.itv, b.itv)
        return VInfo(round_m=_min_rm(a, b), itv=itv,
                     applied=a.applied | b.applied,
                     pending=a.pending | b.pending)

    def muldiv(self, eqn, name, ins, path, env) -> VInfo:
        a, b = ins[0], ins[1]
        itv = _itv_mul(a.itv, b.itv) if name == "mul" \
            else _itv_div(a.itv, b.itv)
        if a.sids and b.sids:   # product of scales is a scale
            return VInfo(itv=itv, sids=a.sids | b.sids,
                         scale_like=a.scale_like and b.scale_like)
        # orient: `val` is the data side, `sc` the (possible) scale side
        val, sc = (a, b) if not a.sids else (b, a)
        sc_sl = sc.scale_like or _scale_shape(
            eqn.invars[1 if sc is b else 0])
        out = VInfo(round_m=val.round_m, itv=itv, qid=val.qid,
                    applied=val.applied, pending=val.pending,
                    div_sid=val.div_sid)
        if sc_sl:
            # a rescale: the value's rounding no longer compounds
            out = replace(out, round_m=None)
        if name == "div" and sc is b and sc_sl:
            # quantizing rescale `x / s`: lazily make `s` a scale
            # identity so a following narrowing convert pairs to it
            # and the later dequant multiply by (a product with) `s`
            # resolves the pairing
            sid = next(iter(sc.sids), None)
            if sid is None:
                sid = self.new_sid()
                self._tag_scale_chain(eqn.invars[1], sid, env)
            out = replace(out, div_sid=sid)
        if sc.sids:
            hit = frozenset(s for s in sc.sids if s in val.applied)
            if hit:
                self.event("double-scale", path,
                           labels=self._sid_labels(hit))
            resolved = frozenset(
                i for i in val.pending
                if self.res.dot_uses[i].qid in self.res.quants
                and self.res.quants[
                    self.res.dot_uses[i].qid].sid in sc.sids)
            for i in resolved:
                self.res.dot_uses[i].resolved = True
                self.res.dot_uses[i].how = "accumulator"
            out = replace(out, pending=out.pending - resolved,
                          applied=out.applied | sc.sids)
        return out

    def _tag_scale_chain(self, var, sid, env, depth=8):
        """Attach a fresh scale identity to a divisor var AND its
        shape/convert ancestors, so any later value derived from the
        same scale (the dequant multiply's operand) carries the sid."""
        while depth and not isinstance(var, jax.core.Literal):
            info = env.get(var) or VInfo(dtype=_dt(var))
            env[var] = replace(info, sids=info.sids | {sid},
                               scale_like=True)
            eqn = self._made_by.get(var)
            if eqn is None or eqn.primitive.name not in (
                    "convert_element_type", *_SHAPE_OPS):
                return
            var = eqn.invars[0]
            depth -= 1

    def _sid_labels(self, sids) -> tuple:
        names = []
        for q in self.res.quants.values():
            if q.sid in sids:
                names.append(q.label)
        return tuple(names) or tuple(sorted(sids))

    def dot(self, eqn, lhs: VInfo, rhs: VInfo, path) -> VInfo:
        odt = _dt(eqn.outvars[0])
        ldt, rdt = _dt(eqn.invars[0]), _dt(eqn.invars[1])
        (lc, _), _ = eqn.params["dimension_numbers"]
        lshape = getattr(eqn.invars[0].aval, "shape", ())
        k = int(np.prod([lshape[i] for i in lc], dtype=np.int64)) or 1
        itv = None
        if lhs.itv is not None and rhs.itv is not None:
            bound = k * _amax(lhs.itv) * _amax(rhs.itv)
            itv = (-bound, bound)
        self.event(
            "dot", path, out_dtype=odt, in_dtypes=(ldt, rdt),
            quant=(lhs.qid is not None or rhs.qid is not None
                   or ldt in QUANT_DTYPES or rdt in QUANT_DTYPES),
            shape=tuple(getattr(eqn.outvars[0].aval, "shape", ())),
            k=k)
        pending = set()
        applied = lhs.applied | rhs.applied
        for me, other, var in ((lhs, rhs, eqn.invars[0]),
                               (rhs, lhs, eqn.invars[1])):
            if me.qid is None:
                continue
            leaf = self.res.quants.get(me.qid)
            if leaf is None or leaf.sid is None:
                continue
            use = DotUse(me.qid, leaf.label, path,
                         tuple(getattr(var.aval, "shape", ())))
            if leaf.sid in me.applied:
                use.resolved, use.how = True, "pre-applied"
            elif leaf.sid in other.sids or leaf.sid in other.applied:
                # VJP form: the cotangent arrives pre-multiplied by
                # the scale, so the product is correctly scaled
                use.resolved, use.how = True, "cotangent-scaled"
                applied = applied | {leaf.sid}
            self.res.dot_uses.append(use)
            if not use.resolved:
                pending.add(len(self.res.dot_uses) - 1)
        return VInfo(itv=itv, pending=frozenset(pending),
                     applied=frozenset(applied))

    def unary(self, eqn, name, src: VInfo, path) -> VInfo:
        odt = _dt(eqn.outvars[0])
        fn, lo_cap, hi_cap = _UNARY_ITV[name]
        itv = None
        if src.itv is not None:
            if name == "exp":
                lim = finfo_max(odt) if odt else math.inf
                if math.isfinite(src.itv[1]) \
                        and src.itv[1] > math.log(lim):
                    self.event("range", path, op=name, itv=src.itv,
                               bound=lim, dst=odt, problem="overflow")
                tiny = _finfo_tiny(odt)
                if tiny > 0.0 and math.isfinite(src.itv[1]) \
                        and src.itv[1] < math.log(tiny):
                    self.event("range", path, op=name, itv=src.itv,
                               bound=tiny, dst=odt,
                               problem="underflow")
            if name in ("log", "log1p", "rsqrt", "sqrt"):
                shift = -1.0 if name == "log1p" else 0.0
                needs_pos = name in ("log", "rsqrt")
                bad = (src.itv[1] <= shift if needs_pos
                       else src.itv[1] < shift)
                if bad:
                    self.event("range", path, op=name, itv=src.itv,
                               dst=odt, problem="domain",
                               bound=shift)
            if name == "neg":
                itv = (-src.itv[1], -src.itv[0])
            elif name == "abs":
                itv = (0.0 if src.itv[0] <= 0 <= src.itv[1]
                       else min(abs(src.itv[0]), abs(src.itv[1])),
                       _amax(src.itv))
            else:
                itv = _mono(fn, src.itv)
            if itv is not None and (itv[0] != itv[0]
                                    or itv[1] != itv[1]):
                itv = None
        if itv is None and lo_cap is not None:
            itv = (lo_cap, hi_cap)
        elif itv is not None and lo_cap is not None:
            itv = (max(itv[0], lo_cap), min(itv[1], hi_cap))
        keep_lineage = name in ("neg", "abs", "round", "floor",
                                "ceil")
        return VInfo(
            itv=itv,
            round_m=src.round_m if name in ("neg", "abs") else None,
            qid=src.qid if keep_lineage else None,
            sids=src.sids if keep_lineage else frozenset(),
            div_sid=src.div_sid if keep_lineage else None,
            scale_like=src.scale_like)


def _finfo_tiny(dt) -> float:
    import ml_dtypes
    try:
        return float(ml_dtypes.finfo(dt).tiny)
    except Exception:
        return 0.0


# monotone/caps table: name -> (pointwise fn, lo cap, hi cap)
_UNARY_ITV = {
    "exp": (math.exp, None, None),
    "log": (lambda x: math.log(x) if x > 0 else -math.inf, None, None),
    "log1p": (lambda x: math.log1p(x) if x > -1 else -math.inf,
              None, None),
    "sqrt": (lambda x: math.sqrt(max(x, 0.0)), None, None),
    "rsqrt": (lambda x: 1.0 / math.sqrt(x) if x > 0 else math.inf,
              None, None),
    "tanh": (math.tanh, -1.0, 1.0),
    "logistic": (lambda x: 1.0 / (1.0 + math.exp(-min(max(x, -700),
                                                      700))),
                 0.0, 1.0),
    "erf": (math.erf, -1.0, 1.0),
    "neg": (lambda x: x, None, None),   # negated inline in unary()
    "abs": (abs, None, None),           # computed inline in unary()
    "sin": (lambda x: x, -1.0, 1.0),
    "cos": (lambda x: x, -1.0, 1.0),
    "floor": (math.floor, None, None),
    "ceil": (math.ceil, None, None),
    "round": (lambda x: float(round(x)), None, None),
    "sign": (lambda x: float(np.sign(x)), -1.0, 1.0),
    "exp2": (lambda x: 2.0 ** min(x, 10000.0), None, None),
}


def _sub_params(eqn):
    out = []
    for v in eqn.params.values():
        items = v if isinstance(v, (tuple, list)) else (v,)
        out.extend(items)
    return out


def _join_infos(infos, dtype) -> VInfo:
    """Least-upper-bound over cond branches: interval union, rounding
    OR, lineage kept only when every branch agrees."""
    if not infos:
        return VInfo(dtype=dtype)
    itv = infos[0].itv
    qid = infos[0].qid
    sids = infos[0].sids
    for i in infos:
        itv = _itv_join(itv, i.itv)
        qid = qid if i.qid == qid else None
        sids = sids if i.sids == sids else frozenset()
    return VInfo(dtype=dtype, round_m=_min_rm(*infos), qid=qid,
                 sids=sids, itv=itv)


def _strips_to(v, target, made_by, depth=8) -> bool:
    """`v` IS `target` modulo converts/shape ops (the direct-carry side
    of an accumulator add)."""
    while depth:
        if v is target:
            return True
        eqn = made_by.get(v)
        if eqn is None or eqn.primitive.name not in (
                "convert_element_type", *_SHAPE_OPS):
            return False
        v = eqn.invars[0]
        depth -= 1
    return False


# ----------------------------------------------------------- seeding


def seed_entrypoint(ep) -> tuple:
    """Flat per-invar VInfo seeds for one entrypoint, in jaxpr invar
    order: Wq/Ws pairs from the arg pytrees get quant/scale
    identities; `ep.ranges` (arg name -> (lo, hi), the init/calibration
    absmax stats) seeds float-leaf intervals; fp8-dtype inputs start
    life already rounded. Returns (seeds, flow) — the flow carries the
    pre-registered QuantLeafs."""
    flow = _Flow()
    seeds: list = []
    ranges = getattr(ep, "ranges", None) or {}
    for arg, arg_name in zip(ep.args, ep.arg_names):
        flat = jax.tree_util.tree_flatten_with_path(arg)[0]
        rng = ranges.get(arg_name)
        pend_pairs: dict = {}   # parent path -> [wq idx, sid]
        infos = []
        for path, leaf in flat:
            dt = _dt(leaf)
            rank = len(getattr(leaf, "shape", ()))
            info = VInfo(dtype=dt, scale_like=rank <= 1,
                         round_m=_MANTISSA.get(dt)
                         if dt in QUANT_DTYPES else None)
            if rng is not None and _is_float(dt):
                info = replace(info, itv=(float(rng[0]),
                                          float(rng[1])))
            key = getattr(path[-1], "key", None) if path else None
            parent = tuple(str(p) for p in path[:-1])
            if key == "Wq" and dt in QUANT_DTYPES:
                ent = pend_pairs.setdefault(parent, {})
                ent["wq"] = (len(infos), info,
                             f"{arg_name}{_fmt_path(path)}",
                             dt, tuple(leaf.shape))
            elif key == "Ws":
                ent = pend_pairs.setdefault(parent, {})
                sid = flow.new_sid()
                ent["sid"] = sid
                info = replace(info, sids=frozenset({sid}),
                               scale_like=True)
            infos.append(info)
        for ent in pend_pairs.values():
            if "wq" in ent and "sid" in ent:
                i, info, label, dt, shape = ent["wq"]
                qid = flow.new_quant(label, ent["sid"], dt, shape)
                infos[i] = replace(infos[i], qid=qid)
        seeds.extend(infos)
    return seeds, flow


def _fmt_path(path) -> str:
    try:
        return jax.tree_util.keystr(path)
    except Exception:
        return "." + ".".join(str(p) for p in path)


def flow_entrypoint(probe, ep) -> FlowResult:
    """Run the precision-flow pass over one entrypoint's jaxpr."""
    closed = probe.jaxpr_of(ep)
    seeds, flow = seed_entrypoint(ep)
    j = closed.jaxpr
    env = {}
    if len(seeds) == len(j.invars):
        env = dict(zip(j.invars, seeds))
    flow.interp(closed, env, (), {})
    return flow.res
