"""CLI gate: `python -m shallowspeed_tpu.analysis --target all`.

Builds each requested target's engines at the test-suite configuration,
runs every rule, prints the findings (suppressed ones with their
reasons — the intentional-deviation documentation), and gates on
unsuppressed HIGH findings. Wired as a pre-commit hook
(`.pre-commit-config.yaml`) and enforced in tier-1 by
`tests/test_analysis.py`.

Exit-code contract (stable — scripts may rely on it):

    0   clean: no unsuppressed HIGH finding (with ``--baseline``: none
        beyond the recorded baseline)
    1   gate failure: at least one (new) unsuppressed HIGH finding
    2   usage error: unknown target, unknown rule, unreadable/invalid
        baseline file, bad flags (argparse's own convention)

``--format json`` emits one machine-readable document on stdout
(schema ``shallowspeed-tpu.analysis/1``):

    {"schema": ..., "gate": <int>, "baselined": <int>,
     "targets": {<probe>: {"findings": [<Finding.to_dict()>, ...],
                           "gating": <int>}},
     "summary": {"targets": n, "findings": n, "gating": n,
                 "suppressed": n}}

``--write-baseline FILE`` records every current gating finding's stable
key; a later run with ``--baseline FILE`` gates only on findings whose
key is NOT recorded — the ratchet mode for adopting a new rule on a
codebase with known, not-yet-fixed violations. Baselined findings are
still printed (marked ``baselined``); fixing them shrinks the file on
the next ``--write-baseline``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

SCHEMA = "shallowspeed-tpu.analysis/1"


def _load_baseline(path, ap) -> set:
    try:
        with open(path) as fh:
            doc = json.load(fh)
        keys = doc["keys"]
        assert isinstance(keys, list)
    except (OSError, ValueError, KeyError, AssertionError) as e:
        ap.error(f"cannot read baseline {path!r}: {e}")  # exits 2
    return set(keys)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.analysis",
        description="statically prove the compiled train steps are "
                    "TPU-clean (dtype / donation / collectives / "
                    "retrace / memory / precision flow)",
        epilog="exit codes: 0 clean, 1 gating finding(s), 2 usage "
               "error")
    ap.add_argument("--target", default="all",
                    help="probe or group: engine, spmd_pipeline, gspmd, "
                         "pipeline_lm, zb, all, or an exact probe name "
                         "like pipeline_lm:1f1b or fp8_train "
                         "(default: all)")
    ap.add_argument("--budget-gb", type=float, default=16.0,
                    help="HBM budget for the memory-highwater rule "
                         "(default: 16 GiB — one v4/v5e-class chip)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--format", choices=("text", "json"),
                    default="text",
                    help="output format (json: one document on stdout, "
                         "schema %s)" % SCHEMA)
    ap.add_argument("--baseline", metavar="FILE",
                    help="gate only on findings whose key is not in "
                         "this baseline file")
    ap.add_argument("--write-baseline", metavar="FILE",
                    help="record current gating findings' keys to FILE "
                         "and exit 0")
    ap.add_argument("--platform", default=os.environ.get(
        "JAX_PLATFORMS", "cpu"),
        help="jax platform (default: cpu — the pass is static; probes "
             "run on 8 virtual host devices)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only gating findings and the summary")
    args = ap.parse_args(argv)

    baseline = (_load_baseline(args.baseline, ap)
                if args.baseline else None)

    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    jax.config.update("jax_platforms", args.platform)

    from shallowspeed_tpu.analysis import (RULES, Severity, analyze,
                                           resolve_targets)

    only = tuple(r for r in args.rules.split(",") if r)
    unknown = [r for r in only if r not in RULES]
    if unknown:  # a typo must not silently run zero rules and exit 0
        ap.error(f"unknown rule(s) {unknown}; "
                 f"pick from {sorted(RULES)}")
    try:  # unknown target is a usage error too (exit 2, not 1)
        resolve_targets(args.target)
    except SystemExit as e:
        ap.error(str(e))
    budget = int(args.budget_gb * (1 << 30))
    results = analyze(args.target, budget=budget, only=only)

    def gates(f):  # unsuppressed HIGH, beyond the baseline if any
        return (f.severity == Severity.HIGH and not f.suppressed
                and (baseline is None or f.key not in baseline))

    total = [f for fs in results.values() for f in fs]
    gating = [f for f in total if gates(f)]
    n_base = sum(1 for f in total
                 if f.severity == Severity.HIGH and not f.suppressed
                 and not gates(f))
    n_sup = sum(1 for f in total if f.suppressed)

    if args.write_baseline:
        keys = sorted({f.key for f in total
                       if f.severity == Severity.HIGH
                       and not f.suppressed})
        with open(args.write_baseline, "w") as fh:
            json.dump({"schema": SCHEMA, "keys": keys}, fh, indent=1)
            fh.write("\n")
        print(f"wrote {len(keys)} baseline key(s) to "
              f"{args.write_baseline}")
        return 0

    if args.format == "json":
        doc = {
            "schema": SCHEMA,
            "gate": len(gating),
            "baselined": n_base,
            "targets": {
                name: {"findings": [f.to_dict() for f in fs],
                       "gating": sum(1 for f in fs if gates(f))}
                for name, fs in results.items()},
            "summary": {"targets": len(results),
                        "findings": len(total),
                        "gating": len(gating),
                        "suppressed": n_sup},
        }
        json.dump(doc, sys.stdout, indent=1)
        sys.stdout.write("\n")
        return 1 if gating else 0

    for name, findings in results.items():
        shown = [f for f in findings if not args.quiet or gates(f)]
        print(f"== {name}: {len(findings)} finding(s), "
              f"{sum(1 for f in findings if gates(f))} gating")
        for f in shown:
            line = "  " + f.format().replace("\n", "\n  ")
            if (baseline is not None and f.severity == Severity.HIGH
                    and not f.suppressed and not gates(f)):
                line += "\n    (baselined)"
            print(line)
    print(f"\n{len(results)} target(s), {len(total)} finding(s): "
          f"{len(gating)} gating high-severity, {n_sup} suppressed "
          f"(documented above)"
          + (f", {n_base} baselined" if baseline is not None else ""))
    return 1 if gating else 0


if __name__ == "__main__":
    sys.exit(main())
