"""CLI gate: `python -m shallowspeed_tpu.analysis --target all`.

Builds each requested target's engines at the test-suite configuration,
runs every rule, prints the findings (suppressed ones with their
reasons — the intentional-deviation documentation), and exits non-zero
iff any unsuppressed HIGH finding remains. Wired as a pre-commit hook
(`.pre-commit-config.yaml`) and enforced in tier-1 by
`tests/test_analysis.py`.
"""

from __future__ import annotations

import argparse
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.analysis",
        description="statically prove the compiled train steps are "
                    "TPU-clean (dtype / donation / collectives / "
                    "retrace / memory)")
    ap.add_argument("--target", default="all",
                    help="probe or group: engine, spmd_pipeline, gspmd, "
                         "pipeline_lm, zb, all, or an exact probe name "
                         "like pipeline_lm:1f1b (default: all)")
    ap.add_argument("--budget-gb", type=float, default=16.0,
                    help="HBM budget for the memory-highwater rule "
                         "(default: 16 GiB — one v4/v5e-class chip)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--platform", default=os.environ.get(
        "JAX_PLATFORMS", "cpu"),
        help="jax platform (default: cpu — the pass is static; probes "
             "run on 8 virtual host devices)")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="print only gating findings and the summary")
    args = ap.parse_args(argv)

    if args.platform == "cpu":
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
    import jax

    jax.config.update("jax_platforms", args.platform)

    from shallowspeed_tpu.analysis import (RULES, Severity, analyze,
                                           gate_count)

    only = tuple(r for r in args.rules.split(",") if r)
    unknown = [r for r in only if r not in RULES]
    if unknown:  # a typo must not silently run zero rules and exit 0
        raise SystemExit(
            f"unknown rule(s) {unknown}; pick from {sorted(RULES)}")
    budget = int(args.budget_gb * (1 << 30))
    results = analyze(args.target, budget=budget, only=only)

    total = []
    for name, findings in results.items():
        total.extend(findings)
        shown = [f for f in findings
                 if not args.quiet or (f.severity == Severity.HIGH
                                       and not f.suppressed)]
        print(f"== {name}: {len(findings)} finding(s), "
              f"{gate_count(findings)} gating")
        for f in shown:
            print("  " + f.format().replace("\n", "\n  "))
    n_gate = gate_count(total)
    n_sup = sum(1 for f in total if f.suppressed)
    print(f"\n{len(results)} target(s), {len(total)} finding(s): "
          f"{n_gate} gating high-severity, {n_sup} suppressed "
          f"(documented above)")
    return 1 if n_gate else 0


if __name__ == "__main__":
    sys.exit(main())
