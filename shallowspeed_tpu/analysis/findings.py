"""Structured findings + the suppression registry.

A `Finding` is one fact the static pass proved about a compiled train
step: which rule fired, how bad it is, which target/entrypoint it lives
in, and the jaxpr provenance (the chain of enclosing sub-jaxprs — pjit /
shard_map / scan / cond / remat — down to the offending equation).

Suppressions are the inline escape hatch: a module that does something
the linter flags ON PURPOSE registers a suppression NEXT TO the code
that causes it, with a mandatory reason string — so the analyzer doubles
as documentation of every deliberate deviation. A suppressed finding is
still reported (with its reason); it just stops counting against the
zero-high-severity gate.

This module is dependency-free (stdlib only) so engine modules can
import it at module scope without dragging jax tracing machinery in.
"""

from __future__ import annotations

import enum
import re
from dataclasses import dataclass, field
from fnmatch import fnmatch


class Severity(enum.IntEnum):
    """LOW = informational (always emitted, never gates); MEDIUM = smells
    that deserve a look; HIGH = provable TPU-cleanliness violations — the
    CI gate fails on any unsuppressed HIGH."""

    LOW = 0
    MEDIUM = 1
    HIGH = 2


@dataclass
class Finding:
    rule: str                    # registry name, e.g. "donation"
    severity: Severity
    target: str                  # probe name, e.g. "pipeline_lm:1f1b"
    site: str                    # entrypoint name, e.g. "_step"
    path: tuple = ()             # enclosing sub-jaxpr chain (prim names)
    message: str = ""
    suppressed: str | None = None  # reason string when suppressed
    # the registration that suppressed it (stale-suppression audit);
    # never serialized — `suppressed` carries the reason
    suppressed_by: object = field(default=None, repr=False,
                                  compare=False)

    @property
    def where(self) -> str:
        chain = "/".join(self.path)
        return f"{self.target}::{self.site}" + (f" [{chain}]" if chain
                                                else "")

    @property
    def key(self) -> str:
        """Stable identity for baseline diffing: location + message with
        the volatile dedup count (` (xN)`) stripped."""
        msg = re.sub(r" \(x\d+\)$", "", self.message)
        return "|".join((self.rule, self.target, self.site,
                         "/".join(self.path), msg))

    def to_dict(self) -> dict:
        """Machine-readable form (--format json). Stable fields: rule,
        severity (name), target, site, path (list), message,
        suppressed (reason or null), key."""
        return {
            "rule": self.rule,
            "severity": self.severity.name,
            "target": self.target,
            "site": self.site,
            "path": list(self.path),
            "message": self.message,
            "suppressed": self.suppressed,
            "key": self.key,
        }

    def format(self) -> str:
        tag = ("suppressed"
               if self.suppressed else self.severity.name)
        out = f"[{tag:>10}] {self.rule:<18} {self.where}: {self.message}"
        if self.suppressed:
            out += f"\n{'':>13}reason: {self.suppressed}"
        return out


@dataclass
class Suppression:
    rule: str       # rule name or "*"
    target: str     # fnmatch glob over the probe name
    match: str      # substring of the finding's message/site ("" = any)
    reason: str


_REGISTRY: list[Suppression] = []


def suppress(rule: str, target: str = "*", match: str = "",
             reason: str = "") -> Suppression:
    """Register an intentional-deviation suppression. `reason` is
    mandatory — the analyzer's report prints it, so the registration
    site IS the documentation of why the finding is deliberate."""
    assert reason.strip(), (
        "suppress() requires a non-empty reason string — the suppression "
        "doubles as documentation of the intentional finding")
    s = Suppression(rule, target, match, reason)
    _REGISTRY.append(s)
    return s


def registered_suppressions() -> tuple:
    return tuple(_REGISTRY)


def clear_suppressions(keep=()) -> None:
    """Testing hook: reset the registry (optionally to a saved snapshot
    from `registered_suppressions`)."""
    _REGISTRY.clear()
    _REGISTRY.extend(keep)


def apply_suppressions(findings: list) -> list:
    """Mark each finding suppressed by the first matching registration.
    Matching: rule name (or '*'), target glob, and `match` as a
    substring of `site`, the sub-jaxpr path, or the message."""
    for f in findings:
        for s in _REGISTRY:
            if s.rule not in ("*", f.rule):
                continue
            if not fnmatch(f.target, s.target):
                continue
            hay = " ".join((f.site, "/".join(f.path), f.message))
            if s.match and s.match not in hay:
                continue
            f.suppressed = s.reason
            f.suppressed_by = s
            break
    return findings


def stale_suppressions(results: dict, ran_rules=()) -> list:
    """The registry anti-rot audit: a registered suppression whose rule
    ran against a probe its target glob matches, yet matched NO finding,
    is itself a MEDIUM finding — the deviation it documented no longer
    exists and the registration must be deleted (or it will silently
    swallow a future regression). `results` is analyze()'s
    {probe: [Finding]} map AFTER apply_suppressions; `ran_rules` the
    rule names that actually ran (empty = audit every registration)."""
    used = {id(f.suppressed_by) for fs in results.values() for f in fs
            if f.suppressed_by is not None}
    out = []
    for s in _REGISTRY:
        if id(s) in used:
            continue
        if ran_rules and s.rule != "*" and s.rule not in ran_rules:
            continue  # its rule didn't run — nothing proven stale
        probes = [p for p in results if fnmatch(p, s.target)]
        if not probes:
            continue  # its target wasn't analyzed
        out.append(Finding(
            "stale-suppression", Severity.MEDIUM, probes[0],
            "(suppression registry)", (),
            f"suppression (rule={s.rule!r}, target={s.target!r}, "
            f"match={s.match!r}) matched no finding in this run — the "
            f"deviation it documented is gone; delete the registration "
            f"before it swallows a future regression (its reason was: "
            f"{s.reason})"))
    return out


def gate_count(findings: list) -> int:
    """Number of findings that fail the CI gate: HIGH and unsuppressed."""
    return sum(1 for f in findings
               if f.severity == Severity.HIGH and not f.suppressed)
