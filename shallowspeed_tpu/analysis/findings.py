"""Structured findings + the suppression registry.

A `Finding` is one fact the static pass proved about a compiled train
step: which rule fired, how bad it is, which target/entrypoint it lives
in, and the jaxpr provenance (the chain of enclosing sub-jaxprs — pjit /
shard_map / scan / cond / remat — down to the offending equation).

Suppressions are the inline escape hatch: a module that does something
the linter flags ON PURPOSE registers a suppression NEXT TO the code
that causes it, with a mandatory reason string — so the analyzer doubles
as documentation of every deliberate deviation. A suppressed finding is
still reported (with its reason); it just stops counting against the
zero-high-severity gate.

This module is dependency-free (stdlib only) so engine modules can
import it at module scope without dragging jax tracing machinery in.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from fnmatch import fnmatch


class Severity(enum.IntEnum):
    """LOW = informational (always emitted, never gates); MEDIUM = smells
    that deserve a look; HIGH = provable TPU-cleanliness violations — the
    CI gate fails on any unsuppressed HIGH."""

    LOW = 0
    MEDIUM = 1
    HIGH = 2


@dataclass
class Finding:
    rule: str                    # registry name, e.g. "donation"
    severity: Severity
    target: str                  # probe name, e.g. "pipeline_lm:1f1b"
    site: str                    # entrypoint name, e.g. "_step"
    path: tuple = ()             # enclosing sub-jaxpr chain (prim names)
    message: str = ""
    suppressed: str | None = None  # reason string when suppressed

    @property
    def where(self) -> str:
        chain = "/".join(self.path)
        return f"{self.target}::{self.site}" + (f" [{chain}]" if chain
                                                else "")

    def format(self) -> str:
        tag = ("suppressed"
               if self.suppressed else self.severity.name)
        out = f"[{tag:>10}] {self.rule:<18} {self.where}: {self.message}"
        if self.suppressed:
            out += f"\n{'':>13}reason: {self.suppressed}"
        return out


@dataclass
class Suppression:
    rule: str       # rule name or "*"
    target: str     # fnmatch glob over the probe name
    match: str      # substring of the finding's message/site ("" = any)
    reason: str


_REGISTRY: list[Suppression] = []


def suppress(rule: str, target: str = "*", match: str = "",
             reason: str = "") -> Suppression:
    """Register an intentional-deviation suppression. `reason` is
    mandatory — the analyzer's report prints it, so the registration
    site IS the documentation of why the finding is deliberate."""
    assert reason.strip(), (
        "suppress() requires a non-empty reason string — the suppression "
        "doubles as documentation of the intentional finding")
    s = Suppression(rule, target, match, reason)
    _REGISTRY.append(s)
    return s


def registered_suppressions() -> tuple:
    return tuple(_REGISTRY)


def clear_suppressions(keep=()) -> None:
    """Testing hook: reset the registry (optionally to a saved snapshot
    from `registered_suppressions`)."""
    _REGISTRY.clear()
    _REGISTRY.extend(keep)


def apply_suppressions(findings: list) -> list:
    """Mark each finding suppressed by the first matching registration.
    Matching: rule name (or '*'), target glob, and `match` as a
    substring of `site`, the sub-jaxpr path, or the message."""
    for f in findings:
        for s in _REGISTRY:
            if s.rule not in ("*", f.rule):
                continue
            if not fnmatch(f.target, s.target):
                continue
            hay = " ".join((f.site, "/".join(f.path), f.message))
            if s.match and s.match not in hay:
                continue
            f.suppressed = s.reason
            break
    return findings


def gate_count(findings: list) -> int:
    """Number of findings that fail the CI gate: HIGH and unsuppressed."""
    return sum(1 for f in findings
               if f.severity == Severity.HIGH and not f.suppressed)
