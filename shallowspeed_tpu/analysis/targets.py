"""Target probes — the real compiled train steps, instrumented.

A `TargetProbe` builds one engine family at the tiny CPU-friendly
configuration the test suite exercises, runs its public train/eval API
a couple of times (the retrace audit's behavioral probe — same shapes,
fresh data, so a stable cache key must yield exactly one executable),
then captures each jitted entrypoint's jaxpr via `jax.make_jaxpr` on
shape/dtype structs of the live arguments. Rules (`rules.py`) consume
the probe; nothing here judges — it only observes.

Engine imports live inside the builders so `shallowspeed_tpu.analysis`
stays importable without tracing anything.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable

import jax
import numpy as np

from shallowspeed_tpu.analysis.walker import iter_eqns, sub_jaxprs

GiB = 1 << 30
DEFAULT_BUDGET = 16 * GiB  # one v4/v5e-class chip's HBM


def _sds(tree):
    """Shape/dtype skeleton of a pytree of arrays (tracing args that can
    never alias or consume the engine's live buffers)."""
    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(np.shape(l), np.asarray(l).dtype)
        if not hasattr(l, "aval") and not hasattr(l, "dtype")
        else jax.ShapeDtypeStruct(l.shape, l.dtype), tree)


@dataclass
class EntryPoint:
    name: str
    fn: Any                       # the jitted callable
    args: tuple                   # SDS pytrees, one per positional arg
    arg_names: tuple              # for messages, same length as args
    donate: tuple = ()            # arg indices that MUST be donated
    calls: int = 0                # public-API calls the probe ran
    n_compiles_expected: int = 1
    observed_compiles: int | None = None  # _cache_size after exercising
    # arg name -> (lo, hi): measured init/calibration absmax bounds
    # seeding the precision-flow pass's interval propagation
    ranges: dict | None = None


@dataclass
class TargetProbe:
    name: str
    mesh: Any
    compute_dtype: Any            # declared compute dtype (None = f32)
    entrypoints: list = field(default_factory=list)
    hbm_budget: int = DEFAULT_BUDGET
    _jaxprs: dict = field(default_factory=dict)
    _flows: dict = field(default_factory=dict)

    # ---------------------------------------------------- jaxpr access

    def jaxpr_of(self, ep: EntryPoint):
        """The entrypoint's ClosedJaxpr (cached; None if untraceable)."""
        if ep.name not in self._jaxprs:
            try:
                self._jaxprs[ep.name] = jax.make_jaxpr(ep.fn)(*ep.args)
            except Exception as e:  # surfaced by the CLI, not swallowed
                raise RuntimeError(
                    f"tracing {self.name}::{ep.name} failed") from e
        return self._jaxprs[ep.name]

    def walk(self, ep: EntryPoint):
        jaxpr = self.jaxpr_of(ep)
        return iter_eqns(jaxpr) if jaxpr is not None else iter(())

    def jaxpr_scopes(self, ep: EntryPoint):
        """Yield (plain jaxpr, path) for the top jaxpr and every
        sub-jaxpr scope — rules that need per-scope def-use maps (the
        dtype lint) walk scopes instead of flat eqns."""
        top = self.jaxpr_of(ep)
        if top is None:
            return

        def rec(j, path):
            yield j, path
            for eqn in j.eqns:
                for sub in sub_jaxprs(eqn):
                    yield from rec(sub, path + (eqn.primitive.name,))

        yield from rec(top.jaxpr, ())

    def flow(self, ep: EntryPoint):
        """The entrypoint's precision-flow result (`provenance.py`),
        computed once and shared by every rule that reads per-value
        provenance (double-rounding, accumulation, scale pairing,
        range safety)."""
        if ep.name not in self._flows:
            from shallowspeed_tpu.analysis.provenance import \
                flow_entrypoint
            self._flows[ep.name] = flow_entrypoint(self, ep)
        return self._flows[ep.name]

    def top_pjit(self, ep: EntryPoint):
        """The outermost pjit eqn (donation lives there), or None."""
        jaxpr = self.jaxpr_of(ep)
        if jaxpr is not None:
            for eqn in jaxpr.jaxpr.eqns:
                if eqn.primitive.name == "pjit":
                    return eqn
        return None

    def seal(self):
        """Record per-entrypoint compile counts NOW (before any rule's
        `make_jaxpr` could touch caches) — the retrace audit reads this
        snapshot, taken right after the exercise calls."""
        for ep in self.entrypoints:
            size = getattr(ep.fn, "_cache_size", None)
            if size is not None and ep.calls:
                ep.observed_compiles = size()
        return self


# ------------------------------------------------------------ MLP probes


class _SynthDS:
    """Duck-typed stand-in for `data.dataset.Dataset` (only the method
    the fused engines read): deterministic per-batch microbatch stacks."""

    def __init__(self, n_mu, mubs, d_in, d_out, seed):
        self._shape = (n_mu, mubs)
        self._dims = (d_in, d_out)
        self._seed = seed

    def load_mubatch_stack(self, batch_id):
        n_mu, mubs = self._shape
        d_in, d_out = self._dims
        rng = np.random.default_rng([self._seed, batch_id])
        x = rng.standard_normal((n_mu, mubs, d_in)).astype(np.float32)
        y = np.eye(d_out, dtype=np.float32)[
            rng.integers(0, d_out, (n_mu, mubs))]
        return x, y


def build_engine(budget: int = DEFAULT_BUDGET) -> TargetProbe:
    """`engine.FusedDPEngine` — the dp-only fused MLP trainer."""
    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.models.mlp import MLPStage
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.mesh import make_mesh

    sizes, gbs, n_mu, dp = [12, 16, 10], 16, 2, 2
    eng = FusedDPEngine(MLPStage(sizes, 0, 1, batch_size=gbs), SGD(0.1),
                        make_mesh(dp, 1))
    ds = [_SynthDS(n_mu, gbs // dp // n_mu, sizes[0], sizes[-1], r)
          for r in range(dp)]
    for b in range(2):
        eng.train_batch(b, ds)
    x = np.random.default_rng(0).standard_normal(
        (8, sizes[0])).astype(np.float32)
    eng.infer(x)
    eng.infer(x + 1)

    probe = TargetProbe("engine", eng.mesh, None, hbm_budget=budget)
    xs, ys = (jax.ShapeDtypeStruct((dp, n_mu, gbs // dp // n_mu, d),
                                   np.float32)
              for d in (sizes[0], sizes[-1]))
    probe.entrypoints = [
        EntryPoint("_step", eng._step,
                   (_sds(eng.params), _sds(eng.opt_state), xs, ys),
                   ("params", "opt_state", "xs", "ys"),
                   donate=(0, 1), calls=2),
        EntryPoint("_infer", eng._infer,
                   (_sds(eng.params),
                    jax.ShapeDtypeStruct((8, sizes[0]), np.float32)),
                   ("params", "x"), calls=2),
    ]
    return probe.seal()


def build_engine_overlap(budget: int = DEFAULT_BUDGET) -> TargetProbe:
    """`engine.FusedDPEngine(overlap=...)` — the bucketed backward-
    overlapped dp reduction. The `overlap-bucket` rule's live target:
    proves every dp reduction is a registered bucket AND that the
    bucket collectives are dataflow-interleaved with backward compute
    (the acceptance shape for `parallel/overlap.py`)."""
    from shallowspeed_tpu.engine import FusedDPEngine
    from shallowspeed_tpu.models.mlp import MLPStage
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.mesh import make_mesh
    from shallowspeed_tpu.parallel.overlap import OverlapConfig

    sizes, gbs, n_mu, dp = [12, 16, 14, 10], 16, 2, 2
    eng = FusedDPEngine(MLPStage(sizes, 0, 1, batch_size=gbs), SGD(0.1),
                        make_mesh(dp, 1),
                        overlap=OverlapConfig(bucket_mb=0.001))
    ds = [_SynthDS(n_mu, gbs // dp // n_mu, sizes[0], sizes[-1], r)
          for r in range(dp)]
    for b in range(2):
        eng.train_batch(b, ds)

    probe = TargetProbe("engine:overlap", eng.mesh, None,
                        hbm_budget=budget)
    xs, ys = (jax.ShapeDtypeStruct((dp, n_mu, gbs // dp // n_mu, d),
                                   np.float32)
              for d in (sizes[0], sizes[-1]))
    probe.entrypoints = [
        EntryPoint("_step", eng._step,
                   (_sds(eng.params), _sds(eng.opt_state), xs, ys),
                   ("params", "opt_state", "xs", "ys"),
                   donate=(0, 1), calls=2),
    ]
    return probe.seal()


def build_spmd_pipeline(budget: int = DEFAULT_BUDGET) -> TargetProbe:
    """`parallel.SPMDPipelineEngine` — the compiled GPipe MLP step."""
    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.mesh import make_mesh
    from shallowspeed_tpu.parallel.spmd_pipeline import SPMDPipelineEngine

    sizes, gbs, n_mu, dp, pp = [12, 14, 13, 10], 16, 2, 2, 2
    mubs = gbs // dp // n_mu
    eng = SPMDPipelineEngine(sizes, SGD(0.1), make_mesh(dp, pp), n_mu,
                             mubs, gbs)
    ds = [_SynthDS(n_mu, mubs, sizes[0], sizes[-1], r)
          for r in range(dp)]
    for b in range(2):
        eng.train_batch(b, ds)

    probe = TargetProbe("spmd_pipeline", eng.mesh, None,
                        hbm_budget=budget)
    wmax = max(sizes)
    xs = jax.ShapeDtypeStruct((dp, n_mu, mubs, wmax), np.float32)
    ys = jax.ShapeDtypeStruct((dp, n_mu, mubs, sizes[-1]), np.float32)
    probe.entrypoints = [
        EntryPoint("_step", eng._step_fn,
                   (_sds(eng.params), _sds(eng.opt_state), xs, ys),
                   ("params", "opt_state", "xs", "ys"),
                   donate=(0, 1), calls=2),
    ]
    return probe.seal()


# ----------------------------------------------------- transformer probes


def _lm_cfg(**kw):
    from shallowspeed_tpu.models import transformer as T

    base = dict(vocab=64, d_model=32, n_heads=4, n_layers=4, max_seq=32)
    base.update(kw)
    return T.TransformerConfig(**base)


def _lm_batch(seed, b=8, t=16, vocab=64):
    rng = np.random.default_rng(seed)
    tok = rng.integers(0, vocab, (b, t)).astype(np.int32)
    return tok, np.roll(tok, -1, axis=1).astype(np.int32)


def build_gspmd(budget: int = DEFAULT_BUDGET) -> TargetProbe:
    """The GSPMD family via its Megatron-TP subclass on ('dp','tp') —
    placement-annotated params, one jitted step, XLA collectives."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from shallowspeed_tpu.optim import Adam
    from shallowspeed_tpu.parallel.tensor import TensorParallelEngine

    cfg = _lm_cfg(compute_dtype=jnp.bfloat16)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    eng = TensorParallelEngine(cfg, Adam(1e-3), mesh)
    for s in range(2):
        tok, tgt = _lm_batch(s)
        eng.train_batch(tok, tgt)
    tok, tgt = _lm_batch(7)
    eng.eval_loss(tok, tgt)
    eng.eval_loss(tok, tgt)

    probe = TargetProbe("gspmd", mesh, cfg.compute_dtype,
                        hbm_budget=budget)
    data = jax.ShapeDtypeStruct((8, 16), np.int32)
    step = jax.ShapeDtypeStruct((), np.uint32)
    probe.entrypoints = [
        EntryPoint("_step", eng._step_fn,
                   (_sds(eng.params), _sds(eng.opt_state), data, data,
                    step),
                   ("params", "opt_state", "tokens", "targets", "step"),
                   donate=(0, 1), calls=2),
        EntryPoint("_eval", eng._eval_fn,
                   (_sds(eng.params), data, data),
                   ("params", "tokens", "targets"), calls=2),
    ]
    return probe.seal()


def build_pipeline_lm(schedule: str = "gpipe", virtual_pp: int = 1,
                      compute_dtype="bf16",
                      budget: int = DEFAULT_BUDGET) -> TargetProbe:
    """`parallel.PipelineLMEngine` over ('dp','pp') — one probe per
    compiled schedule (gpipe / 1f1b / interleaved 1f1b / ZB-H1)."""
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from shallowspeed_tpu.optim import SGD
    from shallowspeed_tpu.parallel.pipeline_lm import PipelineLMEngine

    dt = jnp.bfloat16 if compute_dtype == "bf16" else None
    cfg = _lm_cfg(compute_dtype=dt)
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "pp"))
    eng = PipelineLMEngine(cfg, SGD(0.1), mesh, n_mubatches=2,
                           schedule=schedule, virtual_pp=virtual_pp)
    for s in range(2):
        tok, tgt = _lm_batch(s)
        eng.train_batch(tok, tgt)
    tok, tgt = _lm_batch(7)
    eng.eval_loss(tok, tgt)
    eng.eval_loss(tok, tgt)

    label = "interleaved" if virtual_pp > 1 else schedule
    probe = TargetProbe(f"pipeline_lm:{label}", mesh, dt,
                        hbm_budget=budget)
    placed = eng.place(tok)
    data = jax.ShapeDtypeStruct(placed.shape, placed.dtype)
    step = jax.ShapeDtypeStruct((), np.uint32)
    probe.entrypoints = [
        EntryPoint("_step", eng._step_fn,
                   (_sds(eng.params), _sds(eng.opt_state), data, data,
                    step),
                   ("params", "opt_state", "tokens", "targets", "step"),
                   donate=(0, 1), calls=2),
        EntryPoint("_eval", eng._eval_fn,
                   (_sds(eng.params), data, data),
                   ("params", "tokens", "targets"), calls=2),
    ]
    return probe.seal()


# ----------------------------------------------------- fp8 training probe


def build_fp8_train(budget: int = DEFAULT_BUDGET) -> TargetProbe:
    """`fp8.Fp8TrainEngine` — the fp8-e4m3 forward-matmul training step
    (ROADMAP item 5). The precision-flow rules' primary target: the
    traced step must prove in-program quantization paired to its scale
    on BOTH dot sides (forward and the hand STE VJP), f32 accumulation,
    in-range converts (the saturating clip), and no compounding
    rounding. `ranges` carries measured calibration stats from the live
    warmup steps, seeding the interval pass."""
    import jax.numpy as jnp  # noqa: F401  (symmetry with other builders)

    from shallowspeed_tpu.fp8 import Fp8TrainEngine
    from shallowspeed_tpu.optim import MomentumSGD

    sizes, bs = [12, 16, 10], 8
    eng = Fp8TrainEngine(sizes, MomentumSGD(0.05, momentum=0.9), seed=0)
    rng = np.random.default_rng(0)

    def batch(i):
        x = rng.standard_normal((bs, sizes[0])).astype(np.float32)
        y = np.eye(sizes[-1], dtype=np.float32)[
            rng.integers(0, sizes[-1], bs)]
        return x, y

    for i in range(2):
        eng.train_batch(*batch(i))
    xe, ye = batch(7)
    eng.eval_loss(xe, ye)
    eng.eval_loss(xe, ye)

    # calibration: measured post-warmup absmax bounds seed the interval
    # propagation (params drift during training — these are the stats
    # the certificate is conditioned on, same contract as the scales)
    pmax = max(float(np.max(np.abs(l))) for l in
               jax.tree_util.tree_leaves(eng.params)) * 4.0
    hist = np.asarray(eng.amax_hist)
    ranges = {
        "params": (-pmax, pmax),
        "x": (-6.0, 6.0),            # standard-normal features, 6 sigma
        "y": (0.0, 1.0),             # one-hot targets
        "amax_hist": (float(hist.min()) / 4.0, float(hist.max()) * 4.0),
    }

    probe = TargetProbe("fp8_train", None, None, hbm_budget=budget)
    x_sds = jax.ShapeDtypeStruct((bs, sizes[0]), np.float32)
    y_sds = jax.ShapeDtypeStruct((bs, sizes[-1]), np.float32)
    probe.entrypoints = [
        EntryPoint("_step", eng._step_fn,
                   (_sds(eng.params), _sds(eng.opt_state),
                    _sds(eng.amax_hist), x_sds, y_sds),
                   ("params", "opt_state", "amax_hist", "x", "y"),
                   donate=(0, 1, 2), calls=2, ranges=ranges),
        EntryPoint("_loss", eng._loss_fn,
                   (_sds(eng.params), _sds(eng.amax_hist), x_sds, y_sds),
                   ("params", "amax_hist", "x", "y"), calls=2,
                   ranges=ranges),
    ]
    return probe.seal()


# ------------------------------------------------------- serving probe


def build_serving_decode(budget: int = DEFAULT_BUDGET) -> TargetProbe:
    """The serving fast-decode tick (`serving/engine._decode_tick`) at
    the full quantized configuration: int8 weights (fused-dequant
    matmul), int8 KV pools, and the paged Pallas flash-decode kernel.
    The `dequant-fusion` rule's live target — the traced tick must
    never materialize a full-size dequantized weight copy — plus the
    standard dtype/memory sweeps over the kernel's sub-jaxpr."""
    import jax.numpy as jnp  # noqa: F401  (symmetry with other builders)

    from shallowspeed_tpu.models import transformer as T
    from shallowspeed_tpu.serving.engine import ServingEngine, _decode_tick

    cfg = T.TransformerConfig(vocab=32, d_model=32, n_heads=4,
                              n_layers=2, max_seq=64)
    eng = ServingEngine(T.init(cfg, seed=0), cfg, n_blocks=8,
                        block_size=8, max_slots=2, prefill_chunk=8,
                        weight_quant="int8", kv_quant="int8",
                        attn_impl="flash")
    eng.submit(np.arange(6, dtype=np.int32) % cfg.vocab, 4)
    eng.run()

    def tick(params, pools, tok, pos, bt, temp, seeds, idx):
        return _decode_tick(params, pools, tok, pos, bt, temp, seeds,
                            idx, cfg=cfg, top_k=0, top_p=0.0,
                            attn="flash")

    s = eng.max_slots
    w = 4
    probe = TargetProbe("serving:decode", None, None, hbm_budget=budget)
    probe.entrypoints = [
        EntryPoint("_decode_tick", tick,
                   (_sds(eng.params), _sds(eng.pools),
                    jax.ShapeDtypeStruct((s,), np.int32),
                    jax.ShapeDtypeStruct((s,), np.int32),
                    jax.ShapeDtypeStruct((s, w), np.int32),
                    jax.ShapeDtypeStruct((s,), np.float32),
                    jax.ShapeDtypeStruct((s,), np.uint32),
                    jax.ShapeDtypeStruct((s,), np.int32)),
                   ("params", "pools", "tok", "pos", "bt", "temp",
                    "seeds", "idx")),
    ]
    return probe.seal()


# ----------------------------------------------------------- the registry

TARGET_BUILDERS: dict[str, Callable] = {
    "engine": build_engine,
    "engine:overlap": build_engine_overlap,
    "spmd_pipeline": build_spmd_pipeline,
    "gspmd": build_gspmd,
    "pipeline_lm:gpipe": lambda budget=DEFAULT_BUDGET:
        build_pipeline_lm("gpipe", budget=budget),
    "pipeline_lm:1f1b": lambda budget=DEFAULT_BUDGET:
        build_pipeline_lm("1f1b", budget=budget),
    "pipeline_lm:interleaved": lambda budget=DEFAULT_BUDGET:
        build_pipeline_lm("1f1b", virtual_pp=2, budget=budget),
    "pipeline_lm:zb": lambda budget=DEFAULT_BUDGET:
        build_pipeline_lm("zb", compute_dtype=None, budget=budget),
    "serving": build_serving_decode,
    "fp8_train": build_fp8_train,
}

# CLI aliases: family names expand to their member probes
TARGET_GROUPS: dict[str, tuple] = {
    "pipeline_lm": ("pipeline_lm:gpipe", "pipeline_lm:1f1b",
                    "pipeline_lm:interleaved"),
    "zb": ("pipeline_lm:zb",),
    "all": tuple(TARGET_BUILDERS),
}


def resolve_targets(name: str) -> tuple:
    if name in TARGET_GROUPS:
        return TARGET_GROUPS[name]
    if name in TARGET_BUILDERS:
        return (name,)
    raise SystemExit(
        f"unknown target {name!r}; pick from "
        f"{sorted((*TARGET_BUILDERS, *TARGET_GROUPS))}")
