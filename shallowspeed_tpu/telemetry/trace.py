"""The span tracer — low-overhead runtime tracing for the engines.

Design constraints, in order:

1. `off` must cost nothing: engines call `tracer().span(...)` on every
   step (the VM on every instruction), so the disabled path is one
   module-global read returning a shared no-op span. No buffers, no
   timestamps, and — critically — **no device fences**: the async
   dispatch pipeline the engines are built around is untouched
   (pinned by tests/test_telemetry.py's no-fence test).
2. `steps` records host wall-clock only. Spans are real (buffered,
   exported) but `Span.fence()` is a no-op, so queued device work is
   never drained — timestamps measure *dispatch*, and only log-point
   spans (which the drivers already synchronize) measure compute.
3. `spans` adds a `jax.block_until_ready` on the arrays handed to
   `Span.fence()` at span exit, so a span's duration brackets the
   DEVICE time of the work dispatched inside it. This serializes
   dispatch at every phase boundary — the honest cost of attributable
   time; the README documents it as the measurement mode.

Export: one `spans.jsonl` line per closed span (append-streamed, so a
killed run keeps its trace) and a Chrome-trace `trace.json`
(`ph: "X"` complete events, microsecond timebase) written by `close()`
— loadable in Perfetto / chrome://tracing with zero TPU tooling.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from pathlib import Path

LEVELS = ("off", "steps", "spans")

# in-memory event buffer cap: the VM emits one span per pipeline
# instruction, so a long spans-level run would otherwise grow the
# buffer without bound — spans.jsonl streams EVERY event to disk and
# is the source of truth for trace.json; the buffer only serves
# same-process consumers (the bubble replay reads the last batch via
# `events_since`, far below this cap)
_BUF_CAP = 200_000


class _NullSpan:
    """Shared do-nothing span: the `off` fast path and the object
    returned for spans opened while tracing is disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def fence(self, *arrays):
        return None

    def set(self, **attrs):
        return None


_NULL_SPAN = _NullSpan()

# phase hooks (round 17, telemetry/profiler.py): when a sampling
# profiler runs it installs a (push, pop) pair here and every span
# enter/exit feeds its NAME into the profiler's cross-thread phase
# registry — so samples landing inside a `step` span are attributable
# without the drivers changing. None when no profiler runs: the cost
# on the hot path is one module-global read per span.
PHASE_HOOKS = None


class Span:
    """One timed region. Duration covers enter -> exit; `fence(arrs)`
    marks arrays whose device completion the exit waits on (at the
    `spans` level only)."""

    __slots__ = ("_tr", "name", "attrs", "_t0", "_fences")

    def __init__(self, tr: "Tracer", name: str, attrs: dict):
        self._tr = tr
        self.name = name
        self.attrs = attrs
        self._t0 = 0.0
        self._fences: tuple = ()

    def fence(self, *arrays) -> None:
        """Block the span exit on these arrays' device completion
        (`spans` level; no-op at `steps`). Call with the step's outputs
        so the span measures compute, not dispatch."""
        if self._tr.level == "spans":
            self._fences += arrays

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)

    def __enter__(self):
        self._t0 = self._tr._clock()
        self._tr._thread_stack().append(self)
        if PHASE_HOOKS is not None:
            PHASE_HOOKS[0](self.name)
        return self

    def __exit__(self, *exc):
        tr = self._tr
        if PHASE_HOOKS is not None:
            PHASE_HOOKS[1](self.name)
        if tr.level == "spans" and self._fences:
            _block(self._fences)
        t1 = tr._clock()
        stack = tr._thread_stack()
        assert stack and stack[-1] is self, (
            "span nesting violated: exiting a span that is not the "
            "innermost open span")
        stack.pop()
        tr._record(self, self._t0, t1, depth=len(stack))
        return False


def _block(arrays):
    import jax

    for a in arrays:
        jax.block_until_ready(a)


class Tracer:
    """Buffering span recorder with streaming JSONL + Chrome export.

    Single-threaded by design (the engines dispatch from one Python
    thread); the lock only guards the JSONL append so background
    threads (prefetch, async save) may also emit spans.
    """

    def __init__(self, trace_dir=None, level: str = "off",
                 clock=time.perf_counter):
        assert level in LEVELS, f"level {level!r} not in {LEVELS}"
        self.level = level
        self.dir = Path(trace_dir) if trace_dir else None
        self._clock = clock
        self._epoch = clock()
        self._local = threading.local()  # per-thread span stacks
        self._events: deque = deque(maxlen=_BUF_CAP)
        self._seq = 0                    # total events ever emitted
        self._counters: dict[str, float] = {}
        # named tracks (round 13): explicit Chrome tids above the span
        # nesting depths, one per serving request — depth-tids stay
        # single digits, so the offset can never collide
        self._next_tid = 1000
        self._lock = threading.Lock()
        self._jsonl = None
        # span-event subscribers (round 12): the live monitor's
        # flight recorder rides here so the incident ring holds the
        # phase spans next to the metrics lines. Called under the
        # emit lock — keep them O(ring append) cheap.
        self.subscribers: list = []
        if self.dir is not None and level != "off":
            self.dir.mkdir(parents=True, exist_ok=True)
            # "w", not "a": each run owns its trace dir (appending a
            # second run would mix two ts epochs into one garbled
            # Perfetto timeline); per-line flushes still mean a killed
            # run keeps everything it emitted
            self._jsonl = (self.dir / "spans.jsonl").open("w")

    def _thread_stack(self) -> list:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    # ------------------------------------------------------------ spans

    def span(self, name: str, **attrs):
        """Open a span; use as a context manager. At `off` this returns
        a shared no-op object (zero allocation beyond the call)."""
        if self.level == "off":
            return _NULL_SPAN
        return Span(self, name, attrs)

    def event(self, name: str, **attrs) -> None:
        """Zero-duration instant event (e.g. 'recompile', 'ckpt')."""
        if self.level == "off":
            return
        self._emit({"name": name, "ph": "i",
                    "ts": round((self._clock() - self._epoch) * 1e6, 1),
                    "args": attrs})

    def now(self) -> float:
        """This tracer's clock (perf_counter by default) — callers that
        record phase boundaries host-side and export them later via
        `complete` must stamp on THIS clock, not time.time."""
        return self._clock()

    def track(self, name: str) -> int:
        """Allocate a named Chrome-trace track and return its tid —
        one per serving request, so each request renders as its own
        named row in Perfetto next to the engine tick spans. Emits the
        thread_name metadata line; returns 0 (the shared depth track)
        when tracing is off."""
        if self.level == "off":
            return 0
        with self._lock:
            tid = self._next_tid
            self._next_tid += 1
        self._emit({"name": "thread_name", "ph": "M", "ts": 0.0,
                    "tid": tid, "args": {"name": name}})
        return tid

    def complete(self, name: str, t0: float, t1: float,
                 tid: int | None = None, **attrs) -> None:
        """Emit an already-closed span (ph "X") directly — the
        lifecycle path, where phase boundaries are recorded host-side
        as they happen and exported when the phase ENDS. `t0`/`t1` are
        on this tracer's clock (`now()`); `tid` targets a named track
        from `track()`."""
        if self.level == "off":
            return
        ev = {"name": name, "ph": "X",
              "ts": round((t0 - self._epoch) * 1e6, 1),
              "dur": round(max(0.0, t1 - t0) * 1e6, 1),
              "args": attrs}
        if tid is not None:
            ev["tid"] = tid
        self._emit(ev)

    def counter(self, name: str, value) -> None:
        """Monotonic/telemetry counter sample (recompiles, HBM bytes)."""
        if self.level == "off":
            return
        self._counters[name] = value
        self._emit({"name": name, "ph": "C",
                    "ts": round((self._clock() - self._epoch) * 1e6, 1),
                    "args": {"value": value}})

    def _record(self, span: Span, t0: float, t1: float, depth: int):
        self._emit({
            "name": span.name, "ph": "X",
            "ts": round((t0 - self._epoch) * 1e6, 1),
            "dur": round((t1 - t0) * 1e6, 1),
            "depth": depth,
            "args": span.attrs,
        })

    def _emit(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)
            self._seq += 1
            if self._jsonl is not None:
                self._jsonl.write(json.dumps(ev) + "\n")
                self._jsonl.flush()
            for fn in self.subscribers:
                try:
                    fn(ev)
                except Exception:
                    pass  # a monitor bug must not kill the traced run

    # ----------------------------------------------------------- export

    @property
    def event_count(self) -> int:
        """Total events emitted so far (monotonic; survives buffer
        eviction — pair with `events_since` to read a window)."""
        return self._seq

    @property
    def events(self) -> list[dict]:
        """The buffered events (the most recent `_BUF_CAP`; the full
        stream lives in spans.jsonl). Snapshotted under the lock —
        background threads (prefetch, async save) may emit
        concurrently, and iterating a mutating deque raises."""
        with self._lock:
            return list(self._events)

    def events_since(self, seq: int) -> list[dict]:
        """Events emitted at or after sequence number `seq` (from
        `event_count`) that are still buffered — the O(window) way to
        read e.g. one batch's spans without rescanning the run."""
        with self._lock:
            buf = list(self._events)
            n_evicted = self._seq - len(buf)
        skip = max(0, seq - n_evicted)
        return buf[skip:] if skip else buf

    def spans_named(self, name: str) -> list[dict]:
        return [e for e in self.events
                if e.get("ph") == "X" and e["name"] == name]

    @staticmethod
    def _chrome_event(e: dict) -> dict:
        # explicit tid (a named lifecycle track) wins over the span
        # nesting depth
        ev = {"name": e["name"], "ph": e["ph"], "ts": e["ts"],
              "pid": 0, "tid": e.get("tid", e.get("depth", 0)),
              "args": e.get("args", {})}
        if e["ph"] == "X":
            ev["dur"] = e["dur"]
        return ev

    def chrome_trace(self) -> dict:
        """The trace in Chrome format (Perfetto-loadable). Sourced from
        the streamed spans.jsonl when a trace dir is configured (the
        COMPLETE stream — the RAM buffer is bounded), else from the
        buffer. Span depth maps to tid so nesting renders as the usual
        flame layout; attrs ride in `args`."""
        src: list = self.events
        if self.dir is not None:
            path = self.dir / "spans.jsonl"
            if path.exists():
                with self._lock:
                    if self._jsonl is not None:
                        self._jsonl.flush()
                src = [json.loads(line)
                       for line in path.read_text().splitlines()
                       if line.strip()]
        return {"traceEvents": [self._chrome_event(e) for e in src],
                "displayTimeUnit": "ms"}

    def close(self) -> None:
        """Flush the JSONL stream and write `trace.json` (Chrome)."""
        trace = (self.chrome_trace()
                 if self.dir is not None and self.level != "off"
                 else None)
        if self._jsonl is not None:
            with self._lock:
                self._jsonl.close()
                self._jsonl = None
        if trace is not None:
            (self.dir / "trace.json").write_text(json.dumps(trace))


# ------------------------------------------------------- global tracer

_TRACER = Tracer(level="off")


def configure(trace_dir=None, level: str = "off") -> Tracer:
    """Install (and return) the process-global tracer the engines emit
    into. Drivers call this once from the CLI flags; tests swap it
    freely (the previous tracer is closed)."""
    global _TRACER
    _TRACER.close()
    _TRACER = Tracer(trace_dir=trace_dir, level=level)
    return _TRACER


def tracer() -> Tracer:
    """The active process-global tracer (default: level 'off')."""
    return _TRACER
