"""Live HBM accounting — runtime cross-check of the static memory rule,
per-owner decomposition, and leak/drift detection (the memory
observatory, round 20).

`analysis/rules.py`'s memory-highwater rule predicts a step's
live-buffer peak from the jaxpr; this module samples what is ACTUALLY
resident so every traced run checks the prediction:

- `live_hbm_high_water()`: per-device resident bytes summed over
  `jax.live_arrays()`'s addressable shards — the steady-state
  footprint (params, optimizer state, staged batches) between steps.
- `device_memory_stats()`: the backend allocator's own view
  (`Device.memory_stats()`: bytes_in_use / peak_bytes_in_use) where
  the platform provides one (TPU does; CPU returns nothing) — this is
  the only source that sees TRANSIENTS inside a compiled step.

The cross-check a run report makes: live steady-state bytes must stay
under the static prediction (which includes the step's transients and
is deliberately conservative — `walker.peak_bytes` ignores fusion and
donation). A live sample EXCEEDING static + tolerance means the
estimator lost track of real buffers — the failure mode the gate
exists to catch.

The OWNERSHIP REGISTRY decomposes the live total: engines register
their long-lived pytrees (params, optimizer state, KV block pools,
amax history, draft buffers) as zero-arg resolvers, and
`per_owner_accounting()` attributes each live array to the first owner
whose resolved tree contains it. What no owner claims is the
`untracked` residual — the leak alarm: a residual that grows across
windows is memory the process holds but nothing accounts for.
Resolvers (not pytrees) because the interesting trees ROTATE — pools
are donated through every compiled tick, optimizer state is replaced
every step — so a registered snapshot would both pin dead buffers
alive and go stale within one iteration.

`MemoryWatch` turns the sampled series into `telemetry/anomaly`
verdicts: `mem_drift` (robust EWMA z-spike in resident device bytes or
host RSS) and `mem_leak` (sustained growth over `patience` consecutive
observations — the slope detector a z-score misses because a slow leak
drags the EWMA mean along with it).
"""

from __future__ import annotations

import jax


def live_hbm_high_water() -> dict:
    """Resident bytes per device over all live jax.Arrays; returns
    {"per_device": {dev_str: bytes}, "max_device_bytes", "n_arrays"}.
    Deleted/donated buffers are excluded by construction (donation
    makes the input array non-live). Committed multi-device arrays
    contribute each shard to its own device."""
    per_dev: dict[str, int] = {}
    n = 0
    for arr in jax.live_arrays():
        try:
            shards = arr.addressable_shards
        except Exception:
            continue
        n += 1
        for sh in shards:
            d = str(sh.device)
            per_dev[d] = per_dev.get(d, 0) + int(sh.data.nbytes)
    return {"per_device": per_dev,
            "max_device_bytes": max(per_dev.values(), default=0),
            "n_arrays": n}


def device_memory_stats() -> dict:
    """Allocator stats per device where the backend exposes them
    ({} on CPU). Keys kept verbatim from `Device.memory_stats()`."""
    out = {}
    for d in jax.local_devices():
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        if st:
            out[str(d)] = {k: int(v) for k, v in st.items()
                           if isinstance(v, (int, float))}
    return out


def static_peak_bytes(fn, *args) -> int:
    """The static live-buffer high-water estimate for one entrypoint —
    the same number `analysis/rules.py`'s memory rule reports (traced
    on ShapeDtypeStructs; nothing executes)."""
    from shallowspeed_tpu.analysis.walker import peak_bytes

    closed = jax.make_jaxpr(fn)(*args)
    return peak_bytes(closed.jaxpr)


def cross_check(live_max: int, static_peak: int,
                tolerance: float = 1.05) -> dict:
    """live steady-state vs static prediction: ok iff
    live <= static * tolerance (static includes in-step transients, so
    steady-state residency above it means the estimator lost buffers)."""
    ok = live_max <= static_peak * tolerance
    return {"live_bytes": int(live_max), "static_bytes": int(static_peak),
            "ratio": round(live_max / max(static_peak, 1), 4),
            "within_bound": bool(ok)}


# ------------------------------------------------- ownership registry
#
# name -> zero-arg resolver returning a pytree (or None when the owner
# has nothing resident yet). Module-global on purpose: the registry is
# observability state like the chaos plan or the metrics monitor, and
# a driver's engines, pools and optimizer state all live in one
# process. Resolvers keep it weak — the registry holds no array refs,
# so registering an owner never extends a buffer's lifetime.

_OWNERS: dict[str, object] = {}


def register_owner(name: str, resolve) -> None:
    """Register (or replace) a memory owner. `resolve` is a zero-arg
    callable returning the owner's CURRENT pytree of jax.Arrays — it is
    called fresh at every accounting point, so donated/rotated buffers
    resolve to their latest incarnation. Return None (or raise) to
    report nothing this window."""
    if not callable(resolve):
        raise TypeError(f"register_owner({name!r}): resolver must be "
                        f"callable, got {type(resolve).__name__}")
    _OWNERS[str(name)] = resolve


def unregister_owner(name: str) -> None:
    _OWNERS.pop(str(name), None)


def clear_owners() -> None:
    """Drop every registered owner (test isolation / driver teardown)."""
    _OWNERS.clear()


def registered_owners() -> tuple:
    return tuple(_OWNERS)


def _live_by_id() -> dict[int, "jax.Array"]:
    """id(arr) -> arr over the live set. Identity (not content) keyed:
    attribution must match the EXACT objects an owner resolves, and two
    owners resolving the same array must not double-count it."""
    out = {}
    for arr in jax.live_arrays():
        out[id(arr)] = arr
    return out


def _shard_bytes(arr) -> int:
    try:
        return sum(int(sh.data.nbytes) for sh in arr.addressable_shards)
    except Exception:
        return 0


def per_owner_accounting() -> dict:
    """Decompose total resident bytes (summed over every live array's
    addressable shards — the all-device total, not the per-device max)
    into per-owner contributions plus the unclaimed residual:

        {"owners": {name: bytes}, "tracked_bytes", "untracked_bytes",
         "live_bytes", "n_live_arrays"}

    Each live array is claimed at most once (first registered owner
    wins), so `sum(owners.values()) == tracked_bytes <= live_bytes` and
    `untracked_bytes >= 0` by construction. Leaves an owner resolves
    that are NOT live (stale references, donated-away buffers) cost 0 —
    the accounting never invents bytes the process doesn't hold."""
    live = _live_by_id()
    live_bytes = sum(_shard_bytes(a) for a in live.values())
    claimed: set[int] = set()
    owners: dict[str, int] = {}
    for name, resolve in _OWNERS.items():
        try:
            tree = resolve()
        except Exception:
            tree = None
        total = 0
        if tree is not None:
            for leaf in jax.tree_util.tree_leaves(tree):
                key = id(leaf)
                if key in live and key not in claimed:
                    claimed.add(key)
                    total += _shard_bytes(live[key])
        owners[name] = total
    tracked = sum(owners.values())
    return {"owners": owners, "tracked_bytes": int(tracked),
            "untracked_bytes": int(live_bytes - tracked),
            "live_bytes": int(live_bytes), "n_live_arrays": len(live)}


def top_live_arrays(k: int = 5) -> list[dict]:
    """The k largest live arrays — the first thing to read in an OOM
    dump. Each entry carries shape/dtype/bytes plus the owning
    registry name ("untracked" when nothing claims it)."""
    live = _live_by_id()
    owner_of: dict[int, str] = {}
    for name, resolve in _OWNERS.items():
        try:
            tree = resolve()
        except Exception:
            continue
        if tree is None:
            continue
        for leaf in jax.tree_util.tree_leaves(tree):
            owner_of.setdefault(id(leaf), name)
    rows = []
    for key, arr in live.items():
        nb = _shard_bytes(arr)
        try:
            shape = list(arr.shape)
            dtype = str(arr.dtype)
        except Exception:
            shape, dtype = None, None
        rows.append({"shape": shape, "dtype": dtype, "nbytes": nb,
                     "owner": owner_of.get(key, "untracked")})
    rows.sort(key=lambda r: r["nbytes"], reverse=True)
    return rows[:max(0, int(k))]


def host_rss_bytes() -> int:
    """Host resident set size, stdlib-only: /proc/self/status VmRSS
    where procfs exists (Linux), else getrusage peak (ru_maxrss is KiB
    on Linux semantics, bytes on macOS — close enough for a trend
    series; the detector watches deltas, not absolutes). 0 when
    neither source works."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        import sys

        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return int(rss) if sys.platform == "darwin" else int(rss) * 1024
    except Exception:
        return 0


def forensics(top_k: int = 8) -> dict:
    """The memory flight-dump payload: per-owner decomposition, the
    top-k largest live arrays, backend allocator stats, and host RSS —
    everything host-side, safe to call from an OOM handler (allocates
    no device memory)."""
    return {"accounting": per_owner_accounting(),
            "top_arrays": top_live_arrays(top_k),
            "device_stats": device_memory_stats(),
            "host_rss_bytes": host_rss_bytes()}


class MemoryWatch:
    """Steady-state leak/drift detector over resident-bytes series.

    Two complementary detectors per series (device-resident bytes and
    host RSS, fed by the caller each log window):

    - `mem_drift`: robust EWMA z-spike (`telemetry/anomaly.RobustEWMA`)
      — a step change in residency (a buffer that should have been
      freed and wasn't, a recompile that doubled an arena).
    - `mem_leak`: the slope detector — residency grew by more than
      `growth_frac` in EACH of `patience` consecutive observations. A
      slow leak never z-spikes (the EWMA mean tracks it), but it
      cannot hide from a monotone-growth run.

    Verdicts carry the `telemetry/anomaly` shape, so the monitor's
    flight-recorder and the GuardPolicy (`mem_leak`/`mem_drift`
    fields) treat them exactly like training-health verdicts."""

    def __init__(self, spike_z: float = 6.0, patience: int = 6,
                 growth_frac: float = 0.01, alpha: float = 0.05,
                 warmup: int = 8):
        from shallowspeed_tpu.telemetry.anomaly import RobustEWMA

        self.spike_z = float(spike_z)
        self.patience = int(patience)
        self.growth_frac = float(growth_frac)
        self._ewma = {"device": RobustEWMA(alpha, warmup),
                      "host_rss": RobustEWMA(alpha, warmup)}
        self._last: dict[str, float] = {}
        self._runs: dict[str, int] = {}
        self._leak_reported: set[str] = set()

    def _observe_series(self, step: int, name: str, x: float) -> list:
        from shallowspeed_tpu.telemetry.anomaly import Verdict

        out = []
        z = self._ewma[name].update(x)
        if z is not None and z > self.spike_z:
            out.append(Verdict(
                "mem_drift", step,
                detail=f"{name} resident {x / (1 << 20):.1f} MiB is "
                       f"{z:.1f} robust sigmas above its EWMA "
                       f"{self._ewma[name].mean / (1 << 20):.1f} MiB"))
        last = self._last.get(name)
        self._last[name] = x
        if last is not None and last > 0 \
                and x > last * (1.0 + self.growth_frac):
            run = self._runs.get(name, 0) + 1
            self._runs[name] = run
            if run >= self.patience and name not in self._leak_reported:
                self._leak_reported.add(name)
                out.append(Verdict(
                    "mem_leak", step, severity="error",
                    detail=f"{name} residency grew >"
                           f"{self.growth_frac:.1%} per window for "
                           f"{run} consecutive windows (now "
                           f"{x / (1 << 20):.1f} MiB)"))
        elif last is not None:
            self._runs[name] = 0
            self._leak_reported.discard(name)
        return out

    def observe(self, step: int, device_bytes=None,
                rss_bytes=None) -> list:
        """Feed one log window's samples; returns anomaly Verdicts
        (possibly empty). Either series may be None (CPU runs have no
        allocator stats; tests may feed only one)."""
        out = []
        if device_bytes is not None:
            out.extend(self._observe_series(step, "device",
                                            float(device_bytes)))
        if rss_bytes is not None and rss_bytes > 0:
            out.extend(self._observe_series(step, "host_rss",
                                            float(rss_bytes)))
        return out
