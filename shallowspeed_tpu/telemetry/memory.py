"""Live HBM accounting — runtime cross-check of the static memory rule.

`analysis/rules.py`'s memory-highwater rule predicts a step's
live-buffer peak from the jaxpr; this module samples what is ACTUALLY
resident so every traced run checks the prediction:

- `live_hbm_high_water()`: per-device resident bytes summed over
  `jax.live_arrays()`'s addressable shards — the steady-state
  footprint (params, optimizer state, staged batches) between steps.
- `device_memory_stats()`: the backend allocator's own view
  (`Device.memory_stats()`: bytes_in_use / peak_bytes_in_use) where
  the platform provides one (TPU does; CPU returns nothing) — this is
  the only source that sees TRANSIENTS inside a compiled step.

The cross-check a run report makes: live steady-state bytes must stay
under the static prediction (which includes the step's transients and
is deliberately conservative — `walker.peak_bytes` ignores fusion and
donation). A live sample EXCEEDING static + tolerance means the
estimator lost track of real buffers — the failure mode the gate
exists to catch.
"""

from __future__ import annotations

import jax


def live_hbm_high_water() -> dict:
    """Resident bytes per device over all live jax.Arrays; returns
    {"per_device": {dev_str: bytes}, "max_device_bytes", "n_arrays"}.
    Deleted/donated buffers are excluded by construction (donation
    makes the input array non-live). Committed multi-device arrays
    contribute each shard to its own device."""
    per_dev: dict[str, int] = {}
    n = 0
    for arr in jax.live_arrays():
        try:
            shards = arr.addressable_shards
        except Exception:
            continue
        n += 1
        for sh in shards:
            d = str(sh.device)
            per_dev[d] = per_dev.get(d, 0) + int(sh.data.nbytes)
    return {"per_device": per_dev,
            "max_device_bytes": max(per_dev.values(), default=0),
            "n_arrays": n}


def device_memory_stats() -> dict:
    """Allocator stats per device where the backend exposes them
    ({} on CPU). Keys kept verbatim from `Device.memory_stats()`."""
    out = {}
    for d in jax.local_devices():
        try:
            st = d.memory_stats()
        except Exception:
            st = None
        if st:
            out[str(d)] = {k: int(v) for k, v in st.items()
                           if isinstance(v, (int, float))}
    return out


def static_peak_bytes(fn, *args) -> int:
    """The static live-buffer high-water estimate for one entrypoint —
    the same number `analysis/rules.py`'s memory rule reports (traced
    on ShapeDtypeStructs; nothing executes)."""
    from shallowspeed_tpu.analysis.walker import peak_bytes

    closed = jax.make_jaxpr(fn)(*args)
    return peak_bytes(closed.jaxpr)


def cross_check(live_max: int, static_peak: int,
                tolerance: float = 1.05) -> dict:
    """live steady-state vs static prediction: ok iff
    live <= static * tolerance (static includes in-step transients, so
    steady-state residency above it means the estimator lost buffers)."""
    ok = live_max <= static_peak * tolerance
    return {"live_bytes": int(live_max), "static_bytes": int(static_peak),
            "ratio": round(live_max / max(static_peak, 1), 4),
            "within_bound": bool(ok)}
