"""Bench-trajectory regression gate.

`BENCH_r*.json` is machine-written every round but was never
machine-read — a slow drift in throughput or MFU would only be caught
by a human rereading the trajectory. `python -m
shallowspeed_tpu.telemetry --regress BENCH_*.json` (wired into
pre-commit) reads the whole trajectory and FAILS when the newest
round's headline metrics drop below the prior rounds by more than a
noise band.

Noise bands: bench.py (round 8) records per-side spread diagnostics —
`(max-min)/median` over its interleaved measurement rounds — in every
BENCH line from r06 on. The gate derives each metric's band as
`max(floor, K_SPREAD * max recorded spread)` so a noisy host widens
its own tolerance instead of crying wolf; the floors come from this
host's measured behavior (BASELINE.md documents ±7% wall-clock swings
under load for CPU-side numbers; the bench done-bar is ±2% on MFU, so
MFU gets a tight floor). Rounds r01–r05 predate the spread fields and
are covered by the floors alone.

Comparison: the LAST round's value vs the MEDIAN of all prior rounds
that carry the metric (median, not max — one lucky round must not
ratchet the bar above the machine's honest rate). Metrics where
higher is better throughout.
"""

from __future__ import annotations

import json
from pathlib import Path

K_SPREAD = 3.0  # band = max(floor, K_SPREAD * max recorded spread)

# metric -> (floor band, key into parsed["spread"] when recorded)
METRICS = {
    "value": (0.15, "tpu"),            # raw samples/sec: host-load prone
    "vs_baseline": (0.12, "tpu"),      # ratio, but both sides CPU-noisy
    "transformer_mfu": (0.05, None),   # fused on-chip: the ±2% done-bar
    "big_model_mfu": (0.05, None),
    # serving decode throughput (round 11, bench.py offered-load
    # sweep): per-tick dispatch on a CPU host — wall-clock-noisy like
    # `value`, plus scheduler overhead, so a wide floor; rounds before
    # r07 lack the metric and pass vacuously
    "serving_tok_per_sec": (0.35, None),
    # spec-on serving headline (round 14, the spec-on/off sweep):
    # same dispatch noise as the spec-off number, same wide floor;
    # additionally sensitive to the n-gram proposer's acceptance on
    # the bench's templated prompts — a drop here means speculation
    # stopped paying, which is exactly what the gate should catch
    "serving_spec_tok_per_sec": (0.35, None),
    # fleet router headline (round 15, bench.py's 2-replica in-process
    # sweep): the serving dispatch noise PLUS the router's host-side
    # polling/scoring — a drop here with serving_tok_per_sec flat
    # means routing overhead grew; rounds before r15 pass vacuously
    "fleet_tok_per_sec": (0.35, None),
    # fp8 attribution gate (round 18, bench.py bench_fp8): ratio of
    # the bf16 baseline's attrib_mxu_frac to the fp8-on case's — the
    # quantized-dot pricing must keep it > 1. Mostly deterministic
    # (jaxpr-derived rooflines; the calibrated flops/hbm rate ratio
    # moves it a little per host), so a tight floor; rounds before
    # r18 lack the metric and pass vacuously
    "fp8_mxu_shrink": (0.10, None),
    # prefix-caching fleet headline (round 19, bench.py bench_prefix:
    # the 2-replica sticky-routing shared-prompt sweep, prefix cache
    # on): the same dispatch noise as fleet_tok_per_sec plus the
    # cache-hit admission path — a drop here with fleet_tok_per_sec
    # flat means prefix caching or sticky routing stopped paying;
    # rounds before r19 lack the metric and pass vacuously
    "prefix_tok_per_sec": (0.35, None),
    # serving capacity (round 20, the memory observatory): generated
    # tokens per peak live KV block over bench's serving sweep — how
    # much decode work each resident block bought. A drop with
    # serving_tok_per_sec flat means residency grew (blocks pinned
    # longer, eviction stopped paying, or admission overcommitting);
    # same dispatch noise as the throughput numbers, so the same wide
    # floor. Rounds before r20 lack the metric and pass vacuously.
    "serving_capacity_tok_per_blk": (0.35, None),
}


def load_trajectory(paths) -> list[dict]:
    """Parsed bench entries sorted by round number `n`. Accepts file
    paths and directories (scanned for BENCH_*.json)."""
    files: list[Path] = []
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            files.extend(sorted(p.glob("BENCH_*.json")))
        else:
            files.append(p)
    entries = []
    for f in files:
        rec = json.loads(Path(f).read_text())
        parsed = rec.get("parsed")
        if not isinstance(parsed, dict):
            continue
        entries.append({"n": int(rec.get("n", 0)), "path": str(f),
                        "parsed": parsed})
    entries.sort(key=lambda e: e["n"])
    return entries


def _median(vals):
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def _band(metric: str, entries) -> float:
    floor, spread_key = METRICS[metric]
    if spread_key is None:
        return floor
    spreads = []
    for e in entries:
        sp = e["parsed"].get("spread")
        if isinstance(sp, dict) and isinstance(sp.get(spread_key),
                                               (int, float)):
            spreads.append(float(sp[spread_key]))
    return max(floor, K_SPREAD * max(spreads)) if spreads else floor


def check_trajectory(entries) -> tuple[list[str], list[str]]:
    """(problems, report_lines) for one trajectory. Empty problems =
    the gate passes. Needs >= 2 entries carrying a metric to judge it;
    a trajectory of 0/1 entries passes vacuously."""
    problems: list[str] = []
    report: list[str] = []
    if len(entries) < 2:
        return problems, [f"{len(entries)} bench round(s) — nothing to "
                          f"compare"]
    last = entries[-1]
    prior = entries[:-1]
    for metric in METRICS:
        cur = last["parsed"].get(metric)
        hist = [e["parsed"][metric] for e in prior
                if isinstance(e["parsed"].get(metric), (int, float))]
        if not isinstance(cur, (int, float)) or not hist:
            continue
        ref = _median(hist)
        band = _band(metric, entries)
        drop = (ref - cur) / ref if ref > 0 else 0.0
        verdict = "OK" if drop <= band else "REGRESSION"
        report.append(
            f"{metric:<18} r{last['n']:02d}={cur:<12.4g} "
            f"median(prior {len(hist)})={ref:<12.4g} "
            f"drop={drop:+7.2%}  band={band:.0%}  {verdict}")
        if drop > band:
            problems.append(
                f"{metric}: r{last['n']:02d} value {cur:.6g} is "
                f"{drop:.1%} below the prior-round median {ref:.6g} "
                f"(noise band {band:.0%}) — {last['path']}")
    if not report:
        report.append("no shared metrics across rounds")
    return problems, report


def main(paths) -> int:
    entries = load_trajectory(paths)
    problems, report = check_trajectory(entries)
    print(f"bench trajectory: {len(entries)} round(s) "
          f"({', '.join('r%02d' % e['n'] for e in entries)})")
    for line in report:
        print("  " + line)
    for p in problems:
        print("REGRESSION: " + p)
    print("regress gate: " + ("FAIL" if problems else "OK"))
    return 1 if problems else 0
