"""Live telemetry plane — what the run looks like *while it runs*.

Everything before this module reduced a FINISHED metrics JSONL
(`--goodput`, `report.request_summary`); an operator watching a
serving fleet, the elastic supervisor deciding whether a child is
healthy, or an MPMD-era controller rebalancing stages needs the same
answers while the process is alive. Four parts:

- **Streaming aggregation** (`sketch.LogHistogram`): p50/p95/p99 over
  step time, ttft/tpot, tok/s, queue depth and free blocks in constant
  memory, fed from `metrics.StepRates` (exact pause-excluded window
  rates) and the schema-v6 ``"request"``/``"generate"`` lines. The
  sketches serialize into the JSONL as periodic schema-v7
  ``"monitor"`` snapshot events and MERGE across processes/stanzas —
  `--goodput` cross-checks the merged sketch quantiles against its
  exact offline percentiles (same nearest-rank rule, so they agree to
  the sketch's documented rel_err).
- **Live endpoints** (`StatusServer`): a stdlib ``http.server`` behind
  ``--monitor-port`` on the drivers and the elastic supervisor —
  ``/status.json`` (quantiles, goodput-so-far, health verdict,
  queue/alloc state, last fault, active alerts) and ``/metrics`` in
  Prometheus text exposition format. ``python -m
  shallowspeed_tpu.telemetry --live f.jsonl`` tails a growing file and
  renders the same view for endpoint-less runs.
- **SLO burn-rate alerts** (`parse_slos` + the per-rule dual-window
  evaluator): declarative SLOs (``--slo
  'ttft_p95_ms<500,availability>0.99'``) evaluated over a fast and a
  slow window; an alert fires only when BOTH windows burn error
  budget faster than the threshold (the multiwindow rule that kills
  both flavors of false page: a blip trips the fast window but not
  the slow, a slow bleed trips the slow but resolved blips keep the
  fast window clean). Alerts land as schema-v7 ``"alert"`` events and
  reach `ServingEngine.on_alert` (load shedding, opt-in).
- **Anomaly flight recorder** (`FlightRecorder`): a ring of the last N
  full-resolution lines (step/tick/request/ledger + tracer spans)
  dumped to ``flightrec_<step>.json`` when an anomaly verdict fires, a
  chaos fault stamps, or an SLO alert trips — the forensics AROUND the
  incident, not a summary after it.

One ingestion path: `Monitor.note_line(rec)` accepts exactly the dicts
`metrics.MetricsLogger` writes, so the in-process wiring (the logger
forwards every line), the `--live` tailer, and the supervisor's
aggregation (tailing the child's ledger file across restarts) are the
same code — live and offline can only disagree by the sketch error.

Heavier deps (jax) never load here: pure stdlib, like `sketch`.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from collections import deque
from pathlib import Path

from shallowspeed_tpu.telemetry.sketch import LogHistogram, MetricSketches

# sketch names the monitor maintains; anything can be observed, these
# are the documented core set
CORE_SKETCHES = ("step_ms", "ttft_ms", "tpot_ms", "tok_s",
                 "queue_depth", "free_blocks")

# worst-K exemplars the monitor keeps per latency metric: the request
# ids behind the tail quantile, so a fleet view can name WHICH request
# (on which replica) is burning an SLO instead of just how badly
EXEMPLAR_METRICS = ("ttft_ms", "tpot_ms")
EXEMPLAR_K = 5

# native-Prometheus-histogram bucket ladder (round 16): a FIXED,
# data-independent {1, 2.5, 5} x 10^k grid so every replica exports
# the same `le` boundaries — which is the whole point: cumulative
# bucket counts SUM across replicas, so fleet quantiles computed by
# histogram_quantile() in Prometheus/Grafana are correct, where
# averaging the pre-computed per-replica quantile labels of the
# summary export is not. Spans sub-ms ttft to multi-minute e2e; the
# counts at each boundary come from the log-bucketed sketch at its
# documented rel_err.
HIST_LE = tuple(m * 10.0 ** k for k in range(-1, 6)
                for m in (1.0, 2.5, 5.0))

# the cap on retained in-flight lifecycle accumulations (one dict per
# live request id) — a monitor on a long-lived replica must stay O(1)
LIFECYCLE_CAP = 1024


def prom_histogram_lines(base: str, sk: LogHistogram,
                         label: str = "",
                         type_line: bool = True) -> list[str]:
    """Render one sketch as a native Prometheus histogram
    (`<base>_hist_bucket{le=...}` cumulative counts + `_sum`/`_count`)
    on the shared HIST_LE ladder. `label` is an optional extra label
    clause (e.g. 'replica="r0",') spliced before `le`; pass
    `type_line=False` for every series after the first of one metric
    (the exposition format wants ONE # TYPE per metric name)."""
    lines = [f"# TYPE {base}_hist histogram"] if type_line else []
    for le in HIST_LE:
        lines.append(f'{base}_hist_bucket{{{label}le="{le:g}"}} '
                     f"{sk.count_le(le)}")
    lines.append(f'{base}_hist_bucket{{{label}le="+Inf"}} {sk.n}')
    if label:
        lines.append(f"{base}_hist_sum{{{label.rstrip(',')}}} "
                     f"{sk.total:.6g}")
        lines.append(f"{base}_hist_count{{{label.rstrip(',')}}} "
                     f"{sk.n}")
    else:
        lines.append(f"{base}_hist_sum {sk.total:.6g}")
        lines.append(f"{base}_hist_count {sk.n}")
    return lines


class PortInUseError(OSError):
    """--monitor-port names a port this process cannot bind."""


def prom_escape(value) -> str:
    """Prometheus text-exposition label-value escaping (backslash,
    double quote, newline) — replica names are operator input and must
    not be able to break the /metrics parse."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


# --------------------------------------------------------------- SLOs


_SLO_RE = re.compile(r"^\s*([a-zA-Z0-9_]+)\s*([<>])\s*"
                     r"([0-9]*\.?[0-9]+(?:[eE][+-]?[0-9]+)?)\s*$")
_QUANT_RE = re.compile(r"^(.*?)_p([0-9]{1,2})(_[a-z0-9]+)?$")


class SloRule:
    """One declarative SLO plus its dual-window burn-rate state.

    Two shapes:

    - quantile rule (``ttft_p95_ms<500``): every observation of the
      underlying sketch (here ``ttft_ms``) is good iff it satisfies
      the threshold; the error budget is the quantile's complement
      (p95 -> 5% of observations may be bad). Burn rate over a window
      = bad_fraction / budget — burn 1.0 exactly spends the budget,
      burn 10 exhausts it 10x too fast.
    - scalar rule (``availability>0.99``): fed downtime seconds
      (supervisor restart stamps); burn = downtime_in_window /
      (window * (1 - target)).

    An alert fires when BOTH the fast and the slow window exceed the
    burn threshold, at ``critical`` when both exceed the critical
    threshold; it resolves when either window recovers.
    """

    def __init__(self, spec: str, fast_s: float = 60.0,
                 slow_s: float = 600.0, warn_burn: float = 2.0,
                 critical_burn: float = 10.0, min_count: int = 5):
        m = _SLO_RE.match(spec)
        if not m:
            raise ValueError(
                f"bad SLO {spec!r}: want 'metric<value' or "
                f"'metric>value' (e.g. ttft_p95_ms<500, "
                f"availability>0.99)")
        self.spec = spec.strip()
        self.metric, self.op = m.group(1), m.group(2)
        self.threshold = float(m.group(3))
        self.fast_s, self.slow_s = float(fast_s), float(slow_s)
        self.warn_burn, self.critical_burn = (float(warn_burn),
                                              float(critical_burn))
        self.min_count = int(min_count)
        qm = _QUANT_RE.match(self.metric)
        if self.metric == "availability":
            self.sketch = None
            self.q = None
            if self.op != ">" or not 0.0 < self.threshold < 1.0:
                raise ValueError(f"bad SLO {spec!r}: availability "
                                 f"takes '>frac' with frac in (0, 1)")
            self.budget = 1.0 - self.threshold
        elif qm:
            self.sketch = qm.group(1) + (qm.group(3) or "")
            self.q = int(qm.group(2))
            if not 0 < self.q < 100:
                raise ValueError(f"bad SLO {spec!r}: quantile must be "
                                 f"in (0, 100)")
            self.budget = max(1.0 - self.q / 100.0, 1e-6)
        else:
            raise ValueError(
                f"bad SLO {spec!r}: metric must be 'availability' or "
                f"'<sketch>_pNN[_unit]' over one of the monitor "
                f"sketches (e.g. {', '.join(CORE_SKETCHES)})")
        # (t, bad_count, total_count) for quantile rules;
        # (t, down_seconds, 0) for the availability rule
        self._events: deque = deque()
        self.state: str | None = None      # None | "warn" | "critical"
        self.last_value: float | None = None

    # ------------------------------------------------------------ feed

    def record(self, value: float, now: float, count: int = 1) -> None:
        """One observation of this rule's underlying sketch metric."""
        good = (value < self.threshold if self.op == "<"
                else value > self.threshold)
        self.last_value = float(value)
        self._events.append((now, 0 if good else count, count))
        self._prune(now)

    def record_counts(self, bad: int, total: int, now: float) -> None:
        """Pre-judged observations (the fleet path: a merged sketch
        delta yields bad/total counts against the threshold without
        the raw values)."""
        if total <= 0:
            return
        self._events.append((now, max(0, int(bad)), int(total)))
        self._prune(now)

    def record_down(self, seconds: float, now: float) -> None:
        """Availability rule: `seconds` of downtime ending at `now`."""
        self._events.append((now, float(seconds), 0))
        self._prune(now)

    def _prune(self, now: float) -> None:
        horizon = now - self.slow_s
        while self._events and self._events[0][0] < horizon:
            self._events.popleft()

    # ------------------------------------------------------- evaluate

    def burn(self, window_s: float, now: float) -> float:
        lo = now - window_s
        if self.sketch is None:
            down = sum(b for t, b, _ in self._events if t > lo)
            return down / (window_s * self.budget)
        bad = tot = 0
        for t, b, c in self._events:
            if t > lo:
                bad += b
                tot += c
        if tot < self.min_count:
            return 0.0
        return (bad / tot) / self.budget

    def evaluate(self, now: float) -> dict | None:
        """Returns an alert record when the state CHANGES (fire,
        escalate, resolve), else None."""
        self._prune(now)
        bf = self.burn(self.fast_s, now)
        bs = self.burn(self.slow_s, now)
        sev = ("critical" if min(bf, bs) >= self.critical_burn
               else "warn" if min(bf, bs) >= self.warn_burn else None)
        if sev == self.state:
            return None
        prev, self.state = self.state, sev
        rec = {"slo": self.spec, "metric": self.metric,
               "state": "firing" if sev else "resolved",
               "severity": sev or prev,
               "burn_fast": round(bf, 3), "burn_slow": round(bs, 3),
               "threshold": self.threshold}
        if self.last_value is not None:
            rec["value"] = round(self.last_value, 6)
        return rec

    def status(self, now: float) -> dict:
        return {"slo": self.spec,
                "state": self.state or "ok",
                "burn_fast": round(self.burn(self.fast_s, now), 3),
                "burn_slow": round(self.burn(self.slow_s, now), 3)}


def parse_slos(spec: str, **kw) -> list[SloRule]:
    """``--slo 'ttft_p95_ms<500,availability>0.99'`` -> rules.
    A typed ValueError on the first bad token (fail at arg time, not
    mid-run)."""
    if not spec or not spec.strip():
        return []
    return [SloRule(tok, **kw) for tok in spec.split(",") if tok.strip()]


# ---------------------------------------------------- flight recorder


class FlightRecorder:
    """Ring buffer of the last `capacity` full-resolution records
    (metrics lines + tracer span events), dumped on incident triggers.

    Dumps are deduplicated by (reason, step) and capped per run —
    an alert flapping at log-point cadence must not fill the disk
    with identical snapshots.
    """

    def __init__(self, capacity: int = 256, out_dir=None,
                 max_dumps: int = 16):
        self.ring: deque = deque(maxlen=max(1, int(capacity)))
        self.out_dir = Path(out_dir) if out_dir else Path(".")
        self.max_dumps = int(max_dumps)
        self.dumps: list[str] = []
        self._seen: set = set()

    def record(self, rec: dict) -> None:
        self.ring.append(rec)

    def dump(self, reason: str, step=None, trigger=None) -> str | None:
        key = (reason, step)
        if key in self._seen or len(self.dumps) >= self.max_dumps:
            return None
        self._seen.add(key)
        tag = step if step is not None else f"n{len(self.dumps)}"
        path = self.out_dir / f"flightrec_{tag}.json"
        k = 0
        while path.exists():
            k += 1
            path = self.out_dir / f"flightrec_{tag}_{k}.json"
        payload = {"reason": reason, "step": step,
                   "wall": round(time.time(), 3), "trigger": trigger,
                   "n_entries": len(self.ring),
                   "ring": list(self.ring)}
        try:
            self.out_dir.mkdir(parents=True, exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, indent=1, default=str)
        except OSError:
            return None
        self.dumps.append(str(path))
        return str(path)


# ------------------------------------------------------------ monitor


class Monitor:
    """The live telemetry plane for one process (module docstring).

    `note_line(rec)` is the single ingestion path; `emit` (usually the
    bound `MetricsLogger.log`) receives the periodic ``"monitor"``
    snapshots and ``"alert"`` events this monitor produces;
    `alert_listeners` (e.g. `ServingEngine.on_alert`) get every alert
    record as a dict.

    `derive_steps=True` (the tailer / supervisor mode) reconstructs
    per-step time and tok/s from consecutive ``"step"`` lines; the
    in-process drivers leave it False and feed exact pause-excluded
    window rates through `StepRates(monitor=...)` instead — wiring
    both would double-count.
    """

    def __init__(self, slos: str = "", flight: int = 256,
                 flight_dir=None, rel_err: float = 0.01, emit=None,
                 derive_steps: bool = False, snapshot_every: int = 64,
                 clock=time.time, slo_kw: dict | None = None,
                 label: str | None = None):
        self.label = label          # replica name in a fleet view
        self.sketches = MetricSketches(rel_err=rel_err)
        # worst-K (value, request id) per latency metric — the
        # exemplar linkage a fleet's worst-ttft bucket resolves to
        self.exemplars: dict[str, list] = {}
        self.rules = parse_slos(slos, **(slo_kw or {}))
        self.flight = FlightRecorder(capacity=flight or 256,
                                     out_dir=flight_dir)
        self.flight_enabled = flight > 0
        self.emit = emit
        self.derive_steps = bool(derive_steps)
        self.snapshot_every = int(snapshot_every)
        self.clock = clock
        self.alert_listeners: list = []
        self.counters = {"lines": 0, "steps": 0, "requests": 0,
                         "faults": 0, "alerts": 0, "restarts": 0,
                         "snapshots": 0, "flight_dumps": 0}
        self.health = "ok"
        self.last_fault: dict | None = None
        self.last_step: dict | None = None
        # continuous-profiling plane (round 17): the drivers attach
        # their ProfilerPlane here so (a) /profile.json serves the
        # live sampler state and (b) every flight-dump trigger
        # (anomaly verdict, chaos fault, SLO burn) ALSO arms a
        # high-rate capture window; tailer-mode monitors instead keep
        # the stream's last cumulative "profile" snapshot
        self.profiler = None
        self.last_profile: dict | None = None
        self.serving: dict = {}
        # numerics observatory (round 18): last-seen schema-v13 num_*
        # step fields — the live precision story /status.json and
        # /metrics serve next to health, and the fleet view rolls up
        self.numerics: dict = {}
        # memory observatory (round 20): last-seen schema-v15 memory
        # step fields (per-owner MiB, untracked residual, host RSS),
        # the last recovered-OOM ledger stamp, and the last forensic
        # payload a memory flight dump carried
        self.memory: dict = {}
        # per-request lifecycle accounting (round 16): in-flight
        # phase-time accumulation keyed by request id, reduced on
        # "finished" into the rq_* component sketches and the
        # slowest-request decomposition /status.json serves
        self._lifecycle_acc: dict[str, dict] = {}
        self.slowest_request: dict | None = None
        self.active_alerts: dict[str, dict] = {}
        self._first_wall: float | None = None
        self._last_wall: float | None = None
        self._loss_s = 0.0            # ledgered non-productive seconds
        self._downtime_s = 0.0
        self._prev_step: tuple | None = None   # (step, wall)
        self._lines_since_snap = 0
        self._emitting = False
        self._lock = threading.RLock()

    # -------------------------------------------------------- ingest

    def observe(self, name: str, value, count: int = 1) -> None:
        """Direct sketch feed (exact values — `StepRates` uses this
        for pause-excluded step_ms/tok_s); also feeds any SLO rule
        bound to that sketch."""
        with self._lock:
            self.sketches.observe(name, value, count)
            now = self._now()
            for rule in self.rules:
                if rule.sketch == name:
                    rule.record(float(value), now, count)
            self._evaluate(now)

    def note_line(self, rec: dict) -> None:
        """Ingest one metrics-JSONL record (exactly the dict
        `MetricsLogger` writes / the tailer parses)."""
        if not isinstance(rec, dict):
            return
        if self._emitting:
            return      # our own monitor/alert emission re-entering
        ev = rec.get("event")
        if ev == "monitor":
            return      # derived data; merging it back would double-count
        with self._lock:
            self.counters["lines"] += 1
            wall = rec.get("wall")
            if isinstance(wall, (int, float)):
                if self._first_wall is None:
                    self._first_wall = float(wall)
                self._last_wall = max(self._last_wall or 0.0,
                                      float(wall))
            if ev is not None and self.flight_enabled:
                self.flight.record(rec)
            handler = getattr(self, f"_on_{ev}", None) \
                if isinstance(ev, str) else None
            if handler is not None:
                handler(rec)
            self._lines_since_snap += 1
            if self.snapshot_every and \
                    self._lines_since_snap >= self.snapshot_every:
                self._snapshot_locked()
            self._evaluate(self._now())

    def record_span(self, ev: dict) -> None:
        """Tracer subscriber: span events join the flight ring (full
        resolution around an incident includes the phase spans)."""
        if self.flight_enabled:
            with self._lock:
                self.flight.record(ev)

    # per-event handlers (note_line dispatch) ------------------------

    def _on_step(self, rec: dict) -> None:
        self.counters["steps"] += 1
        self.last_step = {k: rec.get(k) for k in
                          ("step", "loss", "tokens_per_sec", "mfu",
                           "wall") if k in rec}
        verdicts = rec.get("health_verdicts")
        if verdicts:
            self.health = "warn: " + ",".join(str(v) for v in verdicts)
            self._flight_dump("anomaly:" + ",".join(
                str(v) for v in verdicts), rec.get("step"), rec)
        elif rec.get("health_nonfinite"):
            self.health = "warn: nonfinite"
        self._note_numerics(rec)
        self._note_memory(rec)
        if self.derive_steps:
            step, wall = rec.get("step"), rec.get("wall")
            if isinstance(rec.get("tokens_per_sec"), (int, float)):
                self.observe_locked("tok_s", rec["tokens_per_sec"])
            if isinstance(step, int) and isinstance(wall, (int, float)):
                if self._prev_step is not None:
                    s0, w0 = self._prev_step
                    if step > s0 and wall > w0:
                        # approximate (pauses between log points are
                        # not excluded here; the in-process StepRates
                        # feed is the exact one)
                        ms = (wall - w0) * 1e3 / (step - s0)
                        self.observe_locked("step_ms", ms,
                                            count=step - s0)
                self._prev_step = (step, float(wall))

    def _on_request(self, rec: dict) -> None:
        self.counters["requests"] += 1
        now = self._now()
        for field, name in (("ttft_ms", "ttft_ms"),
                            ("tpot_ms", "tpot_ms")):
            v = rec.get(field)
            if isinstance(v, (int, float)):
                self.sketches.observe(name, v)
                if name in EXEMPLAR_METRICS:
                    self._note_exemplar(name, rec.get("id"), float(v))
                for rule in self.rules:
                    if rule.sketch == name:
                        rule.record(float(v), now)
        if isinstance(rec.get("queue_depth"), int):
            self.serving["queue_depth"] = rec["queue_depth"]

    def _on_generate(self, rec: dict) -> None:
        for field, name in (("tokens_per_sec", "tok_s"),
                            ("queue_depth", "queue_depth"),
                            ("free_blocks", "free_blocks")):
            v = rec.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.sketches.observe(name, v)
        now = self._now()
        for field in ("queue_depth", "active_slots", "free_blocks",
                      "blocks_touched", "hbm_gbps",
                      # schema v9: speculative-decoding window tallies
                      # — acceptance rate rides /status.json so a
                      # fleet view sees whether speculation is paying
                      "spec_drafted", "spec_accepted",
                      "spec_accept_rate",
                      # schema v14: prefix-cache gauges — hit rate +
                      # cold-list/index size ride /status.json so the
                      # fleet view sees whether caching is paying
                      "prefix_hit_rate", "cold_blocks",
                      "prefix_blocks",
                      # schema v15: capacity-plane gauges — the
                      # admission-headroom estimate the fleet view and
                      # router placement read (negative = the replica
                      # is overcommitted, evictions coming)
                      "live_blocks", "blocks_needed",
                      "headroom_blocks"):
            if field in rec:
                self.serving[field] = rec[field]
        for rule in self.rules:
            if rule.sketch in ("tok_s", "queue_depth", "free_blocks"):
                v = rec.get({"tok_s": "tokens_per_sec"}.get(
                    rule.sketch, rule.sketch))
                if isinstance(v, (int, float)):
                    rule.record(float(v), now)

    def _on_lifecycle(self, rec: dict) -> None:
        """Accumulate one request's phase transitions into the rq_*
        waterfall components (telemetry/tracing.PHASE_COMPONENT — the
        same mapping the offline stitcher uses), feeding the
        per-component sketches on completion and keeping the
        slowest-request decomposition for /status.json. Engine-side
        components only (queue/prefill/decode); the cross-process
        pieces (failover gap, breaker wait) are the stitcher's."""
        from shallowspeed_tpu.telemetry.tracing import PHASE_COMPONENT

        rid = rec.get("id")
        if not isinstance(rid, str):
            return
        st = self._lifecycle_acc.get(rid)
        if st is None:
            while len(self._lifecycle_acc) >= LIFECYCLE_CAP:
                self._lifecycle_acc.pop(
                    next(iter(self._lifecycle_acc)))
            st = self._lifecycle_acc[rid] = {
                "by": {}, "trace": rec.get("trace")}
        ms = rec.get("ms_in_prev")
        prev = rec.get("prev")
        if isinstance(ms, (int, float)) and isinstance(prev, str):
            comp = PHASE_COMPONENT.get(prev)
            if comp is not None:
                st["by"][comp] = st["by"].get(comp, 0.0) + float(ms)
        if rec.get("phase") != "finished":
            return
        st = self._lifecycle_acc.pop(rid)
        total = sum(st["by"].values())
        for comp, v in st["by"].items():
            self.sketches.observe(comp + "_ms", v)
        if total > (self.slowest_request or {}).get("e2e_ms", -1.0):
            self.slowest_request = {
                "id": rid, "trace": st["trace"],
                "e2e_ms": round(total, 3),
                "by_component_ms": {k: round(v, 3) for k, v
                                    in sorted(st["by"].items())}}

    def _on_ledger(self, rec: dict) -> None:
        secs = rec.get("seconds")
        if isinstance(secs, (int, float)):
            self._loss_s += float(secs)
            if rec.get("kind") == "restart_downtime":
                self._downtime_s += float(secs)
                self.counters["restarts"] += 1
                now = self._now()
                for rule in self.rules:
                    if rule.sketch is None:
                        rule.record_down(float(secs), now)
        if rec.get("kind") == "oom":
            # schema v15: a recovered OutOfBlocks stamp. Trip the
            # memory flight dump here too (tailer mode: no engine
            # listener wired) — in live serve mode the engine's
            # oom_listeners fired the RICH forensic dump first, so
            # this one dedups away on the same ("oom", tick) key.
            self.memory["last_oom"] = {
                k: rec[k] for k in ("requested", "free", "cold",
                                    "live", "id", "tick") if k in rec}
            self._flight_dump("oom", rec.get("tick"), rec)

    def _note_memory(self, rec: dict) -> None:
        """Fold schema-v15 memory step fields into the live memory
        view; a MemoryWatch verdict (mem_leak / mem_drift) trips the
        same incident path as a health verdict — flight dump +
        profiler capture window."""
        for field in ("hbm_live_mib", "hbm_owned_mib",
                      "hbm_untracked_mib", "host_rss_mib",
                      "hbm_within_bound"):
            if field in rec and rec[field] is not None:
                self.memory[field] = rec[field]
        verdicts = rec.get("mem_verdicts")
        if verdicts:
            self.memory["last_verdicts"] = [str(v) for v in verdicts]
            self.health = "warn: " + ",".join(str(v) for v in verdicts)
            self._flight_dump("memory:" + ",".join(
                str(v) for v in verdicts), rec.get("step"), rec)

    def memory_flight_dump(self, payload: dict, step=None) -> None:
        """OOM-forensics trigger (`ServingEngine.oom_listeners` →
        here, wired by serve.py): keep the forensic payload on the
        live memory view and dump it through the flight recorder /
        profiler capture path. `step` is the engine tick, matching the
        ledger stamp's dedup key."""
        with self._lock:
            self.memory["oom_forensics"] = payload
            self._flight_dump("oom", step, payload)

    def _on_fault(self, rec: dict) -> None:
        self.counters["faults"] += 1
        self.last_fault = dict(rec)
        self._flight_dump(f"fault:{rec.get('kind')}", rec.get("step"),
                          rec)

    def _on_health(self, rec: dict) -> None:
        verdicts = rec.get("health_verdicts")
        if verdicts:
            self.health = "warn: " + ",".join(str(v) for v in verdicts)
            self._flight_dump("anomaly:" + ",".join(
                str(v) for v in verdicts), rec.get("step"), rec)
        self._note_numerics(rec)

    def _note_numerics(self, rec: dict) -> None:
        """Fold schema-v13 num_* step fields into the live numerics
        view; a numerics verdict (scale_collapse / parity_drift) trips
        the same incident path as a health verdict — flight dump +
        profiler capture window."""
        for field in ("num_overflow_max", "num_underflow_max",
                      "num_scale_min", "num_amax_max", "num_drift_z",
                      "num_osc", "num_parity_loss_rel",
                      "num_parity_grad_relmax", "num_shadow_total",
                      "num_precision"):
            if field in rec and rec[field] is not None:
                self.numerics[field] = rec[field]
        verdicts = rec.get("num_verdicts")
        if verdicts:
            self.numerics["last_verdicts"] = [str(v) for v in verdicts]
            self.health = "warn: " + ",".join(str(v) for v in verdicts)
            self._flight_dump("numerics:" + ",".join(
                str(v) for v in verdicts), rec.get("step"), rec)

    def _on_profile(self, rec: dict) -> None:
        # tailer/fleet path: a file-fed replica's latest cumulative
        # profiler snapshot (events are cumulative, so last wins)
        self.last_profile = dict(rec)

    def _on_alert(self, rec: dict) -> None:
        # alerts from ANOTHER process's monitor (tailer mode): surface
        # them without re-evaluating
        if rec.get("state") == "firing":
            self.active_alerts[rec.get("slo", "?")] = dict(rec)
        else:
            self.active_alerts.pop(rec.get("slo", "?"), None)

    # ------------------------------------------------------ internals

    def _note_exemplar(self, name: str, rid, value: float) -> None:
        """Keep the K worst (value, id) pairs for `name` — tail-quantile
        forensics: the fleet view's worst-ttft bucket names these."""
        if rid is None:
            return
        ex = self.exemplars.setdefault(name, [])
        ex.append((value, str(rid)))
        ex.sort(key=lambda p: -p[0])
        del ex[EXEMPLAR_K:]

    def observe_locked(self, name, value, count=1):
        # observe() body without re-taking the RLock-guarded evaluate
        # (RLock makes this safe either way; kept for symmetry)
        self.sketches.observe(name, value, count)
        now = self._now()
        for rule in self.rules:
            if rule.sketch == name:
                rule.record(float(value), now, count)

    def _now(self) -> float:
        # event time: the last wall stamp seen keeps tailed history
        # evaluating in ITS timeline; live processes stamp wall
        # continuously so this is ~now there
        return self._last_wall if self._last_wall is not None \
            else self.clock()

    def _evaluate(self, now: float) -> None:
        for rule in self.rules:
            rec = rule.evaluate(now)
            if rec is None:
                continue
            self.counters["alerts"] += 1
            rec["wall"] = round(now, 3)
            if rec["state"] == "firing":
                self.active_alerts[rule.spec] = rec
                self._flight_dump(
                    f"slo:{rule.spec}",
                    (self.last_step or {}).get("step"), rec)
            else:
                self.active_alerts.pop(rule.spec, None)
            self._emit_rec("alert", rec)
            for fn in list(self.alert_listeners):
                try:
                    fn(rec)
                except Exception:
                    pass  # a broken listener must not kill the run

    def flight_dump(self, reason: str, step=None, trigger=None) -> None:
        """Public incident trigger — the drivers call this on their
        labeled-abort paths (divergence exit, fatal anomaly verdict),
        where the process dies before the next line would reach
        `note_line`."""
        with self._lock:
            self._flight_dump(reason, step, trigger)

    def _flight_dump(self, reason: str, step, trigger) -> None:
        # every incident that would dump the metrics ring also arms a
        # profiler capture window (round 17) — the flight dump says
        # what the run's NUMBERS were around the incident, the profcap
        # says what its HOST was doing; the capture's own dedup/
        # cooldown bounds it, independent of --flight-recorder
        if self.profiler is not None:
            try:
                self.profiler.on_incident(reason, step=step,
                                          trigger=trigger)
            except Exception:
                pass
        if not self.flight_enabled:
            return
        path = self.flight.dump(reason, step=step, trigger=trigger)
        if path is not None:
            self.counters["flight_dumps"] += 1

    def _emit_rec(self, event: str, rec: dict) -> None:
        if self.emit is None:
            return
        self._emitting = True
        try:
            self.emit(event=event, **{k: v for k, v in rec.items()
                                      if k != "event"})
        except Exception:
            pass
        finally:
            self._emitting = False

    # ------------------------------------------------------- snapshot

    def _snapshot_locked(self) -> dict:
        self._lines_since_snap = 0
        self.counters["snapshots"] += 1
        snap = {"sketches": self.sketches.to_dict(),
                "counters": dict(self.counters),
                "rel_err": self.sketches.rel_err}
        self._emit_rec("monitor", snap)
        return snap

    def snapshot(self) -> dict:
        """Serialize-and-emit the current sketch state (a schema-v7
        ``"monitor"`` event payload); merge with `merge_snapshot`."""
        with self._lock:
            return self._snapshot_locked()

    def merge_snapshot(self, snap: dict) -> None:
        """Fold another process's ``"monitor"`` payload into this one
        (the fleet/gang aggregation path)."""
        with self._lock:
            self.sketches.merge_dict(snap.get("sketches") or {})

    def close(self) -> None:
        """Final snapshot so the JSONL tail carries the run's whole
        distribution for offline merging."""
        with self._lock:
            if any(sk.n for sk in self.sketches.sketches.values()):
                self._snapshot_locked()

    # --------------------------------------------------------- views

    def goodput_so_far(self) -> float | None:
        """In-flight approximation: 1 - (ledgered losses + downtime) /
        wall. The offline reducer additionally splits compile/replay
        out of the productive share; this is the monotone headline an
        operator watches, not the final accounting."""
        if self._first_wall is None or self._last_wall is None:
            return None
        wall = self._last_wall - self._first_wall
        if wall <= 0:
            return None
        return max(0.0, min(1.0, 1.0 - self._loss_s / wall))

    def availability(self) -> float | None:
        if self._first_wall is None or self._last_wall is None:
            return None
        wall = self._last_wall - self._first_wall
        if wall <= 0:
            return None
        return max(0.0, 1.0 - min(self._downtime_s, wall) / wall)

    def sketch_payload(self) -> dict:
        """The /sketches.json payload: the SERIALIZED (mergeable)
        sketches, not just their quantile summaries — what a
        FleetCollector polls so fleet quantiles are exact bucket
        unions, the same payload a schema-v8 ``"monitor"`` event
        carries."""
        with self._lock:
            return {"sketches": self.sketches.to_dict(),
                    "rel_err": self.sketches.rel_err,
                    "label": self.label,
                    "exemplars": {name: [{"value": v, "id": rid}
                                         for v, rid in ex]
                                  for name, ex in self.exemplars.items()},
                    "counters": dict(self.counters)}

    def profile_payload(self) -> dict:
        """The /profile.json payload: the attached ProfilerPlane's
        cumulative snapshot (live path), else the last "profile" event
        seen in the stream (tailer path), else a typed
        `{"enabled": False}` — an old or unprofiled replica answers
        200 with a miss, and a fleet poller treats absence as
        "no profile", never as "replica dead"."""
        if self.profiler is not None:
            return self.profiler.profile_payload()
        with self._lock:
            if self.last_profile is not None:
                snap = {k: v for k, v in self.last_profile.items()
                        if k not in ("event", "t", "wall", "mono")}
                return {"enabled": True, "source": "log", **snap}
        return {"enabled": False}

    def status(self) -> dict:
        """The /status.json payload."""
        with self._lock:
            now = self._now()
            return {
                "replica": self.label,
                "wall": round(now, 3),
                "uptime_s": (round(now - self._first_wall, 3)
                             if self._first_wall is not None else None),
                "sketches": self.sketches.summary(),
                "rel_err": self.sketches.rel_err,
                "goodput_so_far": self.goodput_so_far(),
                "availability": self.availability(),
                "health": self.health,
                "last_step": self.last_step,
                "serving": self.serving or None,
                # the numerics observatory's last-seen story (schema
                # v13): live precision, clamp fractions, shadow-parity
                # rel-errs, and the last verdicts that fired
                "numerics": self.numerics or None,
                # the memory observatory's last-seen story (schema
                # v15): per-owner decomposition, untracked residual,
                # host RSS, last recovered OOM + forensic payload
                "memory": self.memory or None,
                # the slowest finished request's per-component
                # decomposition (round 16) — where ITS latency went,
                # one hop from the burning quantile
                "slowest_request": self.slowest_request,
                "last_fault": self.last_fault,
                "slo": [r.status(now) for r in self.rules],
                "alerts": sorted(self.active_alerts.values(),
                                 key=lambda a: a.get("slo", "")),
                "worst": {name: [{"value": v, "id": rid}
                                 for v, rid in ex]
                          for name, ex in self.exemplars.items()}
                or None,
                "counters": dict(self.counters),
                "flight_dumps": list(self.flight.dumps),
            }

    def prometheus(self) -> str:
        """The /metrics payload (Prometheus text exposition 0.0.4)."""
        with self._lock:
            P = "shallowspeed_"
            lines = [f"# TYPE {P}up gauge", f"{P}up 1"]
            for name, sk in sorted(self.sketches.sketches.items()):
                if not sk.n:
                    continue
                base = P + re.sub(r"[^a-zA-Z0-9_]", "_", name)
                lines.append(f"# TYPE {base} summary")
                for q in (0.5, 0.95, 0.99):
                    v = sk.quantile(q * 100)
                    lines.append(f'{base}{{quantile="{q}"}} {v:.6g}')
                lines.append(f"{base}_sum {sk.total:.6g}")
                lines.append(f"{base}_count {sk.n}")
                # ... and the NATIVE histogram alongside (round 16):
                # cumulative le buckets on the fixed ladder, so fleet
                # quantiles aggregate correctly in Prometheus instead
                # of averaging pre-computed per-replica quantiles
                lines.extend(prom_histogram_lines(base, sk))
            for name, v in (("goodput_so_far", self.goodput_so_far()),
                            ("availability", self.availability())):
                if v is not None:
                    lines.append(f"# TYPE {P}{name} gauge")
                    lines.append(f"{P}{name} {v:.6g}")
            for field in ("queue_depth", "active_slots", "free_blocks",
                          "spec_accept_rate", "prefix_hit_rate",
                          "cold_blocks", "prefix_blocks",
                          "live_blocks", "blocks_needed",
                          "headroom_blocks"):
                v = self.serving.get(field)
                if isinstance(v, (int, float)):
                    lines.append(f"# TYPE {P}{field} gauge")
                    lines.append(f"{P}{field} {v:.6g}")
            for field in ("hbm_live_mib", "hbm_untracked_mib",
                          "host_rss_mib"):
                v = self.memory.get(field)
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    lines.append(f"# TYPE {P}{field} gauge")
                    lines.append(f"{P}{field} {v:.6g}")
            for field in ("num_overflow_max", "num_underflow_max",
                          "num_scale_min", "num_amax_max",
                          "num_parity_loss_rel",
                          "num_parity_grad_relmax"):
                v = self.numerics.get(field)
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool):
                    lines.append(f"# TYPE {P}{field} gauge")
                    lines.append(f"{P}{field} {v:.6g}")
            if self.numerics.get("num_precision") in ("fp8", "bf16"):
                lines.append(f"# TYPE {P}num_precision_fp8 gauge")
                lines.append(
                    f"{P}num_precision_fp8 "
                    f"{1 if self.numerics['num_precision'] == 'fp8' else 0}")
            if self.last_step and isinstance(
                    self.last_step.get("step"), int):
                lines.append(f"# TYPE {P}last_step gauge")
                lines.append(f"{P}last_step {self.last_step['step']}")
            lines.append(f"# TYPE {P}alerts_firing gauge")
            lines.append(f"{P}alerts_firing {len(self.active_alerts)}")
            for name in ("steps", "requests", "faults", "restarts",
                         "flight_dumps"):
                lines.append(f"# TYPE {P}{name}_total counter")
                lines.append(f"{P}{name}_total {self.counters[name]}")
            lines.append(f"{P}health_ok "
                         f"{1 if self.health == 'ok' else 0}")
            return "\n".join(lines) + "\n"


# ------------------------------------------------------- HTTP server


class StatusServer:
    """stdlib status endpoint: GET /status.json and /metrics on
    127.0.0.1:`port` (port 0 picks a free one — read `.port`). Runs on
    a daemon thread; `close()` shuts it down. No auth, loopback bind —
    an operator tunnel (ssh -L) is the expected transport, same as
    jax's profiler server.

    Unknown paths answer 404 with a JSON error body (round 17) — a
    TYPED miss, so a fleet poller probing /profile.json on an old
    replica can distinguish "endpoint absent" (HTTP 404 + parseable
    body) from "replica dead" (connection refused/timeout) without
    burning availability.

    Duck-typed over `monitor`: anything with `status()`/`prometheus()`
    serves (a `fleet.FleetCollector` plugs in unchanged). Objects that
    also expose `sketch_payload()` get GET /sketches.json (the
    serialized mergeable sketches a fleet poller needs); objects with
    `profile_payload()` get GET /profile.json (the continuous-profiler
    snapshot a fleet merges into its flamegraph); objects with
    `register_replica(payload)` / `deregister_replica(payload)` get
    POST /register and /deregister (a replica announcing — or, on
    clean drain, withdrawing — its status URL at a fleet collector);
    objects with `submit_request` / `poll_requests` / `drain_request`
    (a `serving.router.RequestGateway`) get POST /submit, GET
    /requests and POST /drain — the replica-side request-ingestion
    surface the fleet router drives. `extra` grafts a second target
    behind the same port (serve.py serves its Monitor AND its gateway
    on one endpoint); the first of (monitor, extra) providing a method
    wins."""

    # POST path -> duck-typed method on the served object(s)
    _POSTS = {"/register": "register_replica",
              "/deregister": "deregister_replica",
              "/submit": "submit_request",
              "/drain": "drain_request"}

    def __init__(self, monitor: Monitor, port: int = 0,
                 host: str = "127.0.0.1", extra=None):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        def find(name):
            for obj in (monitor, extra):
                if obj is not None and hasattr(obj, name):
                    return getattr(obj, name)
            return None

        posts = {path: find(meth) for path, meth in self._POSTS.items()
                 if find(meth) is not None}
        mon = monitor
        poll_requests = find("poll_requests")
        profile_payload = find("profile_payload")

        class _Handler(BaseHTTPRequestHandler):
            def _send(self, body: bytes, ctype: str,
                      status: int = 200) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _miss(self, path: str) -> None:
                # typed 404: JSON body, so a poller can tell "endpoint
                # absent on this replica" from "replica dead"
                self._send(json.dumps(
                    {"error": "not found", "path": path}).encode(),
                    "application/json", status=404)

            def do_GET(self):
                path = self.path.split("?")[0]
                try:
                    if path in ("/status.json", "/status", "/"):
                        body = json.dumps(mon.status(),
                                          default=str).encode()
                        ctype = "application/json"
                    elif path == "/sketches.json" \
                            and hasattr(mon, "sketch_payload"):
                        body = json.dumps(mon.sketch_payload(),
                                          default=str).encode()
                        ctype = "application/json"
                    elif path == "/profile.json" \
                            and profile_payload is not None:
                        body = json.dumps(profile_payload(),
                                          default=str).encode()
                        ctype = "application/json"
                    elif path == "/requests" \
                            and poll_requests is not None:
                        body = json.dumps(poll_requests(),
                                          default=str).encode()
                        ctype = "application/json"
                    elif path == "/metrics":
                        body = mon.prometheus().encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    else:
                        self._miss(path)
                        return
                except Exception as e:   # a status bug must not 500-loop
                    body = json.dumps({"error": repr(e)}).encode()
                    ctype = "application/json"
                self._send(body, ctype)

            def do_POST(self):
                path = self.path.split("?")[0]
                fn = posts.get(path)
                if fn is None:
                    self._miss(path)
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    out = fn(payload)
                except Exception as e:
                    self.send_error(400, repr(e)[:120])
                    return
                self._send(json.dumps(out, default=str).encode(),
                           "application/json")

            def log_message(self, *a):   # no per-request stderr spam
                pass

        try:
            self._srv = ThreadingHTTPServer((host, int(port)), _Handler)
        except OSError as e:
            # a busy --monitor-port must fail with the port in the
            # message, not a bare errno traceback three frames deep
            raise PortInUseError(
                f"cannot bind the monitor endpoint to {host}:{port} "
                f"({e.strerror or e}); pick another --monitor-port "
                f"(0 asks the OS for a free one)") from e
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self.host = host
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        name="monitor-http",
                                        daemon=True)
        self._thread.start()

    def url(self, path: str = "/status.json") -> str:
        return f"http://{self.host}:{self.port}{path}"

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)


# ------------------------------------------------- driver-side wiring


def from_args(args, metrics, flight_dir=None, extra=None):
    """One-call driver wiring: build the Monitor + StatusServer when
    any of --monitor-port / --slo / --flight-recorder is set, attach
    it to the MetricsLogger (every logged line flows into
    `note_line`), and return (monitor, server) — (None, None) when the
    plane is off. `extra` (serve.py's request gateway) is grafted onto
    the same endpoint (see StatusServer) and forces the plane on. The
    caller owns `close_monitor(monitor, server)` at teardown."""
    port = getattr(args, "monitor_port", None)
    slo = getattr(args, "slo", "") or ""
    flight = int(getattr(args, "flight_recorder", 0) or 0)
    if port is None and not slo and not flight and extra is None:
        return None, None
    if flight_dir is None:
        log_file = getattr(args, "log_file", "") or ""
        flight_dir = Path(log_file).parent if log_file else Path(".")
    mon = Monitor(slos=slo, flight=flight, flight_dir=flight_dir,
                  emit=metrics.log if metrics is not None else None,
                  label=getattr(args, "replica", None) or None)
    if metrics is not None:
        metrics.monitor = mon
    server = StatusServer(mon, port=port, extra=extra) \
        if port is not None else None
    return mon, server


def close_monitor(monitor, server) -> None:
    if server is not None:
        server.close()
    if monitor is not None:
        monitor.close()


# ------------------------------------------------------- live tailer


def iter_jsonl(path, pos: int = 0):
    """Parse records from `path` starting at byte `pos`; returns
    (records, new_pos). Tolerates a partial last line (the writer may
    be mid-append) by not consuming it. A file SHORTER than `pos`
    means it was truncated or rotated under us — restart from byte 0
    (re-reading a rotated file beats the old behavior of silently
    reading nothing forever)."""
    recs = []
    try:
        with open(path, "rb") as f:
            if os.fstat(f.fileno()).st_size < pos:
                pos = 0
            f.seek(pos)
            data = f.read()
    except OSError:
        return recs, pos
    if not data:
        return recs, pos
    end = data.rfind(b"\n")
    if end < 0:
        return recs, pos
    for line in data[:end].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            recs.append(json.loads(line))
        except ValueError:
            continue
    return recs, pos + end + 1


class FileTailer(threading.Thread):
    """Daemon thread feeding a growing metrics JSONL into a Monitor —
    the elastic supervisor's aggregation path (the ledger file spans
    every child stanza, so one tailer sees the whole gang history)."""

    def __init__(self, path, monitor: Monitor, poll: float = 0.5):
        super().__init__(name="monitor-tail", daemon=True)
        self.path = str(path)
        self.monitor = monitor
        self.poll = float(poll)
        # NOT named _stop: threading.Thread owns that attribute (its
        # join machinery calls self._stop() internally)
        self._halt = threading.Event()
        self._pos = 0
        self._ino: int | None = None

    def drain(self) -> int:
        # rotation to an EQUAL-OR-LARGER file defeats iter_jsonl's
        # size check — a changed inode means a different file, restart
        # from byte 0 (shrinkage is caught either way)
        try:
            ino = os.stat(self.path).st_ino
        except OSError:
            ino = None
        if ino is not None:
            if self._ino is not None and ino != self._ino:
                self._pos = 0
            self._ino = ino
        recs, self._pos = iter_jsonl(self.path, self._pos)
        for rec in recs:
            self.monitor.note_line(rec)
        return len(recs)

    def run(self):
        while not self._halt.is_set():
            self.drain()
            self._halt.wait(self.poll)
        self.drain()

    def stop(self):
        self._halt.set()
        self.join(timeout=5)


def format_status(status: dict) -> str:
    """Human-readable rendering of one /status.json payload (the
    --live terminal view)."""
    lines = []
    up = status.get("uptime_s")
    head = [f"uptime {up:.0f}s" if up is not None else "uptime —"]
    for key in ("goodput_so_far", "availability"):
        v = status.get(key)
        if v is not None:
            head.append(f"{key.replace('_so_far', '')} {v:.1%}")
    head.append(f"health {status.get('health', '?')}")
    lines.append("  ".join(head))
    ls = status.get("last_step")
    if ls:
        bits = [f"step {ls.get('step')}"]
        if isinstance(ls.get("loss"), (int, float)):
            bits.append(f"loss {ls['loss']:.4f}")
        if isinstance(ls.get("tokens_per_sec"), (int, float)):
            bits.append(f"tok/s {ls['tokens_per_sec']:,.0f}")
        lines.append("  ".join(bits))
    for name, sk in (status.get("sketches") or {}).items():
        lines.append(
            f"  {name:<12} n={sk['count']:<7} p50 {sk.get('p50')}  "
            f"p95 {sk.get('p95')}  p99 {sk.get('p99')}  "
            f"[{sk.get('min')} .. {sk.get('max')}]")
    srv = status.get("serving")
    if srv:
        lines.append("  serving " + "  ".join(
            f"{k}={v}" for k, v in sorted(srv.items())))
    for s in status.get("slo") or []:
        lines.append(f"  slo {s['slo']:<24} {s['state']:<8} "
                     f"burn fast/slow {s['burn_fast']}/{s['burn_slow']}")
    for a in status.get("alerts") or []:
        lines.append(f"  ALERT {a.get('severity', '?').upper()} "
                     f"{a.get('slo')} burn {a.get('burn_fast')}/"
                     f"{a.get('burn_slow')}")
    lf = status.get("last_fault")
    if lf:
        lines.append(f"  last fault: {lf.get('kind')} "
                     f"(step {lf.get('step')})")
    for p in status.get("flight_dumps") or []:
        lines.append(f"  flight recorder: {p}")
    return "\n".join(lines)


def live_main(path, slos: str = "", once: bool = False,
              interval: float = 2.0, out=print, max_secs=None) -> int:
    """``python -m shallowspeed_tpu.telemetry --live <jsonl>``: tail a
    growing metrics file and render the same view the /status.json
    endpoint serves — live monitoring for runs started without
    --monitor-port. `once` renders the current state and exits (the
    pre-commit smoke); otherwise polls until Ctrl-C / `max_secs`."""
    mon = Monitor(slos=slos, flight=0, derive_steps=True,
                  snapshot_every=0)
    pos = 0
    t0 = time.time()
    if not Path(path).exists() and once:
        out(f"--live: no such file {path}")
        return 1
    while True:
        recs, pos = iter_jsonl(path, pos)
        for rec in recs:
            mon.note_line(rec)
        out(f"== {path} @ {time.strftime('%H:%M:%S')} "
            f"({mon.counters['lines']} lines)")
        out(format_status(mon.status()))
        if once or (max_secs is not None
                    and time.time() - t0 >= max_secs):
            return 0
        try:
            time.sleep(interval)
        except KeyboardInterrupt:
            return 0
