"""Pipeline bubble accounting — measured idle vs the verified schedule.

`parallel/verify.py` *proves* each schedule and measures its unit-cost
makespan; this module closes the loop at runtime:

- `static_bubble(schedule, n_mu, pp, vpp)` reads the bubble fraction
  off the SAME simulators the engines' tables come from (what the
  schedule promises under the unit-cost model).
- `replay_trace(ops)` REPLAYS an executed trace — per-op measured
  durations in per-stage executed order (the VM's fenced spans via
  `span_replay_ops`) — under dedicated-processor semantics honoring
  the pipeline dataflow dependencies, and reads the bubble off the
  replayed timeline. This is the executed-schedule-vs-makespan-tables
  comparison: wall-clock gaps are meaningless when a shared-core CPU
  host serializes "device" work, but the measured durations laid on
  the verified dependency structure are comparable to the unit-cost
  static fraction on any host.
- `costed_replay(...)` prices the verified placement (the SAME tables
  the compiled engines execute) with measured per-op costs;
  `calibrate_compiled` derives those costs from two fenced
  observations of the live engine (step spans + the pure-F eval
  program) without touching its training state.
- `trace_bubble(events)` is the raw wall-clock variant (busy vs
  window per stage) — honest only where stages own real devices.
- `two_point_bubble(t1, t2)` is the model-free hardware measurement:
  step time at n_mu and at 2x n_mu with the same per-microbatch shape
  (`make_calibration_twin`); the ramp 2*t1 - t2 is the fill/drain
  cost. Exact for any F:B ratio on dedicated devices; too noisy under
  per-program XLA-CPU compile variance, so the driver uses the costed
  replay and leaves this one for on-chip benches.

The bubble FRACTION definition is shared throughout: idle device-
rounds inside the step window over total device-rounds,
`1 - work / (makespan * n_stages)` — so measured and static numbers
are directly comparable (the acceptance gate: within 5 points).
"""

from __future__ import annotations


def static_bubble(schedule: str, n_mu: int, pp: int,
                  vpp: int = 1) -> dict:
    """The unit-cost bubble fraction of a verified schedule instance.

    schedule: 'gpipe' | '1f1b' | 'zb' (vpp > 1 selects the interleaved
    1F1B tables, matching PipelineLMEngine's routing). Returns
    {schedule, n_mu, pp, vpp, makespan, work_rounds, bubble_fraction}.
    Work is per-stage compute rounds: 2*n_mu for gpipe/1f1b (F=B=1 in
    `verify.simulate`'s round model), 2*n_mu*vpp chunk-rounds
    interleaved, 3*n_mu for zb (F=B=W=1).
    """
    from shallowspeed_tpu.parallel import verify

    assert pp >= 1 and n_mu >= 1 and vpp >= 1
    if pp == 1:
        # no pipeline, no bubble — the degenerate anchor the pp=1
        # drivers report
        return {"schedule": schedule, "n_mu": n_mu, "pp": 1, "vpp": vpp,
                "makespan": 2 * n_mu, "work_rounds": 2 * n_mu,
                "bubble_fraction": 0.0}
    if schedule == "zb":
        # the compiled zb engine executes zb_tables verbatim, whose
        # round count IS simulate_zb's verified makespan
        rep = verify.simulate_zb(n_mu, pp)
        makespan, work = rep.makespan, 3 * n_mu
    elif vpp > 1:
        # ditto: the interleaved engine follows interleaved_tables
        rep = verify.simulate_interleaved(n_mu, pp, vpp)
        makespan, work = rep.makespan, 2 * n_mu * vpp
    elif schedule in ("gpipe", "1f1b", "pipedream"):
        # the compiled engines run 2*(n_mu + pp - 1) compute ticks
        # (pipeline_lm's fwd/bwd tick scans; the 1F1B slot algebra has
        # the same span) — the closed form `verify.simulate`'s round
        # model documents. The simulator itself is still run as the
        # schedule PROOF, but its literal round count defers each
        # zero-cost send to the next round (+~1 bookkeeping round per
        # hop no engine executes), so the tick count is the honest
        # makespan for measured-vs-static comparison; the simulator's
        # is reported alongside as `sim_makespan`.
        from shallowspeed_tpu.parallel import schedules

        cls = {"gpipe": schedules.GPipeSchedule,
               "1f1b": schedules.PipeDreamSchedule,
               "pipedream": schedules.PipeDreamSchedule}[schedule]
        rep = verify.simulate(cls, n_mu, pp)  # raises if not sound
        makespan, work = 2 * (n_mu + pp - 1), 2 * n_mu
        return {"schedule": schedule, "n_mu": n_mu, "pp": pp,
                "vpp": vpp, "makespan": makespan,
                "sim_makespan": rep.makespan, "work_rounds": work,
                "bubble_fraction": round(1.0 - work / makespan, 4)}
    elif schedule == "naive":
        from shallowspeed_tpu.parallel import schedules

        rep = verify.simulate(schedules.NaiveParallelSchedule, n_mu, pp)
        makespan, work = rep.makespan, 2 * n_mu
    else:
        raise AssertionError(f"unknown schedule {schedule!r}")
    return {"schedule": schedule, "n_mu": n_mu, "pp": pp, "vpp": vpp,
            "makespan": makespan, "work_rounds": work,
            "bubble_fraction": round(1.0 - work / (makespan * 1.0), 4)}


def trace_bubble(events) -> dict:
    """Measured bubble fraction from an executed schedule trace.

    events: iterable of dicts with at least {"stage", "ts", "dur"}
    (the pipeline VM's per-op spans: ts/dur in any consistent unit) —
    or (stage, ts, dur) tuples. The step window is [min ts, max ts+dur]
    over ALL stages (the pipeline drains as a unit); each stage's idle
    time inside that window is window - sum(dur). Returns
    {window, busy, per_stage_busy, bubble_fraction}.
    """
    per_stage: dict[int, float] = {}
    t_lo, t_hi = float("inf"), float("-inf")
    for ev in events:
        if isinstance(ev, dict):
            s, ts, dur = ev["stage"], ev["ts"], ev["dur"]
        else:
            s, ts, dur = ev
        per_stage[s] = per_stage.get(s, 0.0) + dur
        t_lo = min(t_lo, ts)
        t_hi = max(t_hi, ts + dur)
    assert per_stage, "trace_bubble needs at least one op event"
    window = t_hi - t_lo
    n_stages = len(per_stage)
    busy = sum(per_stage.values())
    frac = (0.0 if window <= 0.0
            else max(0.0, 1.0 - busy / (window * n_stages)))
    return {"window": window, "busy": busy,
            "per_stage_busy": dict(sorted(per_stage.items())),
            "n_stages": n_stages,
            "bubble_fraction": round(frac, 4)}


# ---------------------------------------------------- executed replay


def replay_trace(ops, pp: int | None = None) -> dict:
    """Replay an executed schedule trace under dedicated-processor
    semantics and report its measured bubble fraction.

    ops: (kind, stage, mu, dur[, proc]) tuples in per-processor
    executed order, kind in "F"/"B"/"W" — measured durations (the VM's
    fenced per-op spans, or a costed static placement from
    `costed_replay`). `stage` is the DATAFLOW stage (logical stage for
    interleaved schedules); `proc` is the executing device and
    defaults to the stage (they differ only under vpp, where device d
    hosts logical stages {d, d+pp, ...} and serializes their chunks in
    the greedy table's round order). The replay honors each
    processor's executed op ORDER plus the pipeline dataflow
    dependencies (F(s,m) after F(s-1,m); B(s,m) after F(s,m) and
    B(s+1,m); W(s,m) after B(s,m)) and gives every processor its own
    timeline — which is exactly what "replaying against verify.py's
    makespan model" means: the measured per-op times are laid out on
    the schedule's dependency structure, so the bubble read off the
    replayed timeline is comparable to the unit-cost static fraction
    even when the host serializes execution (a shared-core CPU mesh
    can never show the fill/drain ramp in wall-clock).

    `pp` is the pipeline's processor count when known: a trace with
    MORE processors than that is rejected (mislabeled ops), and one
    with fewer — a partial capture — counts the missing processors as
    fully idle instead of silently reporting a shallower pipeline.
    Duplicate (kind, stage, mu) ops are rejected outright: a sound
    single-batch trace executes each op once, and a duplicate means
    the caller mixed batches/epochs into one window.
    """
    per_proc: dict[int, list] = {}
    stages: set = set()
    seen_ops: set = set()
    for op in ops:
        kind, s, m, dur = op[:4]
        if (kind, s, m) in seen_ops:
            raise ValueError(
                f"duplicate op {(kind, s, m)} in trace — the window "
                f"mixes more than one batch/epoch of spans")
        seen_ops.add((kind, s, m))
        proc = op[4] if len(op) > 4 else s
        stages.add(s)
        per_proc.setdefault(proc, []).append((kind, s, m, float(dur)))
    n_procs = len(per_proc)
    assert n_procs >= 1, "replay_trace needs at least one op"
    if pp is not None:
        if n_procs > pp:
            raise ValueError(
                f"trace names {n_procs} processors but the pipeline "
                f"has {pp} — op attribution is mislabeled")
        n_procs = pp  # absent processors were idle the whole window
    pcs = {p: 0 for p in per_proc}
    free = {p: 0.0 for p in per_proc}
    done: dict[tuple, float] = {}
    busy = {p: 0.0 for p in per_proc}
    remaining = sum(len(v) for v in per_proc.values())

    def deps(kind, s, m):
        if kind == "F":
            return [("F", s - 1, m)] if (s - 1) in stages else []
        if kind == "B":
            out = [("F", s, m)]
            if (s + 1) in stages:
                out.append(("B", s + 1, m))
            return out
        return [("B", s, m)]  # W

    while remaining:
        progressed = False
        for p, prog in per_proc.items():
            while pcs[p] < len(prog):
                kind, s, m, dur = prog[pcs[p]]
                need = deps(kind, s, m)
                if any(d not in done for d in need):
                    break
                start = max([free[p]] + [done[d] for d in need])
                done[(kind, s, m)] = start + dur
                free[p] = start + dur
                busy[p] += dur
                pcs[p] += 1
                remaining -= 1
                progressed = True
        if not progressed:
            stuck = {p: per_proc[p][pcs[p]] for p in per_proc
                     if pcs[p] < len(per_proc[p])}
            raise ValueError(
                f"executed trace violates pipeline dataflow (missing "
                f"producers for {stuck}) — not a sound schedule trace")
    makespan = max(done.values())
    total_busy = sum(busy.values())
    frac = max(0.0, 1.0 - total_busy / (makespan * n_procs))
    return {"makespan": makespan, "busy": total_busy,
            "per_stage_busy": {p: round(b, 6)
                               for p, b in sorted(busy.items())},
            "n_stages": n_procs, "bubble_fraction": round(frac, 4)}


def _placement(schedule: str, n_mu: int, pp: int, vpp: int = 1) -> list:
    """The verified schedule's op placement as per-processor-ordered
    (kind, stage, mu[, proc]) tuples — the same tables the compiled
    engines execute (verify.py's greedy/zb tables for vpp/zb; the tick
    algebra pipeline_lm compiles for gpipe/1f1b). The proc column
    appears only for vpp, where devices host several logical stages."""
    from shallowspeed_tpu.parallel import verify

    ops: list = []
    if schedule == "zb":
        starts = verify.simulate_zb(n_mu, pp).op_rounds
        for (kind, l, m), r in sorted(starts.items(),
                                      key=lambda kv: kv[1]):
            ops.append((kind, l, m))
    elif vpp > 1:
        placed, _, _, _, _ = verify._greedy_interleaved(n_mu, pp, vpp)
        # dataflow deps run over LOGICAL stages; device d executes its
        # vpp chunks serially in the greedy table's round order — the
        # explicit proc column models that contention in the replay
        for (r, d), (kind, ls, m) in sorted(placed.items()):
            ops.append((kind, ls, m, d))
    elif schedule in ("gpipe", "naive"):
        for s in range(pp):
            for m in range(n_mu):
                ops.append(("F", s, m))
            for m in reversed(range(n_mu)):
                ops.append(("B", s, m))
    elif schedule in ("1f1b", "pipedream"):
        for s in range(pp):
            warm = min(pp - s - 1, n_mu)
            seq = [("F", s, m) for m in range(warm)]
            for i in range(n_mu - warm):
                seq.append(("F", s, warm + i))
                seq.append(("B", s, i))
            seq += [("B", s, m) for m in range(n_mu - warm, n_mu)]
            ops.extend(seq)
    else:
        raise AssertionError(f"unknown schedule {schedule!r}")
    return ops


def costed_replay(schedule: str, n_mu: int, pp: int, vpp: int = 1,
                  c_f: float = 1.0, c_b: float = 1.0,
                  c_w: float = 1.0) -> dict:
    """Replay the verified placement with MEASURED per-op costs: the
    bubble fraction of the executed tables priced at what F/B/W
    actually cost on this hardware (equals `static_bubble` at unit
    costs; moves with the real F:B ratio for the slot-scheduled
    1f1b/vpp/zb families)."""
    cost = {"F": c_f, "B": c_b, "W": c_w}
    ops = [(it[0], it[1], it[2], cost[it[0]], *it[3:])
           for it in _placement(schedule, n_mu, pp, vpp)]
    return replay_trace(ops, pp)


def span_ops(events, names=("Forward", "BackwardGradAcc",
                            "BackwardGradAllReduce"),
             batch=None) -> list:
    """Tracer span events -> (stage, ts, dur) op tuples for
    `trace_bubble` (the pipeline VM's executed-schedule trace; filter
    to one batch with `batch=`)."""
    out = []
    for e in events:
        if e.get("ph") != "X" or e["name"] not in names:
            continue
        args = e.get("args", {})
        if "stage" not in args:
            continue
        if batch is not None and args.get("batch") != batch:
            continue
        out.append((args["stage"], e["ts"], e["dur"]))
    return out


_KIND_OF = {"Forward": "F", "BackwardGradAcc": "B",
            "BackwardGradAllReduce": "B"}


def span_replay_ops(events, batch=None) -> list:
    """Tracer span events -> (kind, stage, mu, dur_us) tuples in
    executed order for `replay_trace` (the VM's fenced per-op spans;
    filter to one batch with `batch=`)."""
    out = []
    for e in events:
        kind = _KIND_OF.get(e.get("name"))
        if e.get("ph") != "X" or kind is None:
            continue
        args = e.get("args", {})
        if "stage" not in args or "mu" not in args:
            continue
        if batch is not None and args.get("batch") != batch:
            continue
        out.append((kind, args["stage"], args["mu"], e["dur"]))
    return out


def calibrate_compiled(engine, tracer, tokens, targets,
                       reps: int = 3) -> dict | None:
    """Measured-bubble calibration for a COMPILED pipeline engine,
    where per-op timing is invisible inside the single XLA program.

    Measured inputs (training trajectory untouched):
    - t_step: median of the live engine's already-recorded fenced
      "step" spans (the `spans` level fences each step);
    - t_eval: a few fenced `eval_loss` calls — the eval program is the
      pure-F pipeline, so c_F = t_eval / (n_mu + pp - 1) fwd ticks.

    For gpipe/1f1b, c_B is the residual per-tick cost
    (t_step / ticks - c_F, each engine running 2*(n_mu + pp - 1)
    one-op ticks per device); zb and interleaved vpp replay at uniform
    per-round cost t_step / makespan (zb's F≈B≈W is the schedule's own
    design assumption). The verified placement — the SAME tables the
    engine compiles — is then replayed at those costs
    (`costed_replay`), and the replayed timeline's idle fraction is
    the measured bubble. For gpipe the fraction is F:B-ratio-invariant
    (fill and drain scale together), so measured≈static certifies the
    executed structure; for the slot-scheduled families the measured
    ratio genuinely moves the number.

    Returns {bubble_static, bubble_measured, bubble_detail} or None
    when fewer than 2 post-compile step spans exist yet (call again at
    a later log point).
    """
    import time

    import jax
    import numpy as np

    info = engine.schedule_info()
    schedule, n_mu, pp, vpp = (info["schedule"], info["n_mu"],
                               info["pp"], info["vpp"])
    static = static_bubble(schedule, n_mu, pp, vpp)
    spans = tracer.spans_named("step")[1:]  # [0] includes compile
    if len(spans) < 2:
        return None
    t_step = float(np.median([s["dur"] for s in spans])) / 1e6

    with tracer.span("bubble_calibration", schedule=schedule):
        engine.eval_loss(tokens, targets)  # compile (excluded)
        evals = []
        for _ in range(max(reps, 2)):
            t0 = time.perf_counter()
            jax.block_until_ready(
                engine.eval_loss(tokens, targets))
            evals.append(time.perf_counter() - t0)
    t_eval = float(np.median(evals))

    ticks = n_mu + pp - 1
    if schedule in ("gpipe", "1f1b") and vpp == 1:
        # both engines run `ticks` F-ticks + `ticks` B-ticks per device:
        # t_step = ticks * (c_f + c_b), with c_f measured off the eval
        # (pure-F) program — c_b is the residual
        c_f = t_eval / ticks
        c_b = max(t_step / ticks - c_f, c_f * 0.1)
        rep = costed_replay(schedule, n_mu, pp, vpp, c_f=c_f, c_b=c_b)
    else:
        c = t_step / static["makespan"]
        c_f = c_b = c
        rep = costed_replay(schedule, n_mu, pp, vpp, c_f=c, c_b=c,
                            c_w=c)
    return {
        "bubble_static": static["bubble_fraction"],
        "bubble_measured": rep["bubble_fraction"],
        "bubble_detail": {**static,
                          "measured_makespan_s": round(rep["makespan"],
                                                       6),
                          "t_step": round(t_step, 6),
                          "t_eval": round(t_eval, 6),
                          "c_f": round(c_f, 6), "c_b": round(c_b, 6)},
    }


def two_point_bubble(t1: float, t2: float) -> dict:
    """Measured bubble fraction from two fenced step timings: t1 = the
    live engine at n_mu, t2 = the calibration twin at 2*n_mu with the
    SAME per-microbatch shape (global batch doubled). The ideal step
    time at n_mu is t2 - t1; the ramp (fill + drain) is 2*t1 - t2.
    Negative ramp (timing noise on a bubble-free engine) clamps to 0.
    """
    assert t1 > 0 and t2 > 0, (t1, t2)
    ideal = t2 - t1
    ramp = 2.0 * t1 - t2
    frac = min(1.0, max(0.0, ramp / t1))
    return {"t_step": t1, "t_step_2x": t2, "t_ideal": max(ideal, 0.0),
            "t_ramp": max(ramp, 0.0), "bubble_fraction": round(frac, 6)}
