"""Per-step time attribution — the roofline waterfall.

BASELINE.md's "MFU gap accounting" answered *where the other 40%
goes* once, by hand, from an xprof capture. This module makes that
decomposition a live, self-checking metric: every spans-level step
line reconciles the MEASURED (span-fenced) step time against analytic
components computed from machinery that already exists —

- **compute**: per-op roofline time from the step program's jaxpr
  (`analysis/walker.dot_flops` matmuls at the MXU peak,
  `walker.eqn_bytes` for everything else at the HBM roofline — the
  same walk the lint rules and the collective accounting ride), with
  scan-trip multipliers and shard_map-aware device normalization;
- **exposed communication**: the walker's per-axis collective bytes
  split into exposed vs hidden by PR 4's dataflow exposure
  (`parallel/overlap.collective_exposure`) and priced at the ICI wire
  rate — hidden bytes cost nothing (they ride under compute);
- **pipeline bubble**: PR 2's `costed_replay`/`static_bubble`
  fraction, passed through;
- **host/dispatch gap**: the log window's wall time not covered by
  any fenced step span.

What is left is `attrib_unexplained_frac` — the live version of the
manual gap table, and itself the regression alarm: a step that slows
down without its analytic components changing shows up here first.

Device rates come from `flops.py`'s peak tables on TPU, where the
components are honest fractions of peak and `unexplained` IS the
residual MFU gap BASELINE.md used to account for by hand. On hosts
with no published peak (the CPU test meshes) probe-calibrated rates
set only the RELATIVE MXU/HBM split; the compute component is then
SELF-SCALED over the first two spans-level windows (the first usually
contains the compile-heavy step 0) and frozen at the second, so those
windows balance by construction (`attrib_compute_scale` records the
factor) and every later window's `unexplained_frac` measures drift
from that frozen baseline — a step that slows down without its
analytic components changing raises the alarm on any host, loaded or
not, which is the regression-alarm semantics the gap table needs
(absolute roofline truth off-TPU would just measure host-load noise).
"""

from __future__ import annotations

import time

import numpy as np

from shallowspeed_tpu import flops as _flops

# fp8-operand matmul FLOPs run the MXU at this multiple of the table/
# calibrated rate (mirrors flops.device_peak_flops's fp8 branch — the
# v7 spec's dense fp8 4.6PF vs bf16 2.3PF). Pricing the jaxpr's
# float8-operand dots at 2x is what makes the attribution gate's
# headline work: an fp8-on step's attrib_mxu_frac must SHRINK vs the
# bf16 baseline because the same dots cost half the roofline seconds.
FP8_FLOPS_RATIO = 2.0

# ------------------------------------------------------- device rates

_CALIBRATED: dict | None = None


def _median_timed(fn, reps: int = 5) -> float:
    fn()  # warmup (compile, allocator)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts))


def _calibrate() -> dict:
    """Effective device rates measured in place (cached per process),
    each as a SLOPE between two probe sizes so the per-dispatch launch
    overhead (hundreds of microseconds on a loaded CPU host — it
    dwarfs a small probe) cancels out: f32 matmuls at n=256/512 for
    FLOP/s, 1/4 MiB elementwise sweeps for bytes/s. The slope is the
    effective mid-size rate a compiled program's ops actually see;
    ops smaller than the probes run below it, which is why
    `step_waterfall` prices every matmul at the max of its compute and
    memory roofline times (a small matmul is memory-bound and the
    bytes term carries it). ICI defaults to the memory rate
    (virtual-device collectives are memcpys)."""
    global _CALIBRATED
    if _CALIBRATED is not None:
        return _CALIBRATED
    import jax
    import jax.numpy as jnp

    def slope(points):  # [(work, seconds)] -> work/s with offset removed
        (w1, t1), (w2, t2) = points
        if t2 - t1 <= 1e-9:
            return w2 / max(t2, 1e-9)  # noise floor: direct large rate
        return (w2 - w1) / (t2 - t1)

    mm_pts = []
    for n in (256, 512):
        a = jnp.asarray(np.random.default_rng(0).normal(size=(n, n)),
                        jnp.float32)
        mm = jax.jit(lambda x: x @ x)
        t_mm = _median_timed(lambda: jax.block_until_ready(mm(a)))
        mm_pts.append((2.0 * n ** 3, t_mm))
    ew_pts = []
    for m in (1 << 18, 1 << 20):  # 1 MiB, 4 MiB f32
        x = jnp.zeros((m,), jnp.float32)
        ew = jax.jit(lambda v: v * 1.0000001 + 1.0)
        t_ew = _median_timed(lambda: jax.block_until_ready(ew(x)))
        ew_pts.append((2.0 * m * 4, t_ew))  # read + write
    rate = {
        "flops": max(slope(mm_pts), 1e6),
        "hbm": max(slope(ew_pts), 1e6),
        "source": "calibrated",
    }
    rate["ici"] = rate["hbm"]
    _CALIBRATED = rate
    return rate


def recalibrate() -> dict:
    """Drop the cached calibration and probe again (tests use this to
    shrug off a host-load transient that skewed the first probe)."""
    global _CALIBRATED
    _CALIBRATED = None
    return _calibrate()


def device_rates(dtype: str = "bf16", device=None) -> dict:
    """{"flops", "hbm", "ici", "source"} for one JAX device: the
    published peaks when the device kind is known ("table"), else the
    in-place calibration ("calibrated")."""
    peak = _flops.device_peak_flops(device, dtype)
    if peak is None:
        return _calibrate()
    hbm = _flops.device_mem_bandwidth(device) or peak / 300.0
    ici = _flops.device_ici_bandwidth(device) or hbm / 4.0
    return {"flops": peak, "hbm": hbm, "ici": ici, "source": "table"}


# ---------------------------------------------------- roofline costing


def roofline_of_jaxpr(closed) -> dict:
    """Per-op roofline inputs of one program call: matmul FLOPs and
    non-matmul HBM bytes, each split by whether the op sits inside a
    `shard_map` (per-device shapes — price against ONE device's peak)
    or outside (GSPMD global shapes — price against the fleet peak).
    Scan bodies multiply by their trip count; `cond` takes the
    per-field max over branches (upper bound); `while` counts once and
    flags `approximate`; a `pallas_call` body multiplies by its grid
    size. Collectives are skipped here — their bytes are wire traffic
    (`collectives.traffic_of_jaxpr`), not HBM work.
    """
    from shallowspeed_tpu.analysis.walker import (_as_jaxpr, dot_flops,
                                                  eqn_bytes, sub_jaxprs)
    from shallowspeed_tpu.telemetry.collectives import _COLLECTIVES

    acc = {"flops_shard": 0, "flops_global": 0,
           "flops_fp8_shard": 0, "flops_fp8_global": 0,
           "dot_bytes_shard": 0, "dot_bytes_global": 0,
           "bytes_shard": 0, "bytes_global": 0}
    state = {"approx": False}

    def pallas_grid(eqn) -> int:
        gm = eqn.params.get("grid_mapping")
        grid = getattr(gm, "grid", ()) or ()
        n = 1
        for g in grid:
            if isinstance(g, (int, np.integer)):
                n *= int(g)
            else:
                state["approx"] = True
        return n

    def walk(jaxpr, trips: int, in_shmap: bool, out: dict):
        j = _as_jaxpr(jaxpr)
        for eqn in j.eqns:
            name = eqn.primitive.name
            if name in _COLLECTIVES:
                continue
            subs = sub_jaxprs(eqn)
            if subs:
                n = trips
                if name == "scan":
                    length = eqn.params.get("length")
                    if length is None:
                        state["approx"] = True
                    else:
                        n = trips * int(length)
                elif name == "while":
                    state["approx"] = True
                elif name == "pallas_call":
                    n = trips * pallas_grid(eqn)
                    state["approx"] = True  # tile reuse is not modeled
                child_sh = in_shmap or name == "shard_map"
                if name == "cond":
                    # one branch runs: per-field max is an upper bound
                    trials = []
                    for s in subs:
                        trial = {k: 0 for k in acc}
                        walk(s, n, child_sh, trial)
                        trials.append(trial)
                    if len({tuple(sorted(t.items()))
                            for t in trials}) > 1:
                        state["approx"] = True
                    for k in out:
                        out[k] += max(t[k] for t in trials)
                else:
                    for s in subs:
                        walk(s, n, child_sh, out)
                continue
            fl = dot_flops(eqn)
            key = "shard" if in_shmap else "global"
            if fl:
                out["flops_" + key] += fl * trips
                # float8-operand dots are the quantized matmuls
                # (ops/matmul.fp8_dense) — tracked as a subset so
                # roofline_seconds can price them at FP8_FLOPS_RATIO
                if any(str(getattr(v.aval, "dtype", "")
                           ).startswith("float8")
                       for v in eqn.invars):
                    out["flops_fp8_" + key] += fl * trips
                out["dot_bytes_" + key] += eqn_bytes(eqn) * trips
            else:
                out["bytes_" + key] += eqn_bytes(eqn) * trips

    walk(closed.jaxpr, 1, False, acc)
    acc["approximate"] = state["approx"]
    return acc


# --------------------------------------------------------- the waterfall


def roofline_seconds(roof: dict, rates: dict,
                     n_devices: int = 1) -> dict:
    """Roofline seconds per step: matmuls take the max of their
    compute and operand-byte times (a small matmul is memory-bound —
    its FLOPs alone would undercount it), everything else the HBM
    roofline. Aggregated per locality bucket (max of sums, a mild
    lower bound on the per-op sum of maxes)."""
    nd = max(1, int(n_devices))
    mxu = hbm = 0.0
    for key, div in (("shard", 1), ("global", nd)):
        fl = roof.get("flops_" + key, 0)
        fp8 = min(roof.get("flops_fp8_" + key, 0), fl)
        # fp8-operand dots run at FP8_FLOPS_RATIO x the base rate
        flop_s = ((fl - fp8) / rates["flops"]
                  + fp8 / (rates["flops"] * FP8_FLOPS_RATIO))
        mxu += max(flop_s,
                   roof.get("dot_bytes_" + key, 0) / rates["hbm"]) / div
        hbm += roof.get("bytes_" + key, 0) / rates["hbm"] / div
    return {"mxu_s": mxu, "hbm_s": hbm}


def step_waterfall(t_step: float, roofline: dict | None = None,
                   coll_bytes: int = 0,
                   exposed_frac: float | None = None,
                   bubble_fraction: float | None = None,
                   host_gap: float | None = None,
                   n_devices: int = 1, rates: dict | None = None,
                   dtype: str = "bf16",
                   compute_scale: float | None = None) -> dict:
    """Reconcile one measured (fenced) step time `t_step` (seconds)
    against the analytic components; returns the `attrib_*` step-line
    fields (telemetry schema v4).

    - `roofline`: `roofline_of_jaxpr` output for the step program(s).
    - `coll_bytes`: per-device collective payload bytes per step
      (`collectives` convention); `exposed_frac` splits them into
      exposed (priced at the wire rate) vs hidden (free — overlapped
      under compute). None means no exposure info: all bytes count as
      exposed (conservative).
    - `bubble_fraction`: the pipeline's measured (or static) bubble.
    - `host_gap`: seconds of the window not inside any fenced step
      span, already divided down to PER-STEP terms by the caller.
    - `compute_scale`: the frozen self-calibration factor applied to
      the compute component on rate-calibrated hosts (RunTelemetry
      derives it at the first window; None = absolute rates, the TPU
      path).

    `attrib_unexplained_frac = max(0, 1 - sum(components))` — when the
    components sum past 1 (the byte model is an unfused upper bound)
    unexplained clamps to 0, the safe direction for an alarm.
    """
    assert t_step > 0.0, t_step
    if rates is None:
        rates = device_rates(dtype=dtype)
    out = {"attrib_t_step_ms": round(t_step * 1e3, 3),
           "attrib_rates_source": rates.get("source", "table")}
    explained = 0.0
    if roofline is not None:
        secs = roofline_seconds(roofline, rates, n_devices)
        scale = 1.0 if compute_scale is None else float(compute_scale)
        comp = scale * (secs["mxu_s"] + secs["hbm_s"]) / t_step
        out["attrib_compute_frac"] = round(comp, 4)
        out["attrib_mxu_frac"] = round(scale * secs["mxu_s"] / t_step,
                                       4)
        if compute_scale is not None:
            out["attrib_compute_scale"] = round(scale, 4)
        explained += comp
    if coll_bytes:
        frac = 1.0 if exposed_frac is None else float(exposed_frac)
        wire = coll_bytes * frac / rates["ici"] / t_step
        out["attrib_comm_exposed_frac"] = round(wire, 4)
        explained += wire
    if bubble_fraction is not None:
        out["attrib_bubble_frac"] = round(float(bubble_fraction), 4)
        explained += float(bubble_fraction)
    if host_gap is not None:
        hf = max(0.0, float(host_gap)) / t_step
        out["attrib_host_frac"] = round(hf, 4)
        explained += hf
    out["attrib_unexplained_frac"] = round(max(0.0, 1.0 - explained), 4)
    return out


def window_step_spans(events, names=("step", "batch")) -> list[float]:
    """Fenced step-span durations (seconds) in a tracer event window:
    top-level "X" spans named `step` (the compiled engines) or `batch`
    (the pipeline VM). Nested phase spans (grads/update/per-op) are
    excluded by name."""
    return [e["dur"] / 1e6 for e in events
            if e.get("ph") == "X" and e.get("name") in names
            and e.get("dur")]
