"""Trace/metrics JSONL schema — the committed-artifact gate.

Two line dialects share `docs_runs/*.jsonl`:

- METRICS lines (`metrics.MetricsLogger`): {"event": <type>, ...} with
  per-type required fields (a "step" line must carry step/loss/
  tokens_per_sec — and, when telemetry was on, its telemetry fields
  must be well-typed).
- SPAN lines (`telemetry.trace.Tracer`): Chrome-trace-shaped events
  {"name", "ph": "X"|"i"|"C", "ts"[, "dur"], "args"} in microseconds.

`validate_line` returns a list of problems (empty = valid);
`validate_file` maps them to line numbers. The pre-commit hook runs
`python -m shallowspeed_tpu.telemetry --validate <files>` over any
committed docs_runs JSONL so a snapshot that drifts from the schema
fails at commit time, not at the next reader.
"""

from __future__ import annotations

import json
from pathlib import Path

# Version of the line dialects this module describes. 1 = PR-2 (spans +
# hardware telemetry step fields); 2 = PR-2 plus the training-health
# extension (health_* step fields, the "health" event); 3 = v2 plus
# the comm/compute-overlap fields (`exposed_comm_frac` /
# `overlap_ratio` — the step program's dataflow communication
# exposure, `parallel/overlap.collective_exposure` — and the engine's
# `overlap` mode flag); 4 = v3 plus the time-attribution waterfall
# (`attrib_*` step fields, `telemetry/attribution.py`), the goodput
# ledger (`"ledger"` events, `telemetry/goodput.py`) and the absolute
# `wall` timestamp every metrics line now carries so the ledger
# reducer can account wall clock ACROSS process restarts. Writers
# stamp it on their run_start line (metrics.MetricsLogger); 5 = v4
# plus the chaos/recovery extension (`shallowspeed_tpu/chaos.py`,
# round 10): `"fault"` events stamped at every injected fault, and
# the `fail_class` field on supervisor-stamped ledger lines
# (restart_downtime / poison_step_abort / supervisor_abort) that the
# goodput reducer turns into per-failure-class MTTR; 6 = v5 plus the
# serving extension (round 11, `shallowspeed_tpu/serving/`):
# `"request"` events — one per completed request, carrying the
# per-request SLO record (ttft_ms, tpot_ms, queue depth at
# completion, preemption count, tokens in/out) the `--goodput`
# reducer turns into p50/p95 ttft/tpot — and the serving fields the
# periodic `"generate"` tick lines grew (queue_depth, active_slots,
# free_blocks, the live-blocks HBM sweep); 7 = v6 plus the live
# telemetry plane (round 12, `telemetry/monitor.py` + `sketch.py`):
# `"monitor"` events — periodic serializations of the streaming
# log-bucketed histogram sketches (step time, ttft/tpot, tok/s, queue
# depth, free blocks), mergeable across processes/stanzas so the
# supervisor and `--goodput` can recombine them into whole-run
# quantiles — and `"alert"` events stamped by the SLO burn-rate
# evaluator (--slo) at every state transition; 8 = v7 plus the fleet
# observability extension (round 13, `telemetry/fleet.py` +
# `serving/engine.py` lifecycle tracing): `"straggler"` events — a
# FleetCollector's sustained-divergence verdict on one replica's
# per-metric quantiles vs the fleet median (RobustEWMA-scored),
# naming the replica — and `"lifecycle"` events — one line per
# serving-request phase transition (submit -> queued -> admitted ->
# prefill chunk k -> decoding -> preempted -> requeued -> finished)
# that `report.request_timeline` reconstructs into per-request
# timelines; span lines additionally allow ph "M" (Chrome metadata:
# the named per-request trace tracks); 9 = v8 plus the fast-decode
# extension (round 14, speculative decoding in `serving/engine.py`):
# "request" lines may carry the per-request speculation record
# (spec_drafted / spec_accepted), "generate" tick lines grow typed
# serving + speculation fields (queue_depth, active_slots,
# free_blocks, blocks_touched, bytes_per_tick, hbm_gbps, spec_drafted,
# spec_accepted, spec_accept_rate — the acceptance-rate telemetry the
# monitor surfaces at /status.json), and "ledger" lines allow the
# `table_rebucket` stamp's width/prev_width/tick fields (a request's
# block table crossing a geometric width bucket re-traces the decode
# tick; the stamp keeps attribution from booking it as unexplained).
# 10 = v9 plus the fleet-serving extension (round 15, the router —
# `shallowspeed_tpu/serving/router.py` + `router.py`): "route" events
# (one per dispatch: request id -> replica, with the admission score),
# "failover" events (one per seeded idempotent re-dispatch after a
# replica death / progress timeout: from/to replicas, reason, tokens
# already emitted), "scale" events (autoscale decisions: action
# up/drain/down, replica, reason, the burn that triggered), `replica`
# + `state` fields on "ledger" lines (per-replica restart_downtime
# stamps the fleet MTTR/availability reduction reads; circuit-breaker
# open/half_open/closed transitions), `replica`/`failovers` on the
# router's fleet-edge "request" records, and `resumed` on "lifecycle"
# submit lines (a continuation re-prefilled from another engine).
# 11 = v10 plus the distributed-tracing extension (round 16,
# `telemetry/tracing.py`): every metrics line may carry `mono` — the
# monotonic half of a per-process (wall, monotonic) clock pair the
# cross-process stitcher uses to fit one offset per process stanza —
# and the trace-context fields ride the request-path events: `trace`
# (one id per fleet request, minted by `Router.submit` or by
# `ServingEngine.submit` for standalone serving), `span` (this
# process's span id for the request / dispatch attempt), `parent`
# (the upstream span id), and `attempt` (0-based cross-engine
# dispatch attempt — a failover re-dispatch increments it, which is
# what lets `report.request_timeline` key its reduction on
# (rid, attempt) instead of interleaving two attempts' seq counters).
# "route"/"failover" events additionally carry the dispatch span they
# minted plus the router's pre-POST `dispatch_wall`/`dispatch_mono`
# clock pair (the stamp that happens-before the replica's lifecycle
# "submit" — the skew fit's lower bound); "route" grows `wait_ms`
# (router submit -> dispatch) so the stitcher can recover the
# fleet-edge submit time.
# 12 = v11 plus the continuous-profiling extension (round 17,
# `telemetry/profiler.py`): `"profile"` events — periodic CUMULATIVE
# snapshots of the host sampling profiler (folded-stack top-K counts
# + an exact `other` remainder, the span-tagged `phases` breakdown,
# `step_samples` for the attrib_host_frac cross-check, `max_gap_ms`
# the sampler-liveness bound) that merge across replicas like the v7
# sketch snapshots: the LAST event per process stanza is that
# stanza's whole story, and `python -m shallowspeed_tpu.telemetry
# --profile <log> --out flame.json` reduces them to a flamegraph.
# 13 = v12 plus the numerics-observatory extension (round 18,
# `telemetry/numerics.py` + the fp8 numerics pack): `num_*` step
# fields — per-step worst clamp fractions (num_overflow_max /
# num_underflow_max), the live delayed-scale extrema (num_scale_min /
# num_amax_max), the RobustEWMA scale-drift z and sign-flip
# oscillation score (num_drift_z / num_osc), the latest shadow-parity
# sample vs the frozen master-precision oracle (num_parity_loss_rel /
# num_parity_grad_relmax) with its cumulative sample count
# (num_shadow_total), the live compute precision (num_precision:
# "fp8" | "bf16" — flips when the guard takes the bf16 fallback), and
# num_verdicts (the drained scale_collapse / parity_drift window,
# mirroring health_verdicts); "ledger" lines allow the
# `shadow_parity` kind's seconds (goodput-excluded oracle steps).
# 14 = v13 plus the prefix-caching extension (round 19,
# `serving/cache.PrefixIndex` + sticky routing): "request" lines grow
# `prefix_hit_blocks` (shared blocks mapped from the index across the
# request's admission stints) and `prefill_skipped_tokens` (prefill
# work those mappings avoided); "lifecycle" lines allow the
# `prefill_cached` phase (with `blocks` = matched block count) so
# `report.request_timeline` books the skipped prefill explicitly;
# "generate" tick lines grow the `prefix_hit_rate` / `cold_blocks` /
# `prefix_blocks` gauges /status.json + /metrics + the fleet view
# surface; "route" lines may carry the sticky `affinity` bonus.
# 15 = v14 plus the memory-observatory extension (round 20,
# `telemetry/memory.py`): "step" lines may carry the per-owner HBM
# decomposition (`hbm_owned_mib`: registry-owner name -> resident MiB,
# `hbm_untracked_mib`: the unclaimed residual — the leak alarm), the
# host-side series (`host_rss_mib`), and `mem_verdicts` (the drained
# MemoryWatch mem_leak / mem_drift window, mirroring health_verdicts);
# "generate" tick lines grow the capacity-plane gauges (`live_blocks`,
# `blocks_needed` — blocks required to finish every admitted request
# at its max-token budget — and `headroom_blocks` = free + cold -
# still-needed, negative when the replica is overcommitted); "ledger"
# lines allow the `oom` stamp's typed OutOfBlocks payload (requested /
# free / cold / live block counts + the requester `id`) written at
# every recovered block-exhaustion event.
# The validator accepts ALL dialects — every versioned field is
# optional, so committed v1-v14 artifacts (no version stamp / no
# health / overlap / attrib / wall / fault / request / monitor /
# straggler / lifecycle / speculation / routing / tracing / profile /
# numerics / prefix / memory fields) keep validating unchanged.
SCHEMA_VERSION = 15

_NUM = (int, float)

# metrics dialect: per-event required fields and their types
_METRIC_EVENTS = {
    "run_start": {},
    "epoch": {"epoch": int, "epoch_seconds": _NUM},
    "final": {"accuracy": _NUM, "total_seconds": _NUM},
    "step": {"step": int, "loss": _NUM, "tokens_per_sec": _NUM},
    "val": {"step": int, "val_loss": _NUM},
    "moe_router": {"step": int, "drop_fraction": _NUM},
    "bubble": {"bubble_static": _NUM},
    "telemetry": {},
    "health": {"step": int},   # HealthMonitor verdict/summary lines
    # schema v4: goodput-ledger lines (telemetry/goodput.py) — stamped
    # by metrics.StepRates pauses, the drivers, and the elastic
    # supervisor (restart downtime), all into the same JSONL
    "ledger": {"kind": str},
    # schema v4: decode throughput + HBM-roofline line (models/
    # generate.decode_report via the LM driver)
    "generate": {"tokens_per_sec": _NUM},
    # schema v5: chaos fault-injection stamps (shallowspeed_tpu/
    # chaos.py) — the forensic record of what was injected when,
    # fsync'd into the same JSONL the step lines live in
    "fault": {"kind": str},
    # schema v6: one line per COMPLETED serving request
    # (serving/engine.ServingEngine._finish) — the per-request SLO
    # record the --goodput reducer turns into ttft/tpot percentiles
    "request": {"id": str, "ttft_ms": _NUM, "tokens_in": int,
                "tokens_out": int},
    # schema v7: periodic streaming-sketch snapshot (telemetry/
    # monitor.Monitor) — per-metric log-bucketed histograms,
    # mergeable across processes into whole-run quantiles
    "monitor": {"sketches": dict},
    # schema v7: SLO burn-rate state transition (fire / escalate /
    # resolve) from the --slo evaluator
    "alert": {"slo": str, "state": str},
    # schema v8: a FleetCollector's straggler verdict — one replica's
    # per-metric quantile sustained a divergence from the fleet median
    # (telemetry/fleet.py); `state` is "firing" or "resolved"
    "straggler": {"replica": str, "metric": str, "state": str},
    # schema v8: one line per serving-request phase transition
    # (serving/engine.ServingEngine._lifecycle) — the per-request span
    # timeline `report.request_timeline` reconstructs
    "lifecycle": {"id": str, "phase": str},
    # schema v10: one line per router dispatch decision — which
    # replica got the request (serving/router.Router._dispatch)
    "route": {"id": str, "replica": str},
    # schema v10: one line per seeded idempotent re-dispatch — a
    # request whose replica died (or stalled past the progress
    # timeout) continuing, token-identically, elsewhere
    "failover": {"id": str, "replica": str, "reason": str},
    # schema v10: one line per autoscale decision (up / drain / down)
    "scale": {"action": str},
    # schema v12: periodic cumulative host-profiler snapshot
    # (telemetry/profiler.SamplingProfiler) — folded-stack counts +
    # span-tagged phase buckets, mergeable across replicas
    "profile": {"samples": int},
}

# optional typed fields on a "ledger" line (`fail_class`: the
# supervisor's failure classification riding its restart stamps;
# width/prev_width/tick: the v9 `table_rebucket` retrace stamp;
# replica/state: the v10 router stamps — per-replica restart downtime
# and circuit-breaker transitions)
_LEDGER_OPTIONAL = {"seconds": _NUM, "count": int, "fail_class": str,
                    "width": int, "prev_width": int, "tick": int,
                    "replica": str, "state": str,
                    # v15: the `oom` stamp — a recovered OutOfBlocks'
                    # typed payload (allocator counts at the raise; the
                    # requester rid rides as `id`)
                    "requested": int, "free": int, "cold": int,
                    "live": int, "id": str}

# optional typed fields on a "fault" line
_FAULT_OPTIONAL = {"step": int, "save": int, "seconds": _NUM,
                   "leaf": int, "fault_id": str, "point": str,
                   "path": str, "mode": str}

# optional typed fields on a "request" line (schema v6; spec_* are the
# v9 speculative-decoding record). tpot_ms is absent (not null) for
# single-token generations — there is no inter-token interval to
# average
_REQUEST_OPTIONAL = {"tpot_ms": _NUM, "e2e_ms": _NUM, "wait_ms": _NUM,
                     "queue_depth": int, "preempted": int,
                     "spec_drafted": int, "spec_accepted": int,
                     # v10: the router's fleet-edge request records
                     "replica": str, "failovers": int,
                     # v11: trace context (telemetry/tracing.py)
                     "trace": str, "span": str, "attempt": int,
                     # v14: prefix-cache record (serving/cache)
                     "prefix_hit_blocks": int,
                     "prefill_skipped_tokens": int}

# optional typed fields on a "generate" line (schema v9: the serving
# tick fields written since v6 become typed, plus the speculation
# window tallies — spec_accept_rate is what /status.json surfaces)
_GENERATE_OPTIONAL = {"queue_depth": int, "active_slots": int,
                      "free_blocks": int, "blocks_touched": int,
                      "bytes_per_tick": int, "hbm_gbps": _NUM,
                      "spec_drafted": int, "spec_accepted": int,
                      "spec_accept_rate": _NUM,
                      # v14: prefix-cache window gauges
                      "prefix_hit_rate": _NUM, "cold_blocks": int,
                      "prefix_blocks": int,
                      # v15: capacity-plane gauges (memory
                      # observatory) — headroom_blocks goes NEGATIVE
                      # when admitted max-token budgets overcommit the
                      # pool, which is the shed-before-evict signal
                      "live_blocks": int, "blocks_needed": int,
                      "headroom_blocks": int}

# optional typed fields on the schema-v7 events
_MONITOR_OPTIONAL = {"counters": dict, "rel_err": _NUM}
_ALERT_OPTIONAL = {"severity": str, "metric": str, "burn_fast": _NUM,
                   "burn_slow": _NUM, "value": _NUM,
                   "threshold": _NUM, "step": int}

# optional typed fields on the schema-v8 events
_STRAGGLER_OPTIONAL = {"ratio": _NUM, "z": _NUM, "replica_q": _NUM,
                       "fleet_q": _NUM, "q": int, "rounds": int}
_LIFECYCLE_OPTIONAL = {"seq": int, "slot": int, "tick": int,
                       "chunk": int, "tokens": int, "prev": str,
                       "ms_in_prev": _NUM, "resumed": int,
                       # v11: trace context — one trace id per fleet
                       # request, one span per engine attempt, parent
                       # = the router's dispatch span, attempt = the
                       # 0-based cross-engine dispatch counter
                       "trace": str, "span": str, "parent": str,
                       "attempt": int,
                       # v14: `prefill_cached` phase payload — shared
                       # blocks mapped from the prefix index at admit
                       "blocks": int}

# optional typed fields on the schema-v10 routing events (trace/span/
# parent + route wait_ms are the v11 tracing extension;
# dispatch_wall/dispatch_mono are the router's PRE-POST clock pair —
# the only router stamp that happens-before the replica's lifecycle
# "submit", which the stitcher's skew fit uses as its lower bound)
_ROUTE_OPTIONAL = {"queue_depth": int, "score": _NUM,
                   "trace": str, "span": str, "parent": str,
                   "wait_ms": _NUM,
                   "dispatch_wall": _NUM, "dispatch_mono": _NUM,
                   # v14: the sticky prefix-affinity bonus folded into
                   # this dispatch's ranking (0.0 = no locality)
                   "affinity": _NUM}
_FAILOVER_OPTIONAL = {"from": str, "tokens_done": int, "attempt": int,
                      "trace": str, "span": str, "parent": str,
                      "dispatch_wall": _NUM, "dispatch_mono": _NUM}
_SCALE_OPTIONAL = {"replica": str, "reason": str, "n_replicas": int,
                   "burn": _NUM}

# optional typed fields on the schema-v12 "profile" snapshot:
# `folded` maps "frame;frame;..." strings to exact sample counts
# (top-K; `other` is the exact remainder so counts still sum to
# `samples`), `phases` maps innermost span-tag names to counts,
# `step_samples` counts samples inside a step/batch span (the
# attrib_host_frac cross-check), `max_gap_ms` is the worst
# inter-sample gap (the GIL-safety bound the tests pin)
_PROFILE_OPTIONAL = {"step_samples": int, "hz": _NUM, "top_k": int,
                     "folded": dict, "other": int, "phases": dict,
                     "max_gap_ms": _NUM, "window_s": _NUM,
                     "mode": str, "captures": list}

# telemetry fields a step line MAY carry; when present they must type
_STEP_TELEMETRY = {
    "compiles": int, "recompiles": int,
    "hbm_live_mib": _NUM, "hbm_static_mib": _NUM,
    "hbm_alloc_peak_mib": _NUM, "hbm_within_bound": bool,
    "coll_bytes_per_step": int, "coll_bytes_by_axis": dict,
    "coll_bytes_measured": dict,
    "coll_gbps": _NUM, "bubble_static": _NUM, "bubble_measured": _NUM,
    # --- schema v2: training-health fields (telemetry/health.py)
    "health_grad_norm": _NUM, "health_param_norm": _NUM,
    "health_update_ratio": _NUM, "health_nonfinite": int,
    "health_skipped_total": int, "health_verdicts": list,
    "health_groups": dict,
    # --- schema v3: comm/compute-overlap fields (parallel/overlap.py)
    "exposed_comm_frac": _NUM, "overlap_ratio": _NUM, "overlap": bool,
    # --- schema v4: time-attribution waterfall (telemetry/
    # attribution.py) — fractions of the measured (fenced) step time
    "attrib_compute_frac": _NUM, "attrib_mxu_frac": _NUM,
    "attrib_comm_exposed_frac": _NUM, "attrib_bubble_frac": _NUM,
    "attrib_host_frac": _NUM, "attrib_unexplained_frac": _NUM,
    "attrib_t_step_ms": _NUM, "attrib_rates_source": str,
    "attrib_compute_scale": _NUM,
    # --- schema v13: numerics-observatory fields (telemetry/
    # numerics.py) — the fp8 pack's host-side reduction + the
    # shadow-parity series vs the frozen master-precision oracle
    "num_overflow_max": _NUM, "num_underflow_max": _NUM,
    "num_scale_min": _NUM, "num_amax_max": _NUM,
    "num_drift_z": _NUM, "num_osc": _NUM,
    "num_parity_loss_rel": _NUM, "num_parity_grad_relmax": _NUM,
    "num_shadow_total": int, "num_precision": str,
    "num_verdicts": list,
    # --- schema v15: memory-observatory fields (telemetry/memory.py)
    # — the per-owner HBM decomposition (owner name -> resident MiB),
    # the unclaimed residual, host RSS, and the drained MemoryWatch
    # verdict window
    "hbm_owned_mib": dict, "hbm_untracked_mib": _NUM,
    "host_rss_mib": _NUM, "mem_verdicts": list,
}

# "M" (schema v8): Chrome metadata events — the named per-request
# lifecycle tracks (thread_name) the serving engine emits
_SPAN_PH = {"X", "i", "C", "M"}


def validate_line(rec: dict) -> list[str]:
    """Problems with one parsed JSONL record (empty list = valid)."""
    if not isinstance(rec, dict):
        return ["line is not a JSON object"]
    if "event" in rec:
        return _validate_metric(rec)
    if "ph" in rec or "name" in rec:
        return _validate_span(rec)
    return ["neither a metrics line ('event') nor a span line ('ph')"]


def _validate_metric(rec: dict) -> list[str]:
    probs = []
    ev = rec["event"]
    if ev not in _METRIC_EVENTS:
        return [f"unknown metrics event {ev!r}"]
    if ev == "run_start" and "schema_version" in rec \
            and (not isinstance(rec["schema_version"], int)
                 or isinstance(rec["schema_version"], bool)
                 or rec["schema_version"] < 1):
        probs.append("run_start: schema_version must be a positive int")
    for field, typ in _METRIC_EVENTS[ev].items():
        if field not in rec:
            probs.append(f"{ev}: missing field {field!r}")
        elif not isinstance(rec[field], typ) \
                or isinstance(rec[field], bool):
            probs.append(f"{ev}: field {field!r} is "
                         f"{type(rec[field]).__name__}, want {typ}")
    if ev == "step":
        for field, typ in _STEP_TELEMETRY.items():
            if field in rec and rec[field] is not None \
                    and not isinstance(rec[field], typ):
                probs.append(f"step: telemetry field {field!r} is "
                             f"{type(rec[field]).__name__}")
    if ev == "ledger":
        for field, typ in _LEDGER_OPTIONAL.items():
            if field in rec and (not isinstance(rec[field], typ)
                                 or isinstance(rec[field], bool)):
                probs.append(f"ledger: field {field!r} is "
                             f"{type(rec[field]).__name__}")
    if ev == "fault":
        for field, typ in _FAULT_OPTIONAL.items():
            if field in rec and (not isinstance(rec[field], typ)
                                 or isinstance(rec[field], bool)):
                probs.append(f"fault: field {field!r} is "
                             f"{type(rec[field]).__name__}")
    if ev == "request":
        for field, typ in _REQUEST_OPTIONAL.items():
            if field in rec and (not isinstance(rec[field], typ)
                                 or isinstance(rec[field], bool)):
                probs.append(f"request: field {field!r} is "
                             f"{type(rec[field]).__name__}")
    if ev == "generate":
        for field, typ in _GENERATE_OPTIONAL.items():
            if field in rec and (not isinstance(rec[field], typ)
                                 or isinstance(rec[field], bool)):
                probs.append(f"generate: field {field!r} is "
                             f"{type(rec[field]).__name__}")
    if ev in ("monitor", "alert", "straggler", "lifecycle", "route",
              "failover", "scale", "profile"):
        opt = {"monitor": _MONITOR_OPTIONAL, "alert": _ALERT_OPTIONAL,
               "straggler": _STRAGGLER_OPTIONAL,
               "lifecycle": _LIFECYCLE_OPTIONAL,
               "route": _ROUTE_OPTIONAL,
               "failover": _FAILOVER_OPTIONAL,
               "scale": _SCALE_OPTIONAL,
               "profile": _PROFILE_OPTIONAL}[ev]
        for field, typ in opt.items():
            if field in rec and (not isinstance(rec[field], typ)
                                 or isinstance(rec[field], bool)):
                probs.append(f"{ev}: field {field!r} is "
                             f"{type(rec[field]).__name__}")
    # schema v4: any metrics line may carry an absolute `wall` stamp
    if "wall" in rec and not isinstance(rec["wall"], _NUM):
        probs.append("metrics: 'wall' is not numeric")
    # schema v11: ... and the monotonic half of the clock pair
    if "mono" in rec and not isinstance(rec["mono"], _NUM):
        probs.append("metrics: 'mono' is not numeric")
    return probs


def _validate_span(rec: dict) -> list[str]:
    probs = []
    if "name" not in rec or not isinstance(rec["name"], str):
        probs.append("span: missing/non-string 'name'")
    ph = rec.get("ph")
    if ph not in _SPAN_PH:
        probs.append(f"span: ph {ph!r} not in {sorted(_SPAN_PH)}")
    if not isinstance(rec.get("ts"), _NUM):
        probs.append("span: missing/non-numeric 'ts'")
    if ph == "X" and not isinstance(rec.get("dur"), _NUM):
        probs.append("span: 'X' event without numeric 'dur'")
    if "args" in rec and not isinstance(rec["args"], dict):
        probs.append("span: 'args' is not an object")
    return probs


def parse_metrics_jsonl(path) -> list[dict]:
    """Read one metrics JSONL tolerantly: skip blank lines and
    unparseable JSON (a torn tail mid-write), keep only dicts carrying
    an "event" key — the one line-level dialect every offline reducer
    (goodput, the trace stitcher) consumes. Shared here so hardening
    lands in both."""
    out = []
    for line in Path(path).read_text().splitlines():
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "event" in rec:
            out.append(rec)
    return out


def validate_file(path) -> list[str]:
    """All problems in one JSONL file, prefixed path:lineno."""
    path = Path(path)
    out = []
    for i, line in enumerate(path.read_text().splitlines(), 1):
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError as e:
            out.append(f"{path}:{i}: not JSON ({e.msg})")
            continue
        out.extend(f"{path}:{i}: {p}" for p in validate_line(rec))
    return out
