"""Continuous profiling plane — the fourth observability pillar.

The waterfall (`telemetry/attribution.py`) prices every fenced step
into compute/comm/bubble/host fractions, but `attrib_host_frac` is an
opaque blob: when host time grows — the exact failure mode host-driven
pipeline schedules suffer at scale (PipeDream, arXiv 1806.03377) —
nothing says *where* it went. Four parts close that:

- **Always-on host sampler** (`SamplingProfiler`): a daemon thread
  reads the MAIN thread's Python stack via `sys._current_frames()` at
  ~67 Hz (default off; ``--profile {off,host,host+device}``), folds it
  root->leaf into a `frame;frame;...` string, and aggregates exact
  counts per folded stack. Periodic schema-v12 ``"profile"`` events
  carry the CUMULATIVE top-K + an exact `(other)` remainder — like the
  v7 sketch snapshots, the last event per process stanza is the whole
  story and events MERGE across replicas by summing counts. Reduce to
  a d3-flamegraph-shaped JSON with ``python -m shallowspeed_tpu
  .telemetry --profile <log> --out flame.json``.
- **Span-tagged attribution** (`tag` + the tracer phase hook): every
  sample is labelled with the innermost active phase — tracer spans
  (step/grads/update) auto-push via `trace.PHASE_HOOKS`; the serving
  engine brackets its scheduler phases (data-load, block-alloc,
  prefill-chunk, sampling, decode-tick, logging, gateway) with
  `tag(...)`, which costs one module-global check when no profiler
  runs (the `_NULL_SPAN` pattern). `phases` decomposes the host blob
  into named buckets; `step_samples` (stack contains a step/batch
  span) is the sampler's own estimate of in-step time, cross-checked
  against the waterfall's `attrib_host_frac` in tests.
- **Trigger-driven capture windows** (`CaptureWindow`): a critical SLO
  burn, an anomaly verdict, a chaos fault, or a fleet straggler
  verdict arms ONE bounded high-rate window (~200 Hz for ~0.5 s) via
  the existing `Monitor.alert_listeners` / `chaos.add_observer` /
  flight-recorder plumbing — deduped by (reason, step), capped like
  flight dumps, plus a cooldown so a fault and the SLO burn it causes
  yield one capture, not two. Dumps land as ``profcap_<step>.json``
  next to ``flightrec_*``, naming the dominant tagged phase. At
  ``host+device`` the window also wraps a `jax.profiler` device trace
  (skipped when a whole-run ``--profile-dir`` trace is already live —
  xprof sessions do not nest).
- **Fleet surface**: `Monitor.profile_payload` serves GET
  /profile.json on the duck-typed StatusServer; `fleet.FleetCollector`
  polls it per replica and merges the folded stacks into one
  replica-prefixed fleet flamegraph; `--goodput` grows a `profiling`
  block naming the top host-time frames per replica.

Safety contract: the sampler never touches jax (pure stdlib), so a
profiled run compiles the SAME executables as an unprofiled one (zero
new jit entry points, zero recompiles — pinned); reading frames under
the GIL is O(stack depth), so the sampler cannot block the main thread
beyond a bounded beat — `max_gap_ms` records the worst inter-sample
gap and the test suite asserts it stays bounded.
"""

from __future__ import annotations

import contextlib
import json
import sys
import threading
import time
from collections import Counter
from pathlib import Path

MODES = ("off", "host", "host+device")

DEFAULT_HZ = 67.0          # off the 50/100 round numbers: a sampler
                           # phase-locked to a 10 ms scheduler beat
                           # aliases; 67 Hz keeps ~15 ms spacing
DEFAULT_TOP_K = 40
OTHER_KEY = "(other)"
UNTAGGED = "(untagged)"
# tag names whose presence ANYWHERE in the stack marks a sample as
# inside a fenced step span (attribution.window_step_spans' names)
STEP_TAGS = ("step", "batch")

# ------------------------------------------------------------- tagging
#
# Module-level registry (thread ident -> stack of phase names) instead
# of the tracer's threading.local span stacks: the SAMPLER thread must
# read the MAIN thread's innermost phase, and threading.local is by
# design invisible cross-thread. Mutated only by the owning thread;
# the sampler reads racily under the GIL (a torn read costs one
# mislabelled sample, never a crash).

_TAGS: dict[int, list] = {}
_ACTIVE = 0     # number of running SamplingProfilers; tag() gates on it


class _NullTag:
    """Shared no-op: the `tag()` fast path when no profiler runs."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_TAG = _NullTag()


class _Tag:
    __slots__ = ("name", "_ident")

    def __init__(self, name: str):
        self.name = name
        self._ident = None

    def __enter__(self):
        self._ident = threading.get_ident()
        _TAGS.setdefault(self._ident, []).append(self.name)
        return self

    def __exit__(self, *exc):
        stack = _TAGS.get(self._ident)
        if stack:
            if stack and stack[-1] == self.name:
                stack.pop()
            else:
                # a profiler started/stopped mid-span can leave the
                # stack misaligned once — recover instead of corrupting
                try:
                    stack.remove(self.name)
                except ValueError:
                    pass
        return False


def tag(name: str):
    """Phase-tag context manager for host-attribution buckets. Returns
    a shared no-op unless a profiler is running, so engine hot loops
    may call it unconditionally."""
    if not _ACTIVE:
        return _NULL_TAG
    return _Tag(name)


# package-level re-export alias (`telemetry.profiler_tag`): `tag` is
# too generic a name to surface at the package root unqualified
profiler_tag = tag


def _push_phase(name: str) -> None:
    _TAGS.setdefault(threading.get_ident(), []).append(name)


def _pop_phase(name: str) -> None:
    stack = _TAGS.get(threading.get_ident())
    if stack:
        if stack[-1] == name:
            stack.pop()
        else:
            try:
                stack.remove(name)
            except ValueError:
                pass


def _install_hooks() -> None:
    """Tracer spans feed the phase registry while any profiler runs —
    a `step` span tags its samples without the drivers changing."""
    global _ACTIVE
    _ACTIVE += 1
    if _ACTIVE == 1:
        from shallowspeed_tpu.telemetry import trace

        trace.PHASE_HOOKS = (_push_phase, _pop_phase)


def _uninstall_hooks() -> None:
    global _ACTIVE
    _ACTIVE = max(0, _ACTIVE - 1)
    if _ACTIVE == 0:
        from shallowspeed_tpu.telemetry import trace

        trace.PHASE_HOOKS = None
        _TAGS.clear()


# ------------------------------------------------------------ sampling


def _fold(frame, max_depth: int = 48) -> str:
    """One thread's stack as a root->leaf folded string. Frames render
    as `module:function`; the profiler's own frames never appear (it
    samples other threads only)."""
    parts = []
    f = frame
    while f is not None and len(parts) < max_depth:
        co = f.f_code
        mod = Path(co.co_filename).stem
        parts.append(f"{mod}:{co.co_name}")
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


def _tags_of(ident: int) -> tuple[str, bool]:
    """(innermost phase, in-step?) for one thread — racy-read safe."""
    stack = _TAGS.get(ident)
    if not stack:
        return UNTAGGED, False
    try:
        snap = list(stack)
    except RuntimeError:  # pragma: no cover — resize during copy
        return UNTAGGED, False
    if not snap:
        return UNTAGGED, False
    return (str(snap[-1]),
            any(t in STEP_TAGS for t in snap))


class SamplingProfiler:
    """Daemon-thread stack sampler over the process MAIN thread.

    Main thread only, deliberately: `attrib_host_frac` measures the
    driver/scheduler thread's wall time outside fenced step spans, and
    a monitor HTTP thread parked in `select` would swamp the phase
    buckets with sleep frames. (`all_threads=True` exists for
    forensics; the attribution cross-check assumes the default.)

    All counters are CUMULATIVE; `snapshot()` bounds the payload to
    `top_k` folded stacks plus an exact `(other)` remainder, so
    snapshots merge across processes by summing counts — the reducer
    takes the LAST "profile" event per stanza, like "monitor" events.
    """

    def __init__(self, hz: float = DEFAULT_HZ,
                 top_k: int = DEFAULT_TOP_K, emit=None,
                 emit_every_s: float = 5.0, max_depth: int = 48,
                 all_threads: bool = False,
                 clock=time.perf_counter):
        self.hz = float(hz)
        self.top_k = int(top_k)
        self.emit = emit
        self.emit_every_s = float(emit_every_s)
        self.max_depth = int(max_depth)
        self.all_threads = bool(all_threads)
        self._clock = clock
        self.folded: Counter = Counter()
        self.phases: Counter = Counter()
        self.samples = 0
        self.step_samples = 0
        self._other = 0            # counts compacted out of `folded`
        self.max_gap_ms = 0.0
        self._t_start = None
        self._lock = threading.Lock()
        self._halt = threading.Event()
        self._thread: threading.Thread | None = None
        # RAM bound: compact the folded table back to 4*top_k uniques
        # whenever it doubles past that (exact counts for survivors,
        # the remainder lands in `(other)`)
        self._compact_at = max(64, 8 * self.top_k)

    # --------------------------------------------------------- control

    def start(self) -> "SamplingProfiler":
        if self._thread is not None:
            return self
        _install_hooks()
        self._t_start = self._clock()
        self._thread = threading.Thread(target=self._run,
                                        name="profiler-sampler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        if self._thread is None:
            return
        self._halt.set()
        self._thread.join(timeout=5)
        self._thread = None
        if self.emit is not None and self.samples:
            self._emit_snapshot()
        _uninstall_hooks()

    # -------------------------------------------------------- sampling

    def _run(self) -> None:
        period = 1.0 / max(self.hz, 1e-3)
        last = self._clock()
        next_emit = last + self.emit_every_s
        while not self._halt.wait(period):
            now = self._clock()
            gap_ms = (now - last) * 1e3
            last = now
            with self._lock:
                if self.samples:
                    self.max_gap_ms = max(self.max_gap_ms, gap_ms)
            self.sample_once()
            if self.emit is not None and now >= next_emit:
                next_emit = now + self.emit_every_s
                self._emit_snapshot()

    def sample_once(self) -> None:
        """One sampling beat (public so tests can drive it without the
        thread/clock)."""
        me = threading.get_ident()
        main = threading.main_thread().ident
        frames = sys._current_frames()
        for ident, frame in frames.items():
            if ident == me:
                continue
            if not self.all_threads and ident != main:
                continue
            folded = _fold(frame, self.max_depth)
            phase, in_step = _tags_of(ident)
            with self._lock:
                self.samples += 1
                self.folded[folded] += 1
                self.phases[phase] += 1
                if in_step:
                    self.step_samples += 1
                if len(self.folded) > 2 * self._compact_at:
                    self._compact_locked()

    def _compact_locked(self) -> None:
        keep = dict(self.folded.most_common(self._compact_at))
        dropped = sum(self.folded.values()) - sum(keep.values())
        self._other += dropped
        self.folded = Counter(keep)

    # -------------------------------------------------------- snapshot

    def snapshot(self) -> dict:
        """The cumulative "profile" event payload (schema v12)."""
        with self._lock:
            top = dict(self.folded.most_common(self.top_k))
            other = (self.samples - sum(top.values()))
            snap = {
                "samples": int(self.samples),
                "step_samples": int(self.step_samples),
                "hz": round(self.hz, 3),
                "top_k": int(self.top_k),
                "folded": {k: int(v) for k, v in top.items()},
                "other": max(0, int(other)),
                "phases": {str(k): int(v)
                           for k, v in self.phases.items()},
                "max_gap_ms": round(self.max_gap_ms, 3),
            }
            if self._t_start is not None:
                snap["window_s"] = round(self._clock() - self._t_start,
                                         3)
            return snap

    def _emit_snapshot(self) -> None:
        try:
            self.emit(event="profile", **self.snapshot())
        except Exception:
            pass  # a telemetry sink bug must not kill the sampler


# ----------------------------------------------------- capture windows


class CaptureWindow:
    """Burn/fault/straggler-armed high-rate capture, bounded like the
    flight recorder: dedup by (reason, step), `max_captures` per run,
    plus `cooldown_s` — a stall fault and the SLO alert it trips ~one
    second later must produce ONE profcap, not a pair. `arm()` is
    non-blocking: the window samples on its own short-lived thread
    while the triggering thread (often the one about to stall) keeps
    going — which is exactly what puts the stalled phase in the
    capture."""

    def __init__(self, out_dir=None, duration_s: float = 0.5,
                 hz: float = 200.0, max_captures: int = 16,
                 cooldown_s: float = 30.0, device_trace: bool = False,
                 max_depth: int = 48, clock=time.time):
        self.out_dir = Path(out_dir) if out_dir else Path(".")
        self.duration_s = float(duration_s)
        self.hz = float(hz)
        self.max_captures = int(max_captures)
        self.cooldown_s = float(cooldown_s)
        self.device_trace = bool(device_trace)
        self.max_depth = int(max_depth)
        self._clock = clock
        self.captures: list[str] = []
        self._seen: set = set()
        self._last_arm: float | None = None
        self._lock = threading.Lock()
        self._threads: list[threading.Thread] = []

    def arm(self, reason: str, step=None, trigger=None) -> bool:
        """Start one capture window; False when deduped/capped/cooling
        down."""
        with self._lock:
            key = (reason, step)
            now = self._clock()
            if key in self._seen:
                return False
            if len(self._seen) >= self.max_captures:
                return False
            if self._last_arm is not None \
                    and now - self._last_arm < self.cooldown_s:
                return False
            self._seen.add(key)
            self._last_arm = now
        th = threading.Thread(
            target=self._capture, name="profiler-capture",
            args=(reason, step, trigger), daemon=True)
        self._threads.append(th)
        th.start()
        return True

    def wait(self, timeout: float = 10.0) -> None:
        """Join outstanding capture threads (driver teardown + tests —
        a profcap from a fault on the final tick must hit disk before
        the process exits)."""
        for th in self._threads:
            th.join(timeout=timeout)

    def _capture(self, reason: str, step, trigger) -> None:
        folded: Counter = Counter()
        phases: Counter = Counter()
        main = threading.main_thread().ident
        me = threading.get_ident()
        period = 1.0 / max(self.hz, 1e-3)
        deadline = time.perf_counter() + self.duration_s
        dev_dir = None
        ctx = contextlib.nullcontext()
        if self.device_trace and not _device_trace_active():
            tag_ = step if step is not None else len(self.captures)
            dev_dir = self.out_dir / f"profcap_dev_{tag_}"
            ctx = device_trace_ctx(dev_dir)
        n = 0
        try:
            with ctx:
                while time.perf_counter() < deadline:
                    frames = sys._current_frames()
                    frame = frames.get(main)
                    if frame is not None and main != me:
                        folded[_fold(frame, self.max_depth)] += 1
                        phase, _ = _tags_of(main)
                        phases[phase] += 1
                        n += 1
                    time.sleep(period)
        except Exception:
            pass  # best effort, like flight dumps
        dominant = phases.most_common(1)[0][0] if phases else None
        payload = {"reason": reason, "step": step,
                   "wall": round(time.time(), 3), "trigger": trigger,
                   "duration_s": self.duration_s, "hz": self.hz,
                   "samples": n,
                   "dominant_phase": dominant,
                   "phases": {k: int(v) for k, v in phases.items()},
                   "folded": dict(folded.most_common(200))}
        if dev_dir is not None:
            payload["device_trace"] = str(dev_dir)
        tag_ = step if step is not None else f"n{len(self.captures)}"
        path = self.out_dir / f"profcap_{tag_}.json"
        k = 0
        while path.exists():
            k += 1
            path = self.out_dir / f"profcap_{tag_}_{k}.json"
        try:
            self.out_dir.mkdir(parents=True, exist_ok=True)
            with open(path, "w") as f:
                json.dump(payload, f, indent=1, default=str)
        except OSError:
            return
        self.captures.append(str(path))


# ----------------------------------------------- device-trace plumbing
#
# Exactly ONE jax.profiler entry point for the whole repo: the drivers'
# --profile-dir whole-run trace, the host+device mode, and the capture
# windows all come through here, and the depth counter keeps a capture
# from trying to nest a second xprof session inside a live one.

_DEVICE_TRACE_DEPTH = 0


def _device_trace_active() -> bool:
    return _DEVICE_TRACE_DEPTH > 0


@contextlib.contextmanager
def device_trace_ctx(trace_dir):
    """`jax.profiler.trace` as a reusable context manager; a falsy
    `trace_dir` is a no-op (so drivers pass --profile-dir through
    unconditionally)."""
    global _DEVICE_TRACE_DEPTH
    if not trace_dir:
        yield
        return
    import jax

    _DEVICE_TRACE_DEPTH += 1
    try:
        with jax.profiler.trace(str(trace_dir)):
            yield
    finally:
        _DEVICE_TRACE_DEPTH -= 1


# -------------------------------------------------------------- plane


class ProfilerPlane:
    """One process's profiling plane: the always-on sampler + the
    trigger-armed capture windows, with the listener endpoints the
    drivers wire (`on_alert` -> Monitor.alert_listeners, `on_fault` ->
    chaos.add_observer, `on_straggler` -> FleetCollector) and the
    /profile.json payload the StatusServer duck-types."""

    def __init__(self, mode: str = "host", metrics=None, out_dir=None,
                 hz: float = DEFAULT_HZ, top_k: int = DEFAULT_TOP_K,
                 emit_every_s: float = 5.0, capture_s: float = 0.5,
                 capture_hz: float = 200.0, cooldown_s: float = 30.0,
                 max_captures: int = 16):
        assert mode in MODES and mode != "off", mode
        self.mode = mode
        self.sampler = SamplingProfiler(
            hz=hz, top_k=top_k,
            emit=metrics.log if metrics is not None else None,
            emit_every_s=emit_every_s)
        self.capture = CaptureWindow(
            out_dir=out_dir, duration_s=capture_s, hz=capture_hz,
            cooldown_s=cooldown_s, max_captures=max_captures,
            device_trace=(mode == "host+device"))
        self._closed = False

    def start(self) -> "ProfilerPlane":
        self.sampler.start()
        return self

    # ------------------------------------------------------- triggers

    def on_alert(self, rec: dict) -> None:
        """Monitor.alert_listeners endpoint: critical burns arm a
        capture (warn-level flapping must not churn windows)."""
        try:
            if rec.get("state") == "firing" \
                    and rec.get("severity") == "critical":
                self.capture.arm(f"slo:{rec.get('slo')}",
                                 step=rec.get("step"), trigger=rec)
        except Exception:
            pass

    def on_fault(self, rec: dict) -> None:
        """chaos.add_observer endpoint: fires BEFORE the fault body
        (the stall sleep), so the window samples the stalled phase."""
        try:
            if rec.get("event") == "fault":
                self.capture.arm(f"fault:{rec.get('kind')}",
                                 step=rec.get("step"), trigger=rec)
        except Exception:
            pass

    def on_straggler(self, rec: dict) -> None:
        """FleetCollector straggler endpoint (router-side plane)."""
        try:
            if rec.get("state", "firing") == "firing":
                self.capture.arm(
                    f"straggler:{rec.get('replica')}:"
                    f"{rec.get('metric')}", trigger=rec)
        except Exception:
            pass

    def on_incident(self, reason: str, step=None, trigger=None) -> None:
        """Generic trigger — the Monitor's flight-dump path (anomaly
        verdicts) arms through this."""
        try:
            self.capture.arm(reason, step=step, trigger=trigger)
        except Exception:
            pass

    # -------------------------------------------------------- surface

    def profile_payload(self) -> dict:
        return {"enabled": True, "mode": self.mode,
                **self.sampler.snapshot(),
                "captures": list(self.capture.captures)}

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.capture.wait(timeout=self.capture.duration_s + 5.0)
        self.sampler.stop()


def from_args(args, metrics=None, out_dir=None):
    """Driver wiring, mirroring `monitor.from_args`: build-and-start a
    ProfilerPlane from ``--profile`` (plus optional ``--profile-hz``),
    or None when off. Captures land next to the metrics log (where
    flightrec_* go) unless `out_dir` says otherwise."""
    mode = getattr(args, "profile", "off") or "off"
    if mode not in MODES:
        raise SystemExit(f"--profile {mode!r} not in {MODES}")
    if mode == "off":
        return None
    if out_dir is None:
        log_file = getattr(args, "log_file", "") or ""
        out_dir = Path(log_file).parent if log_file else Path(".")
    plane = ProfilerPlane(
        mode, metrics=metrics, out_dir=out_dir,
        hz=float(getattr(args, "profile_hz", 0) or DEFAULT_HZ))
    return plane.start()


# ---------------------------------------------------------- reduction


def merge_profiles(snaps: dict[str, dict]) -> dict:
    """Fold {label: profile-payload} into one fleet view: folded
    stacks prefixed with their replica label (one flamegraph with a
    per-replica first level), phases and counters summed."""
    folded: Counter = Counter()
    phases: Counter = Counter()
    samples = step = other = 0
    for label, snap in sorted(snaps.items()):
        for stack, n in (snap.get("folded") or {}).items():
            folded[f"{label};{stack}"] += int(n)
        oth = int(snap.get("other") or 0)
        if oth:
            folded[f"{label};{OTHER_KEY}"] += oth
            other += oth
        for ph, n in (snap.get("phases") or {}).items():
            phases[ph] += int(n)
        samples += int(snap.get("samples") or 0)
        step += int(snap.get("step_samples") or 0)
    return {"samples": samples, "step_samples": step, "other": other,
            "folded": dict(folded), "phases": dict(phases),
            "replicas": sorted(snaps)}


def flame_tree(folded: dict) -> dict:
    """Folded counts -> hierarchical {name, value, children} JSON (the
    d3-flamegraph shape; Perfetto imports collapsed stacks too, so the
    folded dict itself is also an artifact)."""
    root = {"name": "root", "value": 0, "children": {}}
    for stack, n in folded.items():
        n = int(n)
        root["value"] += n
        node = root
        for part in stack.split(";"):
            child = node["children"].get(part)
            if child is None:
                child = node["children"][part] = {
                    "name": part, "value": 0, "children": {}}
            child["value"] += n
            node = child

    def _materialize(node):
        kids = [_materialize(c) for c in node["children"].values()]
        out = {"name": node["name"], "value": node["value"]}
        if kids:
            out["children"] = sorted(kids, key=lambda c: -c["value"])
        return out

    return _materialize(root)


def last_profiles(paths) -> dict[str, dict]:
    """{label: last "profile" event} across metrics JSONLs. Events are
    cumulative, so the LAST one per process stanza (a run_start opens a
    stanza) is that stanza's whole story; labels come from the
    run_start `replica` field, else the file stem (suffixed on
    collision so two unlabelled stanzas never silently merge)."""
    from shallowspeed_tpu.telemetry.schema import parse_metrics_jsonl

    out: dict[str, dict] = {}
    for path in paths:
        stem = Path(path).stem
        label, last = stem, None

        def _flush():
            if last is None:
                return
            key, k = label, 1
            while key in out:
                k += 1
                key = f"{label}#{k}"
            out[key] = last

        for rec in parse_metrics_jsonl(path):
            ev = rec.get("event")
            if ev == "run_start":
                _flush()
                label, last = rec.get("replica") or stem, None
            elif ev == "profile":
                last = rec
        _flush()
    return out


def profile_main(paths, out=None, top: int = 10, echo=print) -> int:
    """``python -m shallowspeed_tpu.telemetry --profile <log> [--out
    flame.json]``: reduce the "profile" events of one or more metrics
    JSONLs to a flamegraph JSON + a printed top-frames/phases summary.
    Exit 1 when no profile events exist (a profiled artifact that
    lost its events should fail the smoke, not print an empty tree)."""
    snaps = last_profiles(paths)
    if not snaps:
        echo(f"--profile: no 'profile' events in "
             f"{', '.join(str(p) for p in paths)}")
        return 1
    if len(snaps) == 1:
        merged = dict(next(iter(snaps.values())))
        merged.setdefault("folded", {})
        if merged.get("other"):
            merged["folded"] = dict(merged["folded"])
            merged["folded"][OTHER_KEY] = int(merged["other"])
    else:
        merged = merge_profiles(snaps)
    folded = merged.get("folded") or {}
    samples = int(merged.get("samples") or 0)
    echo(f"profile: {samples} samples over {len(snaps)} "
         f"stanza(s) [{', '.join(sorted(snaps))}]")
    phases = merged.get("phases") or {}
    tot = sum(phases.values()) or 1
    for ph, n in sorted(phases.items(), key=lambda kv: -kv[1]):
        echo(f"  phase {ph:<16} {n:>8}  {n / tot:6.1%}")
    for stack, n in sorted(folded.items(),
                           key=lambda kv: -kv[1])[:top]:
        leaf = stack.rsplit(";", 1)[-1]
        echo(f"  {n:>8}  {leaf}  [{stack[:90]}]")
    if out:
        tree = flame_tree(folded)
        tree["phases"] = {str(k): int(v) for k, v in phases.items()}
        tree["samples"] = samples
        Path(out).write_text(json.dumps(tree))
        echo(f"flamegraph JSON -> {out}")
    return 0
