"""Mergeable log-bucketed histogram sketches — streaming quantiles in
constant memory.

The offline reducers (`report.percentile`, `goodput.run_goodput`) sort
the full value list; a live endpoint cannot (a day of serving is
millions of ttft samples, and `/status.json` must answer *now*). A
`LogHistogram` keeps counts in geometrically spaced buckets
(DDSketch-style): bucket ``i`` covers ``(gamma^(i-1), gamma^i]`` with
``gamma = (1 + rel_err) / (1 - rel_err)``, and a bucket's
representative value ``2 * gamma^i / (gamma + 1)`` is within
``rel_err`` of every sample that landed in it. Counts are EXACT —
only the value axis is quantized — so:

- ``quantile(q)`` is the nearest-rank percentile (the SAME rank rule
  as `report.percentile`, so live and offline disagree only by the
  documented bucket error, never by rank convention) with relative
  error <= ``rel_err`` (clamped into the exact [min, max] envelope);
- ``merge`` is exact bucket-count addition: per-process sketches
  serialized into the metrics JSONL (schema-v7 ``"monitor"`` events)
  recombine across supervisor restarts and gang members into the
  whole-run distribution — the property a fleet aggregator needs;
- memory is O(log(max/min) / rel_err) buckets whatever the stream
  length (~700 buckets spans nanoseconds..days at 1% error).

Pure stdlib (math + dict) — no jax, no numpy — so the `--live` tailer
and the elastic supervisor can run it anywhere, at import cost zero.
"""

from __future__ import annotations

import math


class LogHistogram:
    """One metric's streaming distribution (module docstring)."""

    __slots__ = ("rel_err", "_log_gamma", "_gamma", "buckets", "n_zero",
                 "n", "vmin", "vmax", "total")

    def __init__(self, rel_err: float = 0.01):
        if not 0.0 < rel_err < 1.0:
            raise ValueError(f"rel_err must be in (0, 1), got {rel_err}")
        self.rel_err = float(rel_err)
        self._gamma = (1.0 + rel_err) / (1.0 - rel_err)
        self._log_gamma = math.log(self._gamma)
        self.buckets: dict[int, int] = {}
        self.n_zero = 0          # samples <= 0 (queue depth 0 is real)
        self.n = 0
        self.vmin = math.inf
        self.vmax = -math.inf
        self.total = 0.0         # running sum -> mean

    # ------------------------------------------------------------ feed

    def add(self, x, count: int = 1) -> None:
        """Absorb `count` observations of value `x` (a window average
        fed with its window's step count weights correctly)."""
        x = float(x)
        count = int(count)
        if count <= 0 or not math.isfinite(x):
            return
        self.n += count
        self.total += x * count
        self.vmin = min(self.vmin, x)
        self.vmax = max(self.vmax, x)
        if x <= 0.0:
            self.n_zero += count
            return
        i = math.ceil(math.log(x) / self._log_gamma)
        self.buckets[i] = self.buckets.get(i, 0) + count

    # --------------------------------------------------------- queries

    def _bucket_value(self, i: int) -> float:
        # midpoint estimate: within rel_err of anything in the bucket
        return 2.0 * self._gamma ** i / (self._gamma + 1.0)

    def quantile(self, q: float) -> float | None:
        """Nearest-rank percentile, q in [0, 100] (None when empty).
        Same rank rule as `report.percentile`: rank = floor(q/100 *
        (n-1) + 0.5), so the live and offline reducers share one
        definition and differ only by the bucket's rel_err."""
        if self.n == 0:
            return None
        rank = min(self.n - 1,
                   max(0, math.floor(q / 100.0 * (self.n - 1) + 0.5)))
        if rank < self.n_zero:
            return min(0.0, self.vmin)
        seen = self.n_zero
        for i in sorted(self.buckets):
            seen += self.buckets[i]
            if rank < seen:
                v = self._bucket_value(i)
                return min(self.vmax, max(self.vmin, v))
        return self.vmax  # unreachable unless counts drifted

    def mean(self) -> float | None:
        return self.total / self.n if self.n else None

    def count_above(self, x: float) -> int:
        """Observations whose value exceeds `x`, at bucket resolution:
        a bucket counts by its representative value, so the answer is
        exact except for samples within `rel_err` of `x` — the bound
        the fleet SLO evaluator inherits when it scores a merged
        stream against a threshold without the raw values."""
        x = float(x)
        n = self.n_zero if x < 0.0 else 0
        for i, c in self.buckets.items():
            if self._bucket_value(i) > x:
                n += c
        return n

    def count_le(self, x: float) -> int:
        """Observations at or below `x`, at bucket resolution (the
        complement of `count_above`, zeros/negatives included) — the
        cumulative counter behind the native Prometheus histogram
        export. Exact except for samples within `rel_err` of `x`."""
        return self.n - self.count_above(x)

    def summary(self, qs=(50, 95, 99)) -> dict:
        """The /status.json block for this sketch."""
        out = {"count": self.n}
        if self.n:
            out["min"] = round(self.vmin, 6)
            out["max"] = round(self.vmax, 6)
            out["mean"] = round(self.mean(), 6)
            for q in qs:
                out[f"p{q}"] = round(self.quantile(q), 6)
        return out

    # ------------------------------------------------- merge/serialize

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """Exact in-place union (same rel_err required — bucket indices
        are only comparable on one gamma grid)."""
        if abs(other.rel_err - self.rel_err) > 1e-12:
            raise ValueError(
                f"cannot merge sketches with different rel_err "
                f"({self.rel_err} vs {other.rel_err})")
        for i, c in other.buckets.items():
            self.buckets[i] = self.buckets.get(i, 0) + c
        self.n_zero += other.n_zero
        self.n += other.n
        self.total += other.total
        if other.n:
            self.vmin = min(self.vmin, other.vmin)
            self.vmax = max(self.vmax, other.vmax)
        return self

    def to_dict(self) -> dict:
        """JSON-safe serialization (bucket keys become strings); the
        schema-v7 ``"monitor"`` event carries one of these per metric."""
        out = {"rel_err": self.rel_err, "n": self.n,
               "zero": self.n_zero,
               "buckets": {str(i): c for i, c in self.buckets.items()}}
        if self.n:
            out["min"] = self.vmin
            out["max"] = self.vmax
            out["sum"] = self.total
        return out

    @classmethod
    def from_dict(cls, d: dict) -> "LogHistogram":
        sk = cls(rel_err=float(d.get("rel_err", 0.01)))
        sk.buckets = {int(i): int(c)
                      for i, c in (d.get("buckets") or {}).items()}
        sk.n_zero = int(d.get("zero", 0))
        sk.n = int(d.get("n", 0))
        sk.total = float(d.get("sum", 0.0))
        if sk.n:
            sk.vmin = float(d.get("min", math.inf))
            sk.vmax = float(d.get("max", -math.inf))
        return sk


class MetricSketches:
    """A named family of LogHistograms sharing one rel_err — the
    monitor's whole streaming state, one `observe` call per sample."""

    def __init__(self, rel_err: float = 0.01):
        self.rel_err = float(rel_err)
        self.sketches: dict[str, LogHistogram] = {}

    def observe(self, name: str, value, count: int = 1) -> None:
        sk = self.sketches.get(name)
        if sk is None:
            sk = self.sketches[name] = LogHistogram(self.rel_err)
        sk.add(value, count)

    def quantile(self, name: str, q: float) -> float | None:
        sk = self.sketches.get(name)
        return sk.quantile(q) if sk is not None else None

    def summary(self, qs=(50, 95, 99)) -> dict:
        return {name: sk.summary(qs)
                for name, sk in sorted(self.sketches.items()) if sk.n}

    def to_dict(self) -> dict:
        return {name: sk.to_dict()
                for name, sk in sorted(self.sketches.items()) if sk.n}

    def merge_dict(self, snap: dict) -> "MetricSketches":
        """Fold one serialized sketch family (a ``"monitor"`` event's
        ``sketches`` payload) into this one — the cross-process /
        cross-stanza aggregation path."""
        for name, d in (snap or {}).items():
            sk = LogHistogram.from_dict(d)
            if name in self.sketches:
                self.sketches[name].merge(sk)
            else:
                self.sketches[name] = sk
        return self
