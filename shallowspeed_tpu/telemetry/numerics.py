"""Numerics observatory — runtime precision telemetry for the fp8 path.

PR 16's prover certifies the fp8-e4m3 step STATICALLY (no double
rounding, f32 accumulation, paired scales, in-range converts under the
calibration intervals). This module is the RUNTIME half of ROADMAP
item 5's rollout gate: the certificate is conditioned on measured
calibration stats, and a live run can leave them — a distribution
shift blows past the amax history, a chaos fault zeroes a scale, a
code change quietly saturates a layer. The observatory turns the
step's own numerics into verdicts the existing recovery stack acts on:

- the **numerics pack** (device side, in `fp8.Fp8TrainEngine._step`):
  per-layer overflow/underflow fractions at every activation quantize
  plus the live amax/scale values, riding the health pack under its
  zero-new-executables contract;
- `NumericsMonitor` (host side, this module): robust-EWMA drift
  z-scores over each layer's log2(scale) series, a sign-flip
  oscillation score (a scale ping-ponging between window maxima — the
  classic delayed-scaling instability), scale-collapse detection at
  the 1e-12 floor, and the shadow-parity series from the frozen
  master-precision oracle (`Fp8TrainEngine.shadow_parity`);
- verdicts reuse `anomaly.Verdict` with kinds ``scale_collapse`` /
  ``parity_drift``; `GuardPolicy` maps them to actions, with
  ``fallback_bf16`` as the guarded default — and the monitor
  ESCALATES: a kind that fires again after the fallback was taken
  comes back with action ``abort`` (warn → fall back → abort).

Fields ride step lines as `num_*` (schema v13), `/status.json` +
`/metrics` numerics blocks (telemetry/monitor.py), the fleet view, and
the `--goodput` report's numerics block. Pure host-side math — no jax
imports — so the monitor runs in drivers, tailers, and offline reducers
alike.
"""

from __future__ import annotations

import math

from shallowspeed_tpu.telemetry.anomaly import (GuardPolicy, RobustEWMA,
                                                Verdict)

# a delayed scale at (or indistinguishably near) the 1e-12 divide
# floor means the amax history is gone — nothing real is that small
COLLAPSE_FLOOR = 1e-10

# parity envelopes. The LOSS rel-err is the discriminative gate: a
# healthy fp8 step tracks the f32 oracle to ~1e-3..2e-2 once the amax
# history has warmed, while a collapsed scale blows it past 0.1
# (measured on the fp8_train MLP). The worst-leaf grad relmax is
# deliberately loose — on small models a single ReLU mask flip under
# quantization drives one leaf's relmax toward 1.0 on perfectly
# healthy steps (and a fully-collapsed scale only saturates it AT
# 1.0), so the grad budget catches only outright blowups (quantized
# grads LARGER than oracle: scale explosion, inf); the field's job is
# attribution on the step line, not the trigger.
PARITY_LOSS_BUDGET = 0.05
PARITY_GRAD_BUDGET = 2.0

# oscillation: fraction of sign flips in successive log2(scale) deltas
# over the window; a scale alternating every observation scores 1.0
OSC_WINDOW = 16
OSC_THRESHOLD = 0.75


class NumericsMonitor:
    """Host-side reducer for the numerics pack + shadow-parity series.

    `observe(step, pack)` ingests one health-pack fetch (the same dict
    `HealthMonitor.observe` sees — only the `fp8_*` keys are read);
    `note_parity(step, parity)` ingests one shadow-parity sample.
    Both return policy-annotated verdicts. `step_fields()` is merged
    into step lines by `metrics.StepRates(numerics=...)` and drains
    the verdict window, mirroring `HealthMonitor.step_fields`."""

    def __init__(self, policy: GuardPolicy | None = None,
                 drift_z: float = 6.0, patience: int = 3,
                 collapse_floor: float = COLLAPSE_FLOOR,
                 parity_loss_budget: float = PARITY_LOSS_BUDGET,
                 parity_grad_budget: float = PARITY_GRAD_BUDGET,
                 alpha: float = 0.05, warmup: int = 8):
        self.policy = policy or GuardPolicy()
        self.drift_z = float(drift_z)
        self.patience = int(patience)
        self.collapse_floor = float(collapse_floor)
        self.parity_loss_budget = float(parity_loss_budget)
        self.parity_grad_budget = float(parity_grad_budget)
        self._alpha, self._warmup = float(alpha), int(warmup)
        self._scale_ewma: dict[int, RobustEWMA] = {}
        self._deltas: dict[int, list[float]] = {}   # log2-scale deltas
        self._prev_log2: dict[int, float] = {}
        self._parity_ewma = RobustEWMA(alpha, warmup)
        self._collapse_run: dict[int, int] = {}
        self._parity_run = 0
        self._last: dict = {}
        self._last_parity: dict = {}
        self.shadow_total = 0
        self.fallback_taken = False
        self._verdicts_since_log: list[Verdict] = []

    # ------------------------------------------------------- ingest

    def observe(self, step: int, pack: dict | None) -> list[Verdict]:
        """One health-pack observation; returns this observation's
        numerics verdicts with `action` set (escalated past the
        fallback where it was already taken)."""
        if not pack or "fp8_scale" not in pack:
            return []
        scales = [float(s) for s in pack["fp8_scale"]]
        self._last = {
            "scales": scales,
            "amaxes": [float(a) for a in pack.get("fp8_amax", ())],
            "overflow": [float(v) for v in pack.get("fp8_overflow", ())],
            "underflow": [float(v)
                          for v in pack.get("fp8_underflow", ())],
        }
        out: list[Verdict] = []
        drift_layers = []
        for i, s in enumerate(scales):
            if not math.isfinite(s):
                continue
            # collapse: the floor means the history behind this layer's
            # scale is zero/denormal — every quantize saturates
            if s <= self.collapse_floor:
                run = self._collapse_run.get(i, 0) + 1
                self._collapse_run[i] = run
                if run == 1:     # report on arrival, not every step
                    out.append(Verdict(
                        "scale_collapse", step, severity="error",
                        detail=f"layer {i} delayed scale {s:.3g} is at "
                               f"the divide floor (amax history "
                               f"collapsed); overflow frac "
                               f"{self._overflow_at(i):.3f}"))
            else:
                self._collapse_run[i] = 0
            log2s = math.log2(max(s, 1e-300))
            ew = self._scale_ewma.get(i)
            if ew is None:
                ew = self._scale_ewma[i] = RobustEWMA(self._alpha,
                                                      self._warmup)
            z = ew.update(log2s)
            if z is not None and abs(z) > self.drift_z:
                drift_layers.append((i, z))
            prev = self._prev_log2.get(i)
            if prev is not None:
                d = self._deltas.setdefault(i, [])
                d.append(log2s - prev)
                del d[:-OSC_WINDOW]
            self._prev_log2[i] = log2s
        self._last["drift_z"] = max(
            (abs(z) for _, z in drift_layers), default=None)
        self._last["osc"] = max(
            (self._osc_score(i) for i in self._deltas), default=0.0)
        # drift/oscillation inform, they do not fire alone: a real
        # range shift lands in the parity gate or the clamp fractions;
        # the z-score and osc score ride the step line for the operator
        for v in out:
            v.action = self._action(v.kind)
        self._verdicts_since_log.extend(out)
        return out

    def note_parity(self, step: int, parity: dict) -> list[Verdict]:
        """One shadow-parity sample (`Fp8TrainEngine.shadow_parity`'s
        dict: parity_loss_rel + parity_grad_relmax)."""
        loss_rel = float(parity.get("parity_loss_rel", float("nan")))
        grad_rel = float(parity.get("parity_grad_relmax", float("nan")))
        self.shadow_total += 1
        self._last_parity = {"loss_rel": loss_rel, "grad_rel": grad_rel}
        out: list[Verdict] = []
        bad = (not math.isfinite(loss_rel)
               or loss_rel > self.parity_loss_budget
               or not math.isfinite(grad_rel)
               or grad_rel > self.parity_grad_budget)
        z = self._parity_ewma.update(loss_rel)
        trending = z is not None and z > self.drift_z
        if bad or trending:
            self._parity_run += 1
            # an outright envelope violation fires immediately; a
            # trend inside the envelope needs `patience` consecutive
            # samples (slow walks should not flap the guard)
            if bad or self._parity_run >= self.patience:
                why = (f"loss rel-err {loss_rel:.3g} vs budget "
                       f"{self.parity_loss_budget:g}, grad relmax "
                       f"{grad_rel:.3g} vs {self.parity_grad_budget:g}"
                       if bad else
                       f"loss rel-err {loss_rel:.3g} is {z:.1f} robust "
                       f"sigmas above its EWMA "
                       f"{self._parity_ewma.mean:.3g}")
                out.append(Verdict("parity_drift", step,
                                   severity="error",
                                   detail=f"shadow parity: {why}"))
                self._parity_run = 0
        else:
            self._parity_run = 0
        for v in out:
            v.action = self._action(v.kind)
        self._verdicts_since_log.extend(out)
        return out

    def note_fallback(self) -> None:
        """The driver took the bf16 fallback — the same verdict kinds
        now escalate to abort (warn → fall back → abort)."""
        self.fallback_taken = True

    def _action(self, kind: str) -> str:
        act = self.policy.action(kind)
        if act == "fallback_bf16" and self.fallback_taken:
            return "abort"    # the middle rung was already used
        return act

    def _overflow_at(self, i: int) -> float:
        over = self._last.get("overflow") or []
        return over[i] if i < len(over) else float("nan")

    def _osc_score(self, i: int) -> float:
        d = [x for x in self._deltas.get(i, ()) if x != 0.0]
        if len(d) < 2:
            return 0.0
        flips = sum(1 for a, b in zip(d, d[1:]) if a * b < 0)
        return flips / (len(d) - 1)

    # -------------------------------------------------------- output

    def step_fields(self) -> dict:
        """`num_*` fields for the next step line (schema v13 types
        them); drains the verdict window."""
        out: dict = {}
        p = self._last
        if p:
            if p.get("overflow"):
                out["num_overflow_max"] = round(max(p["overflow"]), 6)
            if p.get("underflow"):
                out["num_underflow_max"] = round(max(p["underflow"]), 6)
            if p.get("scales"):
                out["num_scale_min"] = float(
                    f"{min(p['scales']):.6g}")
            if p.get("amaxes"):
                out["num_amax_max"] = float(
                    f"{max(p['amaxes']):.6g}")
            if p.get("drift_z") is not None:
                out["num_drift_z"] = round(p["drift_z"], 3)
            out["num_osc"] = round(p.get("osc", 0.0), 3)
        if self._last_parity:
            out["num_parity_loss_rel"] = float(
                f"{self._last_parity['loss_rel']:.6g}")
            out["num_parity_grad_relmax"] = float(
                f"{self._last_parity['grad_rel']:.6g}")
        if self.shadow_total:
            out["num_shadow_total"] = self.shadow_total
        if self.fallback_taken:
            out["num_precision"] = "bf16"
        elif p:
            out["num_precision"] = "fp8"
        verdicts = self._verdicts_since_log
        self._verdicts_since_log = []
        if verdicts:
            out["num_verdicts"] = [v.kind for v in verdicts]
        return out
