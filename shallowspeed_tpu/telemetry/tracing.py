"""Distributed request tracing — one request, one timeline, N logs.

A fleet request crosses router → replica → (failover) → replica; its
schema-v8 ``"lifecycle"`` events land in per-replica JSONLs with
per-process clocks and no shared identity. This module supplies the
three missing pieces (round 16, schema v11):

- **Trace context.** `Router.submit` mints a `trace` id + root `span`
  per request; every dispatch mints a child span that rides the
  ``POST /submit`` payload into `ServingEngine.submit()` (including
  the ``generated=`` failover re-dispatch), so every lifecycle /
  route / failover / request event carries ``trace``/``span``/
  ``parent`` plus ``attempt`` — the 0-based cross-engine dispatch
  counter that makes one rid's journey joinable across the router and
  N replica logs, breaker-delayed retries and re-prefills included.
- **Stitching + skew correction.** Every metrics line carries a
  ``(wall, mono)`` clock pair (`metrics.MetricsLogger`). `stitch()`
  splits each input file into process stanzas (at ``run_start`` —
  chaos respawns restart the monotonic epoch) and fits ONE offset per
  stanza onto the router's clock from the dispatch transaction the
  trace context brackets: the router's pre-POST stamp
  (``dispatch_wall``/``dispatch_mono`` on ``route``/``failover``)
  precedes the replica's lifecycle ``submit``, the event's own stamp
  follows it, and a lifecycle ``finished`` precedes the router's
  ``request`` record — the minimum-RTT transaction's midpoint is the
  fit, NTP-style (`_fit_offsets`). The result is ONE
  Perfetto-loadable Chrome trace: per-replica phase tracks plus a
  per-request journey track (queue-wait → dispatch → prefill chunks →
  decode → failover gap → re-prefill → decode → finish).
- **Per-request waterfall.** `report.request_waterfall` reduces a
  stitched journey into named components —
  ``rq_queue / rq_dispatch / rq_prefill / rq_decode /
  rq_failover_gap / rq_breaker_wait / rq_unexplained`` — that sum to
  the measured e2e BY CONSTRUCTION (the residual is
  ``rq_unexplained``, the stitching-quality alarm). `goodput_block`
  aggregates a fleet of journeys to p50/p95 per component with
  worst-``rq_unexplained`` exemplars (the ``tracing`` block of
  ``--goodput``).

CLI::

    python -m shallowspeed_tpu.telemetry --trace-stitch \\
        run/router.jsonl run/replica_r0.jsonl run/replica_r1.jsonl \\
        --out stitched.json

Pure stdlib (json/math/statistics), like `monitor` and `sketch` — the
stitcher runs anywhere the logs can be read.
"""

from __future__ import annotations

import json
import secrets
import statistics
from pathlib import Path

# engine lifecycle phase -> waterfall component: the ONE mapping the
# offline stitcher, the live Monitor's per-component sketches, and
# bench's phase accounting share. Time "in" a phase is booked to its
# component; submit/finished are instants (their in-phase time is ~0
# but maps somewhere deterministic anyway).
PHASE_COMPONENT = {
    "submit": "rq_queue",
    "queued": "rq_queue",
    "requeued": "rq_queue",
    "preempted": "rq_queue",
    "admitted": "rq_prefill",
    "prefill_cached": "rq_prefill",   # v14: prefix-cache hit at admit
    "prefill": "rq_prefill",
    "decoding": "rq_decode",
    "finished": "rq_dispatch",   # finished -> router finalize = poll
}

# the named components, in waterfall order (rq_unexplained is the
# residual request_waterfall appends)
COMPONENTS = ("rq_queue", "rq_dispatch", "rq_prefill", "rq_decode",
              "rq_failover_gap", "rq_breaker_wait")


def new_trace_id() -> str:
    """One id per fleet request (128-bit hex, W3C-trace-context
    sized)."""
    return secrets.token_hex(16)


def new_span_id() -> str:
    """One id per hop (router root span, per-dispatch span, per-engine
    attempt span)."""
    return secrets.token_hex(8)


# ------------------------------------------------------------- parsing


def _parse(path) -> list[dict]:
    from shallowspeed_tpu.telemetry.schema import parse_metrics_jsonl

    return parse_metrics_jsonl(path)


def _stanzas(recs: list[dict]) -> list[list[dict]]:
    """Split one file's records at run_start lines: a respawned
    process (chaos drill, supervisor restart) appends a fresh stanza
    with a fresh monotonic epoch — each stanza gets its own offset."""
    out: list[list[dict]] = []
    for rec in recs:
        if rec.get("event") == "run_start" or not out:
            out.append([])
        out[-1].append(rec)
    return out


def _ts(rec: dict, base: str) -> float | None:
    v = rec.get(base)
    return float(v) if isinstance(v, (int, float)) else None


class _Stanza:
    """One process incarnation: a slice of one file with one clock."""

    __slots__ = ("source", "index", "name", "recs", "base", "offset",
                 "pairs", "is_router")

    def __init__(self, source: str, index: int, recs: list[dict]):
        self.source = source
        self.index = index
        self.recs = recs
        start = recs[0] if recs and recs[0].get("event") == "run_start" \
            else {}
        self.is_router = (start.get("kind") == "router"
                          or any(r.get("event") in ("route", "failover")
                                 for r in recs))
        self.name = (start.get("replica")
                     if isinstance(start.get("replica"), str)
                     else "router" if self.is_router
                     else Path(source).stem)
        # prefer the monotonic clock when the stanza stamps it (jump-
        # free within a process); wall is the pre-v11 fallback
        self.base = ("mono" if all(
            isinstance(r.get("mono"), (int, float)) for r in recs)
            else "wall")
        self.offset = 0.0
        self.pairs = {"dispatch": 0, "ack": 0}

    def t(self, rec: dict) -> float | None:
        v = _ts(rec, self.base)
        return v + self.offset if v is not None else None


def _load_stanzas(paths, first_recs=None) -> list[_Stanza]:
    """`first_recs`: already-parsed records for paths[0] (the goodput
    reducer has them in hand — no point re-reading the primary log)."""
    out = []
    for n, path in enumerate(paths):
        recs = first_recs if n == 0 and first_recs is not None \
            else _parse(path)
        for i, chunk in enumerate(_stanzas(recs)):
            if chunk:
                out.append(_Stanza(str(path), i, chunk))
    return out


# ------------------------------------------------------ skew correction


def _clock_delta(s: _Stanza) -> float:
    """This stanza's (mono - wall) epoch delta — the per-process
    clock pair, made robust with the median over every stamped
    line."""
    ds = [float(r["mono"]) - float(r["wall"]) for r in s.recs
          if isinstance(r.get("mono"), (int, float))
          and isinstance(r.get("wall"), (int, float))]
    return statistics.median(ds) if ds else 0.0


def _fit_offsets(stanzas: list[_Stanza]) -> None:
    """Fit each non-router stanza's clock onto the router's.

    Baseline: the per-stanza (wall, mono) clock pair aligns every
    process under the synchronized-wall assumption (offset = the
    router's mono-wall delta minus this stanza's). Refinement — the
    actual skew correction, from the dispatch TRANSACTION the trace
    context brackets: the router stamps a pre-POST clock pair T1
    (``dispatch_wall``/``dispatch_mono`` on the ``route``/``failover``
    event) strictly BEFORE the replica's lifecycle ``submit`` at T2,
    and emits the event itself at T4 strictly AFTER — so for the same
    (trace, attempt): T1 - T2 <= offset <= T4 - T2, and the
    transaction's own estimate is (T1 + T4)/2 - T2 with uncertainty
    (T4 - T1)/2, the POST round trip. The minimum-RTT transaction —
    NTP's filter — gives the fit, clamped into the intersection of
    every pair's bounds plus the ack bound (lifecycle ``finished``
    strictly precedes the router's ``request`` record -> offset <=
    T_r - T_p). The ack leg is NOT used as an estimate on its own:
    finish -> finalize rides the router's progress poll, a one-sided
    seconds-scale lag on a busy fleet; likewise the replica's
    engine-thread INGESTION lag sits between T2 and admission, which
    is why the fit brackets the gateway stamp, not later phases.

    Pre-v11.1 logs (no ``dispatch_*`` pre stamps — e.g. the committed
    trace_r14 artifact) fall back to the event-time heuristic: treat
    T4 - T2 as the dispatch mark and take the midpoint of max(lo) /
    min(hi) — biased late by the POST round trip, but bounded by it.
    A replica whose WALL clock is wrong still lands exactly on the
    router's timeline. Stanzas with no trace pairs (an idle respawn)
    keep the wall-aligned baseline — the best remaining guess."""
    routers = [s for s in stanzas if s.is_router]
    if not routers:
        return
    r0 = routers[0]
    router_delta = _clock_delta(r0) if r0.base == "mono" else 0.0
    # later router stanzas (one log appended across runs — each
    # run_start restarts the mono epoch) wall-align onto the FIRST
    # router stanza's clock; leaving them at offset 0 would mix two
    # mono epochs into one mark set and silently poison every fit
    # (trace ids keep the runs' journeys apart, but the marks share
    # the dicts below)
    for s in routers[1:]:
        s.offset = (router_delta - _clock_delta(s)
                    if s.base == "mono" else router_delta)
    for s in stanzas:
        if not s.is_router:
            s.offset = (router_delta - _clock_delta(s)
                        if s.base == "mono" else router_delta)
    # dispatch marks (T4 event time, T1 pre-POST time or None) / ack
    # marks on the FIRST router stanza's (offset-0) clock
    dispatch: dict[tuple, tuple] = {}
    ack: dict[str, float] = {}
    for s in routers:
        for rec in s.recs:
            ev = rec.get("event")
            tr = rec.get("trace")
            if not isinstance(tr, str):
                continue
            t = s.t(rec)
            if t is None:
                continue
            if ev in ("route", "failover"):
                att = rec.get("attempt") if ev == "failover" else 0
                if isinstance(att, int):
                    pre = rec.get(f"dispatch_{s.base}")
                    dispatch[(tr, att)] = (
                        t, float(pre) + s.offset
                        if isinstance(pre, (int, float)) else None)
            elif ev == "request":
                ack[tr] = t
    # final attempt per trace, across ALL stanzas: a timeout failover
    # abandons live work, and the old replica can stamp "finished"
    # AFTER the router already finalized via the new attempt — only
    # the FINAL attempt's finished is guaranteed to precede the
    # request record, so only it may contribute an ack bound
    final_att: dict[str, int] = {}
    for s in stanzas:
        for rec in s.recs:
            if rec.get("event") != "lifecycle":
                continue
            tr = rec.get("trace")
            if isinstance(tr, str):
                att = rec.get("attempt")
                att = att if isinstance(att, int) else 0
                if att > final_att.get(tr, -1):
                    final_att[tr] = att
    for s in stanzas:
        if s.is_router:
            continue
        lo: list[float] = []    # offset >= router_pre_post - my_submit
        hi: list[float] = []    # offset <= router_event - my_stamp
        samples: list[tuple] = []   # (rtt, est) per pre-stamped pair
        n_dispatch = n_ack = 0
        for rec in s.recs:
            if rec.get("event") != "lifecycle":
                continue
            tr = rec.get("trace")
            if not isinstance(tr, str):
                continue
            t = _ts(rec, s.base)
            if t is None:
                continue
            att = rec.get("attempt")
            att = att if isinstance(att, int) else 0
            if rec.get("phase") == "submit":
                td = dispatch.get((tr, att))
                if td is not None:
                    t4, t1 = td
                    n_dispatch += 1
                    if t1 is not None:
                        lo.append(t1 - t)
                        hi.append(t4 - t)
                        samples.append((t4 - t1,
                                        (t1 + t4) / 2.0 - t))
                    else:
                        # legacy: the event stamp is really an upper
                        # bound, but with no pre stamp the midpoint
                        # heuristic below is the best available
                        lo.append(t4 - t)
            elif rec.get("phase") == "finished" \
                    and att == final_att.get(tr, 0):
                ta = ack.get(tr)
                if ta is not None:
                    n_ack += 1
                    hi.append(ta - t)
        s.pairs = {"dispatch": n_dispatch, "ack": n_ack}
        if samples:
            est = min(samples)[1]
            if lo:
                est = max(est, max(lo))
            if hi:
                est = min(est, min(hi))
            s.offset = est
        elif lo and hi:
            s.offset = (max(lo) + min(hi)) / 2.0
        elif lo:
            s.offset = max(lo)
        elif hi:
            s.offset = min(hi)
        # else: the wall-aligned baseline set above stands


# ------------------------------------------------------------ journeys


def _breaker_open_windows(stanzas) -> list[tuple[float, float]]:
    """Corrected-time windows during which EVERY replica the router
    ever put a breaker on was simultaneously open — the only state in
    which a pending request is waiting on breakers rather than on
    failure detection. No breaker events -> no windows."""
    state: dict[str, bool] = {}
    events: list[tuple[float, str, str]] = []
    for s in stanzas:
        if not s.is_router:
            continue
        for rec in s.recs:
            if rec.get("event") == "ledger" \
                    and rec.get("kind") == "breaker" \
                    and isinstance(rec.get("replica"), str) \
                    and isinstance(rec.get("state"), str):
                t = s.t(rec)
                if t is not None:
                    events.append((t, rec["replica"], rec["state"]))
    events.sort(key=lambda e: e[0])
    windows = []
    open_since: float | None = None
    for t, rep, st in events:
        state[rep] = (st == "open")
        all_open = bool(state) and all(state.values())
        if all_open and open_since is None:
            open_since = t
        elif not all_open and open_since is not None:
            windows.append((open_since, t))
            open_since = None
    if open_since is not None:
        windows.append((open_since, float("inf")))
    return windows


def _overlap(lo: float, hi: float, windows) -> float:
    return sum(max(0.0, min(hi, w1) - max(lo, w0))
               for w0, w1 in windows if w1 > lo and w0 < hi)


def build_journeys(stanzas: list[_Stanza]) -> dict[str, dict]:
    """Join the corrected per-process streams by trace id. Returns
    {trace: journey}; a journey carries the request id, the corrected
    event list, the router marks (submit/dispatches/finish), the
    per-attempt lifecycle groups, and the segment list
    `report.request_waterfall` reduces."""
    journeys: dict[str, dict] = {}

    def j(trace: str) -> dict:
        return journeys.setdefault(trace, {
            "trace": trace, "rid": None,
            "submit_t": None, "finish_t": None, "e2e_ms": None,
            "dispatches": [],        # (t, attempt, replica, event)
            "attempts": {},          # attempt -> [(t, proc, rec)]
            "events": [],            # every correlated event
            "segments": [],
            "sources": set(),
        })

    for s in stanzas:
        for rec in s.recs:
            tr = rec.get("trace")
            if not isinstance(tr, str):
                continue
            t = s.t(rec)
            if t is None:
                continue
            ev = rec.get("event")
            jn = j(tr)
            jn["events"].append((t, s.name, rec))
            jn["sources"].add(s.name)
            rid = rec.get("id")
            if isinstance(rid, str):
                jn["rid"] = jn["rid"] or rid
            if s.is_router:
                if ev == "route":
                    jn["dispatches"].append(
                        (t, 0, rec.get("replica"), rec))
                    w = rec.get("wait_ms")
                    if isinstance(w, (int, float)):
                        jn["submit_t"] = t - float(w) / 1e3
                elif ev == "failover":
                    att = rec.get("attempt")
                    jn["dispatches"].append(
                        (t, att if isinstance(att, int) else None,
                         rec.get("replica"), rec))
                elif ev == "request":
                    e2e = rec.get("e2e_ms")
                    if isinstance(e2e, (int, float)):
                        jn["e2e_ms"] = float(e2e)
                        if jn["submit_t"] is None:
                            jn["submit_t"] = t - float(e2e) / 1e3
                    jn["finish_t"] = t
            elif ev == "lifecycle":
                att = rec.get("attempt")
                att = att if isinstance(att, int) else 0
                jn["attempts"].setdefault(att, []).append(
                    (t, s.name, rec))
    breaker_windows = _breaker_open_windows(stanzas)
    for jn in journeys.values():
        jn["events"].sort(key=lambda e: e[0])
        jn["dispatches"].sort(key=lambda d: d[0])
        for evs in jn["attempts"].values():
            evs.sort(key=lambda e: (e[2].get("seq", 0), e[0]))
        jn["sources"] = sorted(jn["sources"])
        _segment(jn, breaker_windows)
    return journeys


def _segment(jn: dict, breaker_windows) -> None:
    """Carve the journey's router-clock span into contiguous named
    segments. Standalone (router-less) journeys — a lone serve.py —
    degrade to the engine-phase components only."""
    segs: list[dict] = []

    def add(component: str, lo: float, hi: float, **extra) -> None:
        ms = max(0.0, (hi - lo)) * 1e3
        if ms <= 0.0:
            return
        segs.append({"component": component, "t0": lo, "t1": hi,
                     "ms": ms, **extra})

    attempts = sorted(jn["attempts"])
    # engine-side phases, per attempt: [event_i, event_{i+1}] is time
    # IN phase_i (the lifecycle contract). An attempt is TRUNCATED at
    # the next attempt's first event: a timeout failover abandons
    # live work, so the old replica can keep stamping (even
    # "finished") after the router moved the request elsewhere — the
    # user's stream switched at the failover, and booking the
    # abandoned tail would double-count against the real attempt's
    # work (and swallow the failover gap)
    starts = {att: jn["attempts"][att][0][0] for att in attempts}
    cutoff = {att: starts[nxt]
              for att, nxt in zip(attempts, attempts[1:])}
    attempt_bounds: dict[int, tuple[float, float]] = {}
    for att in attempts:
        evs = jn["attempts"][att]
        cut = cutoff.get(att, float("inf"))
        for (t0, proc, r0), (t1, _p1, _r1) in zip(evs, evs[1:]):
            if t0 >= cut:
                continue
            comp = PHASE_COMPONENT.get(r0.get("phase"))
            if comp and comp != "rq_dispatch":
                add(comp, t0, min(t1, cut), attempt=att, replica=proc)
        attempt_bounds[att] = (evs[0][0], min(evs[-1][0], cut))
    # router-side marks
    dispatches = {att: t for t, att, _rep, _rec in jn["dispatches"]
                  if att is not None}
    if jn["submit_t"] is not None and attempts:
        first_mark = (dispatches.get(attempts[0],
                                     attempt_bounds[attempts[0]][0]))
        add("rq_queue", jn["submit_t"], min(
            first_mark, attempt_bounds[attempts[0]][0]))
    if attempts:
        td = dispatches.get(attempts[0])
        if td is not None:
            # first dispatch -> the engine's first lifecycle stamp
            add("rq_dispatch", td, attempt_bounds[attempts[0]][0],
                attempt=attempts[0])
    # failover gaps: the whole hole in the user's stream — last event
    # of attempt k -> FIRST event of attempt k+1 (detection latency +
    # the re-dispatch + the resumed engine's ingestion all live in
    # here, which is why the gap >= the router's recorded detection ->
    # ready interval whenever the stitching is consistent); the
    # sub-span where every breaker was open books to rq_breaker_wait
    for prev, nxt in zip(attempts, attempts[1:]):
        lo = attempt_bounds[prev][1]
        hi = attempt_bounds[nxt][0]
        if hi > lo:
            bw = _overlap(lo, hi, breaker_windows)
            gap_ms = (hi - lo) * 1e3
            if bw > 0:
                segs.append({"component": "rq_breaker_wait",
                             "t0": lo, "t1": hi, "ms": bw * 1e3,
                             "attempt": nxt})
                gap_ms -= bw * 1e3
            if gap_ms > 0:
                segs.append({"component": "rq_failover_gap",
                             "t0": lo, "t1": hi, "ms": gap_ms,
                             "attempt": nxt})
    # tail: engine finished -> the router's request finalize (progress
    # poll + transport, the symmetric half of rq_dispatch)
    if attempts and jn["finish_t"] is not None:
        add("rq_dispatch", attempt_bounds[attempts[-1]][1],
            jn["finish_t"], tail=True)
    if jn["e2e_ms"] is None and attempts:
        # standalone serving: e2e is the engine-phase span
        lo = attempt_bounds[attempts[0]][0]
        hi = attempt_bounds[attempts[-1]][1]
        jn["submit_t"] = jn["submit_t"] or lo
        jn["finish_t"] = jn["finish_t"] or hi
        jn["e2e_ms"] = (hi - lo) * 1e3
    segs.sort(key=lambda s: s["t0"])
    jn["segments"] = segs


# --------------------------------------------------------- chrome trace


def _chrome(stanzas, journeys) -> dict:
    """One Perfetto-loadable Chrome trace: pid per process (router =
    pid 0), per-replica request tracks with the lifecycle phase spans,
    and a per-request journey track on the router pid showing the
    waterfall segments in timeline order."""
    events: list[dict] = []
    t0s = [s.t(r) for s in stanzas for r in s.recs
           if s.t(r) is not None]
    epoch = min(t0s) if t0s else 0.0

    def us(t: float) -> float:
        return round((t - epoch) * 1e6, 1)

    pid_of: dict[str, int] = {}
    for s in stanzas:
        if s.name in pid_of:
            continue
        pid_of[s.name] = 0 if s.is_router else len(pid_of) + 1
    router = [s.name for s in stanzas if s.is_router]
    if router and pid_of.get(router[0]) != 0:
        pid_of[router[0]] = 0
    for name, pid in sorted(pid_of.items(), key=lambda kv: kv[1]):
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "tid": 0, "ts": 0.0, "args": {"name": name}})
    # replica tracks: one tid per (rid, attempt) within a replica pid
    tids: dict[tuple, int] = {}

    def tid(pid: int, key) -> int:
        k = (pid, key)
        if k not in tids:
            tids[k] = len([1 for p, _ in tids if p == pid]) + 1
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pid, "tid": tids[k], "ts": 0.0,
                           "args": {"name": str(key)}})
        return tids[k]

    for jn in journeys.values():
        rid = jn["rid"] or jn["trace"][:8]
        for att, evs in sorted(jn["attempts"].items()):
            for (t0, proc, r0), (t1, _p, _r) in zip(evs, evs[1:]):
                pid = pid_of.get(proc, 0)
                events.append({
                    "name": r0.get("phase", "?"), "ph": "X",
                    "pid": pid,
                    "tid": tid(pid, f"{rid}#{att}"),
                    "ts": us(t0), "dur": round((t1 - t0) * 1e6, 1),
                    "args": {"id": rid, "trace": jn["trace"],
                             "attempt": att,
                             "tick": r0.get("tick")}})
        # the journey track on the router pid
        for seg in jn["segments"]:
            events.append({
                "name": seg["component"], "ph": "X", "pid": 0,
                "tid": tid(0, f"request {rid}"),
                "ts": us(seg["t0"]),
                "dur": round(seg["ms"] * 1e3, 1),
                "args": {k: v for k, v in seg.items()
                         if k not in ("t0", "t1")}
                | {"id": rid, "trace": jn["trace"]}})
        for t, att, rep, _rec in jn["dispatches"]:
            events.append({
                "name": "failover" if att else "route", "ph": "i",
                "pid": 0, "tid": tid(0, f"request {rid}"),
                "ts": us(t), "args": {"id": rid, "attempt": att,
                                      "replica": rep}})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# --------------------------------------------------------------- public


def stitch(paths) -> dict:
    """Stitch N metrics JSONLs (one router log + replica logs, or a
    lone serving log) into one corrected view:

        {"processes": [...per-stanza fit report...],
         "journeys": {trace: journey},
         "chrome": {... Perfetto-loadable ...}}
    """
    stanzas = _load_stanzas(paths)
    _fit_offsets(stanzas)
    journeys = build_journeys(stanzas)
    return {
        "processes": [{"source": s.source, "stanza": s.index,
                       "name": s.name, "router": s.is_router,
                       "clock": s.base,
                       "offset_s": round(s.offset, 6),
                       "pairs": dict(s.pairs)}
                      for s in stanzas],
        "journeys": journeys,
        "chrome": _chrome(stanzas, journeys),
    }


def goodput_block(paths, first_recs=None) -> dict | None:
    """The ``--goodput`` tracing block: fleet-level aggregation of the
    per-request waterfalls — p50/p95 ms per component plus the
    worst-``rq_unexplained`` exemplars (stitching-quality forensics).
    None when no stream carries trace-context lifecycle events.
    `first_recs` forwards the caller's already-parsed paths[0]."""
    from shallowspeed_tpu.telemetry.report import (percentile,
                                                   request_waterfall)

    stanzas = _load_stanzas(paths, first_recs=first_recs)
    if not any(r.get("event") == "lifecycle"
               and isinstance(r.get("trace"), str)
               for s in stanzas for r in s.recs):
        return None
    _fit_offsets(stanzas)
    journeys = build_journeys(stanzas)
    falls = []
    for jn in journeys.values():
        wf = request_waterfall(jn)
        if wf is not None:
            wf["id"] = jn["rid"]
            wf["trace"] = jn["trace"]
            falls.append(wf)
    if not falls:
        return None
    comps = {}
    for name in COMPONENTS + ("rq_unexplained",):
        vals = [wf[f"{name}_ms"] for wf in falls]
        if any(vals):
            comps[name] = {
                "p50_ms": round(percentile(vals, 50), 3),
                "p95_ms": round(percentile(vals, 95), 3)}
    worst = sorted(falls, key=lambda wf: -abs(wf["rq_unexplained_ms"]))
    return {
        "requests": len(falls),
        "components": comps,
        "e2e_p50_ms": round(percentile(
            [wf["e2e_ms"] for wf in falls], 50), 3),
        "worst_unexplained": [
            {"id": wf["id"], "trace": wf["trace"],
             "rq_unexplained_ms": round(wf["rq_unexplained_ms"], 3),
             "e2e_ms": round(wf["e2e_ms"], 3)}
            for wf in worst[:3]],
    }


def format_stitch(st: dict) -> str:
    """Human summary of one stitch (the --trace-stitch console
    surface); the Chrome JSON itself goes to --out."""
    from shallowspeed_tpu.telemetry.report import request_waterfall

    lines = []
    for p in st["processes"]:
        role = "router " if p["router"] else "replica"
        lines.append(
            f"{role} {p['name']:<12} stanza {p['stanza']}  "
            f"clock {p['clock']:<4} offset {p['offset_s']:+.6f}s  "
            f"pairs d/a {p['pairs']['dispatch']}/{p['pairs']['ack']}")
    lines.append(f"{len(st['journeys'])} traced request(s)")
    for jn in sorted(st["journeys"].values(),
                     key=lambda j: -(j["e2e_ms"] or 0.0)):
        wf = request_waterfall(jn)
        if wf is None:
            continue
        parts = [f"{k[3:]} {wf[f'{k}_ms']:.0f}"
                 for k in COMPONENTS + ("rq_unexplained",)
                 if abs(wf[f"{k}_ms"]) >= 0.5]
        lines.append(
            f"  {jn['rid'] or jn['trace'][:8]:<8} "
            f"e2e {wf['e2e_ms']:8.1f} ms  "
            f"attempts {len(jn['attempts'])}  "
            f"[{', '.join(jn['sources'])}]  " + "  ".join(parts))
    return "\n".join(lines)


def stitch_main(paths, out: str | None = None,
                printer=print) -> int:
    """``--trace-stitch`` entry: stitch, write the Chrome trace, print
    the fit + per-request waterfall summary."""
    missing = [p for p in paths if not Path(p).exists()]
    if missing:
        printer(f"--trace-stitch: no such file(s): "
                f"{', '.join(missing)}")
        return 1
    st = stitch(paths)
    if out:
        Path(out).write_text(json.dumps(st["chrome"]))
        printer(f"wrote {out} "
                f"({len(st['chrome']['traceEvents'])} events — load "
                f"in Perfetto / chrome://tracing)")
    printer(format_stitch(st))
    return 0
