"""CLI: validate committed JSONL, gate the bench trajectory, reduce a
run's goodput ledger, or watch a run live.

    python -m shallowspeed_tpu.telemetry --validate docs_runs/*.jsonl
    python -m shallowspeed_tpu.telemetry --validate docs_runs/
    python -m shallowspeed_tpu.telemetry --regress BENCH_*.json
    python -m shallowspeed_tpu.telemetry --regress .
    python -m shallowspeed_tpu.telemetry --goodput run/metrics.jsonl
    python -m shallowspeed_tpu.telemetry --goodput run/router.jsonl \
        run/replica_r0.jsonl run/replica_r1.jsonl
    python -m shallowspeed_tpu.telemetry --trace-stitch \
        run/router.jsonl run/replica_r0.jsonl run/replica_r1.jsonl \
        --out stitched.json
    python -m shallowspeed_tpu.telemetry --profile run/metrics.jsonl \
        --out flame.json
    python -m shallowspeed_tpu.telemetry --live run/metrics.jsonl
    python -m shallowspeed_tpu.telemetry --live f.jsonl --once
    python -m shallowspeed_tpu.telemetry --fleet http://127.0.0.1:9100 \
        http://127.0.0.1:9101 --port 9200
    python -m shallowspeed_tpu.telemetry --fleet r0.jsonl r1.jsonl --once

--validate and --regress are the pre-commit gates for committed
`docs_runs/*.jsonl` snapshots and the `BENCH_r*.json` trajectory —
both pure-stdlib checks that cost only the package import (~1 s), not
a trace or a bench run of anything. --goodput prints the run-level
wall-clock decomposition (goodput + named losses) of one metrics
JSONL, including runs that span supervisor restarts; extra files
after the first are replica logs joined BY TRACE ID into the
per-request waterfall (tracing) block. --trace-stitch joins a
router log + N replica logs (schema v11 trace context) on one
skew-corrected timeline and writes a Perfetto-loadable Chrome trace
(--out) with per-replica tracks and a per-request journey track —
queue-wait -> dispatch -> prefill -> decode -> failover gap ->
re-prefill -> decode -> finish (telemetry/tracing.py). --live tails a
GROWING metrics JSONL and renders the same view the --monitor-port
/status.json endpoint serves (streaming sketch quantiles, goodput so
far, health, SLO burn rates with --slo) — live monitoring for runs
started without an endpoint; --once renders the current state and
exits (the pre-commit smoke mode). --fleet aggregates N replicas
(status URLs and/or metrics JSONL files) into one fleet view — merged
quantiles, per-replica breakdown, fleet SLO burn, straggler detection
— optionally re-served on --port as the fleet's own /status.json +
/metrics (telemetry/fleet.py).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m shallowspeed_tpu.telemetry")
    g = p.add_mutually_exclusive_group(required=True)
    g.add_argument("--validate", nargs="+", metavar="PATH",
                   help="JSONL files (or directories scanned for "
                        "*.jsonl) to check against the telemetry/"
                        "metrics schema")
    g.add_argument("--regress", nargs="+", metavar="PATH",
                   help="BENCH_r*.json files (or directories scanned "
                        "for them) — fail when the newest round drops "
                        "below the prior rounds beyond the noise band")
    g.add_argument("--goodput", nargs="+", metavar="JSONL",
                   help="reduce one metrics JSONL to the goodput "
                        "report (wall-clock decomposition + losses, "
                        "per-failure-class MTTR, availability, the "
                        "injected-fault tally on chaos drills, and "
                        "p50/p95 ttft/tpot on serving runs with "
                        "schema-v6 request events); extra files are "
                        "replica logs joined by trace id into the "
                        "per-request waterfall block (schema v11)")
    g.add_argument("--trace-stitch", nargs="+", metavar="JSONL",
                   help="join a router log + N replica logs on one "
                        "skew-corrected timeline (schema-v11 trace "
                        "context; per-stanza offsets fitted from the "
                        "router's dispatch/ack pairs) and write a "
                        "Perfetto-loadable Chrome trace to --out; "
                        "prints the clock fit and each request's "
                        "latency waterfall")
    g.add_argument("--profile", nargs="+", metavar="JSONL",
                   help="reduce the schema-v12 'profile' events of one "
                        "or more metrics JSONLs (the host sampling "
                        "profiler's cumulative snapshots; multiple "
                        "files/stanzas merge replica-prefixed) to a "
                        "flamegraph JSON (--out) + a printed "
                        "top-frames/phases summary")
    g.add_argument("--live", metavar="JSONL",
                   help="tail a growing metrics JSONL and render the "
                        "live status view (the /status.json surface "
                        "for endpoint-less runs); Ctrl-C exits")
    g.add_argument("--fleet", nargs="+", metavar="TARGET",
                   help="aggregate N replicas into one fleet view: "
                        "http(s) targets are polled /status.json + "
                        "/sketches.json endpoints, anything else is a "
                        "metrics JSONL to tail (telemetry/fleet.py) — "
                        "merged quantiles, per-replica breakdown, "
                        "fleet SLO burn, straggler detection")
    p.add_argument("--once", action="store_true",
                   help="with --live/--fleet: render the current "
                        "state once and exit instead of following")
    p.add_argument("--slo", default="",
                   help="with --live/--fleet: evaluate these SLOs "
                        "over the (merged) stream (telemetry/monitor "
                        "DSL, e.g. 'ttft_p95_ms<500,"
                        "availability>0.99')")
    p.add_argument("--interval", type=float, default=2.0,
                   help="with --live/--fleet: seconds between renders")
    p.add_argument("--port", type=int, default=None,
                   help="with --fleet: ALSO serve the fleet's own "
                        "/status.json + /metrics (replica-labelled) "
                        "here (0 = free port)")
    p.add_argument("--log-file", default=None,
                   help="with --fleet: append straggler/alert events "
                        "(schema v8) to this JSONL")
    p.add_argument("--out", default=None, metavar="PATH",
                   help="with --trace-stitch: where the Chrome trace "
                        "JSON lands (default: stitched_trace.json "
                        "next to the first input); with --profile: "
                        "where the flamegraph JSON lands (omitted = "
                        "summary only)")
    args = p.parse_args(argv)

    if args.profile:
        from shallowspeed_tpu.telemetry.profiler import profile_main

        return profile_main(args.profile, out=args.out)

    if args.trace_stitch:
        from shallowspeed_tpu.telemetry.tracing import stitch_main

        out = args.out
        if out is None:
            out = str(Path(args.trace_stitch[0]).parent
                      / "stitched_trace.json")
        return stitch_main(args.trace_stitch, out=out)

    if args.fleet:
        from shallowspeed_tpu.telemetry.fleet import fleet_main

        return fleet_main(args.fleet, slos=args.slo, once=args.once,
                          interval=args.interval, port=args.port,
                          log_file=args.log_file)

    if args.live:
        from shallowspeed_tpu.telemetry.monitor import live_main

        return live_main(args.live, slos=args.slo, once=args.once,
                         interval=args.interval)

    if args.regress:
        from shallowspeed_tpu.telemetry.regress import main as rmain

        return rmain(args.regress)
    if args.goodput:
        from shallowspeed_tpu.telemetry.goodput import (format_report,
                                                        run_goodput)

        print(format_report(run_goodput(
            args.goodput[0], extra_paths=args.goodput[1:])))
        return 0

    from shallowspeed_tpu.telemetry.schema import validate_file

    files: list[Path] = []
    for raw in args.validate:
        path = Path(raw)
        if path.is_dir():
            files.extend(sorted(path.glob("*.jsonl")))
        else:
            files.append(path)
    if not files:
        print("no .jsonl files to validate")
        return 0
    problems = []
    for f in files:
        if not f.exists():
            problems.append(f"{f}: no such file")
            continue
        problems.extend(validate_file(f))
    for prob in problems:
        print(prob, file=sys.stderr)
    print(f"validated {len(files)} file(s): "
          f"{'OK' if not problems else f'{len(problems)} problem(s)'}")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
