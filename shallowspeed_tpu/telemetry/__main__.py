"""CLI: validate committed trace/metrics JSONL against the schema.

    python -m shallowspeed_tpu.telemetry --validate docs_runs/*.jsonl
    python -m shallowspeed_tpu.telemetry --validate docs_runs/

Exits 1 listing path:line problems; 0 when every line conforms. This
is the pre-commit gate for `docs_runs/*.jsonl` — the schema module is
pure stdlib, so the check costs only the package import (~1 s), not a
trace of anything.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="python -m shallowspeed_tpu.telemetry")
    p.add_argument("--validate", nargs="+", metavar="PATH", required=True,
                   help="JSONL files (or directories scanned for "
                        "*.jsonl) to check against the telemetry/"
                        "metrics schema")
    args = p.parse_args(argv)

    from shallowspeed_tpu.telemetry.schema import validate_file

    files: list[Path] = []
    for raw in args.validate:
        path = Path(raw)
        if path.is_dir():
            files.extend(sorted(path.glob("*.jsonl")))
        else:
            files.append(path)
    if not files:
        print("no .jsonl files to validate")
        return 0
    problems = []
    for f in files:
        if not f.exists():
            problems.append(f"{f}: no such file")
            continue
        problems.extend(validate_file(f))
    for prob in problems:
        print(prob, file=sys.stderr)
    print(f"validated {len(files)} file(s): "
          f"{'OK' if not problems else f'{len(problems)} problem(s)'}")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
