"""Fleet observability — N live processes, one view.

Everything in `monitor.py` is per-process: one `Monitor`, one
`/status.json`, one metrics file. A serving fleet (ROADMAP item 2's
router/autoscaler) and an MPMD stage controller (item 3) both need the
NEXT layer: merged quantiles across replicas, fleet goodput and
availability, per-replica breakdown, SLO burn over the merged stream,
and — the scheduling-relevant signal — which replica is the straggler.
`FleetCollector` builds that from parts that already exist:

- **Replica sources.** Each replica is either a live endpoint (polled:
  ``/status.json`` for the summary view plus ``/sketches.json`` for
  the SERIALIZED mergeable sketches) or a metrics JSONL file (tailed
  through `monitor.FileTailer` into a per-replica `Monitor`,
  truncation/rotation-safe). Replicas can also self-register: the
  fleet's own endpoint accepts ``POST /register {"url", "name"}``
  (serve.py's ``--fleet-register``).
- **Merged quantiles.** Fleet p50/p95/p99 per metric are EXACT bucket
  unions of the replicas' latest cumulative sketches
  (`sketch.LogHistogram.merge` — exact counts, so the fleet quantile
  is provably within the recorded rel_err of the pooled offline
  reduction, the same contract `--goodput`'s monitor block pins
  per-process). Mixed-rel_err replicas reduce to the largest
  same-rel_err group, like the offline reducer.
- **Fleet SLOs.** The same declarative rules (`monitor.parse_slos`)
  evaluated over the merged stream: each refresh diffs every
  replica's sketch against its previous poll and feeds the DELTA
  bad/total counts (`LogHistogram.count_above` vs the rule threshold)
  into the dual-window burn evaluator — no raw values needed, the
  bucket boundary costs at most rel_err. Unreachable endpoint
  replicas feed the availability rule as downtime.
- **Straggler/skew detection.** Per refresh, each replica's quantile
  (p50 by default) of each watched metric (step_ms, ttft_ms) is
  scored against the median of its PEERS' quantiles (leave-one-out —
  a median that included the straggler itself would dilute the
  signal; with 2 replicas the self-inclusive ratio can never pass
  2x): the ratio stream runs through `anomaly.RobustEWMA` (a z-spike
  marks a replica that just CHANGED) and against an absolute
  divergence factor (a replica persistently ≥2x its peers is a
  straggler even after its own EWMA has normalized). Sustained
  divergence
  (`patience` consecutive rounds) emits a schema-v8 ``"straggler"``
  event naming the replica and dumps the flight ring; recovery emits
  the matching "resolved".
- **Exemplar linkage.** Each replica's monitor keeps the worst-K
  (ttft, request-id) pairs; the fleet view merges them with replica
  labels, so a burning ttft SLO resolves to "request r17 on replica
  b" in one hop — and `report.request_timeline` over that replica's
  JSONL reconstructs WHICH PHASE the time went to.

Serving surface: `FleetCollector.status()` / `.prometheus()` plug
into `monitor.StatusServer` unchanged (replica-labelled series, label
values escaped). Standalone:

    python -m shallowspeed_tpu.telemetry --fleet \
        http://127.0.0.1:9100 http://127.0.0.1:9101   # endpoints
    python -m shallowspeed_tpu.telemetry --fleet r0.jsonl r1.jsonl \
        --once                                        # files, one shot

Embedded: the elastic `GangSupervisor` grows one collector over all
children's per-member metrics files (`elastic.py`).

Pure stdlib, like `monitor` and `sketch` — a fleet collector runs on
any box that can reach the replicas.
"""

from __future__ import annotations

import json
import random
import statistics
import threading
import time
import urllib.request
from pathlib import Path

from shallowspeed_tpu.telemetry.anomaly import RobustEWMA
from shallowspeed_tpu.telemetry.monitor import (EXEMPLAR_K, FileTailer,
                                                FlightRecorder, Monitor,
                                                parse_slos, prom_escape,
                                                prom_histogram_lines)
from shallowspeed_tpu.telemetry.sketch import LogHistogram, MetricSketches

# per-replica quantile metrics the straggler detector watches, and the
# quantile it scores (the median is robust to a replica's own tail)
STRAGGLER_METRICS = ("step_ms", "ttft_ms")
STRAGGLER_Q = 50


class Replica:
    """One fleet member: an endpoint to poll or a file to tail, plus
    the latest observed state the collector aggregates.

    Unreachable endpoints back off exponentially (seeded jitter, round
    15) instead of re-GETting every refresh round: a dead replica used
    to cost every round a full connect-timeout, which is exactly when
    the fleet's own /status.json most needs the poll loop responsive.
    The backoff state is visible in the per-replica breakdown
    (`summary()["backoff"]`), downtime keeps feeding the availability
    rule on skipped rounds, and a successful poll (or a
    re-registration) resets the stream."""

    def __init__(self, name: str | None, url: str | None = None,
                 path=None, timeout: float = 5.0,
                 poll_backoff: float = 1.0,
                 poll_backoff_max: float = 30.0):
        assert (url is None) != (path is None), "exactly one source"
        self._label = name
        self.uid = -1            # stable collector-assigned index: the
        #                          internal key (display names can
        #                          collide — two fleets' metrics.jsonl)
        self.url = url.rstrip("/").removesuffix("/status.json") \
            if url else None
        self.path = str(path) if path is not None else None
        self.timeout = float(timeout)
        self.poll_backoff = float(poll_backoff)
        self.poll_backoff_max = float(poll_backoff_max)
        self.fail_streak = 0      # consecutive failed polls
        self.backoff_s = 0.0      # current backoff window (jittered)
        self.next_poll = 0.0      # wall before which refresh skips I/O
        self._rng = random.Random(url or str(path))
        self.alive = False
        self.last_seen: float | None = None
        self.error: str | None = None
        self._status: dict = {}
        self._profile: dict = {}    # latest profiler snapshot (rnd 17)
        self._exemplars: dict = {}
        self._rel_err: float | None = None
        self._sketches: dict[str, LogHistogram] = {}
        self._mon: Monitor | None = None
        self._tailer: FileTailer | None = None
        if self.path is not None:
            # snapshot_every=0: the collector reads the live sketches
            # directly, re-emitting "monitor" lines would be noise
            self._mon = Monitor(flight=0, derive_steps=True,
                                snapshot_every=0)
            self._tailer = FileTailer(self.path, self)  # drained, not run

    @property
    def name(self) -> str:
        if self._label:
            return self._label
        if self.path is not None:
            return Path(self.path).stem
        return self.url or "?"

    def note_line(self, rec: dict) -> None:
        """FileTailer target: learn the replica label from the child's
        run_start stamp (--replica), forward everything to the
        per-replica Monitor."""
        if isinstance(rec, dict) and rec.get("event") == "run_start" \
                and isinstance(rec.get("replica"), str):
            self._label = self._label or rec["replica"]
        self._mon.note_line(rec)

    # ------------------------------------------------------------ poll

    def refresh(self, now: float) -> bool:
        """One observation round; returns liveness. File replicas are
        'alive' once the file has yielded any line; endpoint replicas
        are alive iff both GETs answered this round."""
        if self.path is not None:
            n = self._tailer.drain()
            # SNAPSHOT under the monitor's lock (sketch_payload), then
            # parse into private LogHistograms — status()/prometheus()
            # readers iterate these without a lock, so they must never
            # alias the live dicts the next drain mutates
            payload = self._mon.sketch_payload()
            self._sketches = {
                name: LogHistogram.from_dict(d)
                for name, d in payload["sketches"].items()}
            self._rel_err = float(payload["rel_err"])
            self._exemplars = payload["exemplars"]
            self._status = self._mon.status()
            # file-fed replicas: the latest tailed "profile" event
            # (cumulative snapshot — last wins) is this replica's
            # contribution to the fleet flamegraph
            self._profile = self._mon.last_profile or {}
            if n or self._mon.counters["lines"]:
                self.alive = True
                self.error = None
                if n:
                    self.last_seen = now
            return self.alive
        if self.fail_streak and now < self.next_poll:
            # backing off: no I/O this round (the replica stays "down"
            # and keeps burning availability; summary() shows why)
            return False
        try:
            self._status = self._get("/status.json")
            payload = self._get("/sketches.json")
        except Exception as e:
            self.alive = False
            self.error = f"{type(e).__name__}: {e}"
            self.fail_streak += 1
            base = min(self.poll_backoff * 2 ** (self.fail_streak - 1),
                       self.poll_backoff_max)
            self.backoff_s = base * (1.0 + 0.25 * self._rng.random())
            self.next_poll = now + self.backoff_s
            return False
        self.fail_streak = 0
        self.backoff_s = 0.0
        # /profile.json is best-effort and NEWER than the replicas'
        # required surface: an error here (404 JSON body on a pre-v12
        # replica, a profiler-off run) must not mark the replica down
        try:
            prof = self._get("/profile.json")
            self._profile = prof if prof.get("enabled") else {}
        except Exception:
            self._profile = {}
        self._label = self._label or payload.get("label") \
            or self._status.get("replica")
        self._rel_err = float(payload.get("rel_err", 0.01))
        self._sketches = {
            name: LogHistogram.from_dict(d)
            for name, d in (payload.get("sketches") or {}).items()}
        self._exemplars = payload.get("exemplars") or {}
        self.alive = True
        self.last_seen = now
        self.error = None
        return True

    def _get(self, endpoint: str) -> dict:
        with urllib.request.urlopen(self.url + endpoint,
                                    timeout=self.timeout) as r:
            return json.loads(r.read())

    # ----------------------------------------------------------- views

    def sketch(self, name: str) -> LogHistogram | None:
        return self._sketches.get(name)

    def profile(self) -> dict:
        """This replica's latest profiler snapshot ({} = profiler off
        or pre-v12 replica) — the fleet flamegraph's input."""
        return {k: v for k, v in self._profile.items()
                if k not in ("event", "t", "wall", "mono", "enabled")}

    def serialized_sketches(self) -> dict:
        return {name: sk.to_dict()
                for name, sk in self._sketches.items() if sk.n}

    def summary(self) -> dict:
        """The per-replica block of the fleet /status.json."""
        st = self._status or {}
        out = {
            "source": self.url or self.path,
            "alive": self.alive,
            "last_seen": self.last_seen,
            "health": st.get("health"),
            "goodput_so_far": st.get("goodput_so_far"),
            "availability": st.get("availability"),
            "last_step": st.get("last_step"),
            "serving": st.get("serving"),
            "numerics": st.get("numerics"),
            "memory": st.get("memory"),
            "alerts": st.get("alerts") or [],
            "quantiles": {name: {"count": sk.n,
                                 "p50": sk.quantile(50),
                                 "p95": sk.quantile(95)}
                          for name, sk in sorted(self._sketches.items())
                          if sk.n},
        }
        if self.error:
            out["error"] = self.error
        if self.fail_streak:
            out["backoff"] = {"failures": self.fail_streak,
                              "backoff_s": round(self.backoff_s, 3),
                              "retry_at": round(self.next_poll, 3)}
        return out


class FleetCollector:
    """Aggregate N replicas into one live fleet view (module
    docstring). `status()`/`prometheus()` make it a drop-in
    `StatusServer` target; `refresh()` is one aggregation round
    (`start()`/`stop()` run it on a daemon thread for embedded use)."""

    def __init__(self, urls=(), paths=(), labels=None, slos: str = "",
                 straggler_metrics=STRAGGLER_METRICS,
                 straggler_q: int = STRAGGLER_Q,
                 straggler_factor: float = 2.0,
                 straggler_z: float = 6.0,
                 straggler_patience: int = 3,
                 straggler_min_count: int = 8,
                 flight: int = 0, flight_dir=None, emit=None,
                 log_file=None, clock=time.time, timeout: float = 5.0,
                 slo_kw: dict | None = None):
        self.clock = clock
        self.timeout = float(timeout)
        self._lock = threading.RLock()
        self.replicas: list[Replica] = []
        labels = list(labels) if labels else []
        for i, u in enumerate(urls):
            self.add_url(u, labels[i] if i < len(labels) else None)
        off = len(list(urls))
        for i, p in enumerate(paths):
            self.add_file(p, labels[off + i]
                          if off + i < len(labels) else None)
        self.rules = parse_slos(slos, **(slo_kw or {}))
        self.straggler_metrics = tuple(straggler_metrics)
        self.straggler_q = int(straggler_q)
        self.straggler_factor = float(straggler_factor)
        self.straggler_z = float(straggler_z)
        self.straggler_patience = int(straggler_patience)
        self.straggler_min_count = int(straggler_min_count)
        self.flight = (FlightRecorder(capacity=flight,
                                      out_dir=flight_dir)
                       if flight > 0 else None)
        self.emit = emit
        self.log_file = str(log_file) if log_file else None
        self.events: list[dict] = []     # every straggler/alert emitted
        # round 17: profiling-plane hooks — a firing straggler event
        # invokes each listener(rec) (ProfilerPlane.on_straggler arms
        # a capture window); a broken listener must not kill scoring
        self.straggler_listeners: list = []
        self.active_alerts: dict[str, dict] = {}
        self.stragglers: dict[tuple, dict] = {}
        self.counters = {"refreshes": 0, "stragglers": 0, "alerts": 0,
                         "flight_dumps": 0}
        self._ewma: dict[tuple, RobustEWMA] = {}
        self._runs: dict[tuple, int] = {}
        self._slo_prev: dict[tuple, tuple] = {}  # (spec, uid) -> (bad, tot)
        self._last_refresh: float | None = None
        # serializes replica polling only: two concurrent refresh()
        # calls (the embedded loop + a manual/HTTP-driven one) must
        # not drain the same tailer twice from one position — while
        # status()/prometheus() readers, who take only _lock, stay
        # responsive during a slow poll
        self._poll_lock = threading.Lock()
        self._halt = threading.Event()
        self._thread: threading.Thread | None = None

    # -------------------------------------------------------- members

    def add_url(self, url: str, label: str | None = None) -> Replica:
        with self._lock:
            rep = Replica(label, url=url, timeout=self.timeout)
            rep.uid = len(self.replicas)
            self.replicas.append(rep)
            return rep

    def add_file(self, path, label: str | None = None) -> Replica:
        with self._lock:
            rep = Replica(label, path=path)
            rep.uid = len(self.replicas)
            self.replicas.append(rep)
            return rep

    def _display_names(self) -> dict:
        """uid -> unique display name: a colliding name (two fleets'
        metrics.jsonl tailed without labels) gets '#uid' appended so
        the per-replica breakdown and prometheus labels stay
        one-row-per-replica; internal state is keyed by uid, never by
        the display name."""
        out, seen = {}, set()
        for rep in self.replicas:
            name = rep.name
            if name in seen:
                name = f"{name}#{rep.uid}"
            seen.add(name)
            out[rep.uid] = name
        return out

    def register_replica(self, payload: dict) -> dict:
        """POST /register body: {"url": status URL, "name": label}.
        Re-registration of a known URL refreshes its label instead of
        duplicating the replica (a restarted replica re-announces);
        re-registration of a known NAME at a new URL re-points that
        replica (a respawned process binds a fresh port — its history,
        straggler state and uid stay attached to the name). Either
        way the poller's backoff resets: a replica announcing itself
        is the strongest possible liveness signal."""
        url = payload.get("url")
        if not isinstance(url, str) or not url.startswith("http"):
            raise ValueError(f"register needs a status 'url', got "
                             f"{url!r}")
        name = payload.get("name")
        with self._lock:
            base = url.rstrip("/").removesuffix("/status.json")
            for rep in self.replicas:
                if rep.url == base or (name and rep.url is not None
                                       and rep._label == name):
                    rep._label = name or rep._label
                    rep.url = base
                    rep.fail_streak = 0
                    rep.backoff_s = 0.0
                    rep.next_poll = 0.0
                    return {"ok": True, "replicas": len(self.replicas)}
            self.add_url(url, name)
            return {"ok": True, "replicas": len(self.replicas)}

    def deregister_replica(self, payload: dict) -> dict:
        """POST /deregister body: {"url" and/or "name"} — removal on
        clean drain. Registration used to be one-way: a drained
        replica stayed in the fleet as "unreachable" and burned
        availability forever. Removes the replica AND purges its
        uid-keyed detector state (SLO deltas, straggler EWMAs) so a
        later replica re-using the name starts clean. Unknown
        replicas raise (the HTTP surface turns that into a 400)."""
        url = payload.get("url")
        name = payload.get("name")
        base = url.rstrip("/").removesuffix("/status.json") \
            if isinstance(url, str) else None
        with self._lock:
            for rep in self.replicas:
                if (base is not None and rep.url == base) \
                        or (name and rep.name == name):
                    self.replicas.remove(rep)
                    uid = rep.uid
                    self._slo_prev = {k: v for k, v
                                      in self._slo_prev.items()
                                      if k[1] != uid}
                    for d in (self._ewma, self._runs, self.stragglers):
                        for key in [k for k in d if k[0] == uid]:
                            del d[key]
                    return {"ok": True, "replicas": len(self.replicas),
                            "removed": rep.name}
            raise ValueError(
                f"deregister: no replica matches url={url!r} / "
                f"name={name!r}")

    # --------------------------------------------------------- refresh

    def refresh(self) -> dict:
        """One aggregation round: poll/drain every replica, evaluate
        fleet SLOs on the sketch deltas, score stragglers. Returns the
        fleet status payload. The blocking I/O (endpoint GETs can hang
        for `timeout` seconds on a dead replica) runs OUTSIDE the
        collector lock — the fleet's own /status.json must stay
        responsive exactly when a replica is down."""
        now = self.clock()
        with self._lock:
            dt = (now - self._last_refresh
                  if self._last_refresh is not None else None)
            self.counters["refreshes"] += 1
            reps = list(self.replicas)
        with self._poll_lock:
            polled = [(rep, rep.refresh(now)) for rep in reps]
        with self._lock:
            for rep, up in polled:
                if not up and rep.url is not None and dt:
                    # an unreachable endpoint is fleet downtime for
                    # the availability SLO
                    for rule in self.rules:
                        if rule.sketch is None:
                            rule.record_down(float(dt), now)
                if self.flight is not None:
                    self.flight.record(
                        {"event": "fleet_poll", "replica": rep.name,
                         "alive": up, "wall": round(now, 3),
                         "quantiles": rep.summary()["quantiles"]})
            self._feed_slos(now)
            self._score_stragglers(now)
            for rule in self.rules:
                rec = rule.evaluate(now)
                if rec is None:
                    continue
                self.counters["alerts"] += 1
                if rec["state"] == "firing":
                    self.active_alerts[rule.spec] = rec
                    self._flight_dump(f"slo:{rule.spec}", rec)
                else:
                    self.active_alerts.pop(rule.spec, None)
                self._emit("alert", rec, now)
            self._last_refresh = now
            return self._status_locked(now)

    def _feed_slos(self, now: float) -> None:
        """Quantile rules over the merged stream: per replica, diff
        the cumulative (bad, total) counts against the rule threshold
        since the last poll and feed the deltas. A shrunk total means
        the replica restarted — re-baseline, don't feed."""
        for rule in self.rules:
            if rule.sketch is None:
                continue
            bad_d = tot_d = 0
            for rep in self.replicas:
                sk = rep.sketch(rule.sketch)
                if sk is None or not sk.n:
                    continue
                above = sk.count_above(rule.threshold)
                bad = above if rule.op == "<" else sk.n - above
                key = (rule.spec, rep.uid)
                pb, pt = self._slo_prev.get(key, (0, 0))
                if sk.n < pt:
                    pb, pt = 0, 0
                bad_d += max(0, bad - pb)
                tot_d += sk.n - pt
                self._slo_prev[key] = (bad, sk.n)
            if tot_d > 0:
                rule.record_counts(bad_d, tot_d, now)

    def _score_stragglers(self, now: float) -> None:
        names = self._display_names()
        for metric in self.straggler_metrics:
            vals = {}
            for rep in self.replicas:
                sk = rep.sketch(metric)
                if sk is not None and sk.n >= self.straggler_min_count:
                    vals[rep.uid] = sk.quantile(self.straggler_q)
            if len(vals) < 2:
                continue
            for uid, v in vals.items():
                # leave-one-out: score against the median of the
                # PEERS — a fleet median that includes the straggler
                # itself dilutes the signal (with 2 replicas the
                # self-inclusive ratio can never exceed 2.0 however
                # bad the skew)
                med = statistics.median(
                    [x for u, x in vals.items() if u != uid])
                if med <= 0:
                    continue
                name = names[uid]
                key = (uid, metric)
                ratio = v / med
                ew = self._ewma.setdefault(
                    key, RobustEWMA(alpha=0.3,
                                    warmup=self.straggler_patience))
                z = ew.update(ratio)
                # two detectors: the absolute factor catches a replica
                # persistently far off the fleet (its OWN EWMA baseline
                # normalizes to the slow level, so z alone would go
                # quiet); the robust z catches a replica that just
                # CHANGED relative to its history
                diverged = (ratio >= self.straggler_factor
                            or (z is not None and z > self.straggler_z))
                if diverged:
                    self._runs[key] = self._runs.get(key, 0) + 1
                    if self._runs[key] >= self.straggler_patience \
                            and key not in self.stragglers:
                        rec = {"replica": name, "metric": metric,
                               "state": "firing",
                               "ratio": round(ratio, 3),
                               "q": self.straggler_q,
                               "replica_q": round(v, 3),
                               "fleet_q": round(med, 3),
                               "rounds": self._runs[key]}
                        if z is not None:
                            rec["z"] = round(z, 2)
                        self.stragglers[key] = rec
                        self.counters["stragglers"] += 1
                        self._emit("straggler", rec, now)
                        self._flight_dump(
                            f"straggler:{name}:{metric}", rec)
                        for listener in self.straggler_listeners:
                            try:
                                listener({"event": "straggler", **rec})
                            except Exception:
                                pass
                else:
                    self._runs[key] = 0
                    if key in self.stragglers:
                        self.stragglers.pop(key)
                        self._emit("straggler",
                                   {"replica": name, "metric": metric,
                                    "state": "resolved",
                                    "ratio": round(ratio, 3),
                                    "q": self.straggler_q}, now)

    def _emit(self, event: str, rec: dict, now: float) -> None:
        rec = {"event": event, **rec}
        rec.setdefault("wall", round(now, 3))
        self.events.append(rec)
        if self.log_file:
            try:
                with open(self.log_file, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                pass
        if self.emit is not None:
            try:
                self.emit(**rec)
            except Exception:
                pass  # a broken sink must not kill the collector

    def _flight_dump(self, reason: str, trigger) -> None:
        if self.flight is None:
            return
        if self.flight.dump(reason, trigger=trigger) is not None:
            self.counters["flight_dumps"] += 1

    # ---------------------------------------------------------- views

    def _merged(self) -> tuple[MetricSketches, float, int]:
        """(merged sketches, rel_err, skipped): exact bucket union of
        every replica's latest cumulative sketches; mixed-rel_err
        replicas reduce to the largest same-rel_err group (the
        goodput monitor-block convention)."""
        by_err: dict[float, list[Replica]] = {}
        for rep in self.replicas:
            if rep._rel_err is not None and rep._sketches:
                by_err.setdefault(rep._rel_err, []).append(rep)
        if not by_err:
            return MetricSketches(), 0.01, 0
        rel_err, group = max(by_err.items(), key=lambda kv: len(kv[1]))
        merged = MetricSketches(rel_err=rel_err)
        for rep in group:
            merged.merge_dict(rep.serialized_sketches())
        skipped = sum(len(v) for v in by_err.values()) - len(group)
        return merged, rel_err, skipped

    def worst(self, metric: str = "ttft_ms", k: int = EXEMPLAR_K) -> list:
        """The fleet's worst-`metric` exemplars, replica-labelled: the
        one-hop answer to 'WHICH request is burning the SLO, where'."""
        names = self._display_names()
        out = []
        for rep in self.replicas:
            for ex in rep._exemplars.get(metric, []):
                if isinstance(ex, dict) and "value" in ex:
                    out.append({"replica": names[rep.uid],
                                "id": ex.get("id"),
                                metric: ex["value"]})
        out.sort(key=lambda e: -e[metric])
        return out[:k]

    def status(self) -> dict:
        with self._lock:
            return self._status_locked(self.clock())

    def profile_payload(self) -> dict:
        """The fleet flamegraph (round 17): every profiling replica's
        folded stacks merged replica-prefixed (one flamegraph whose
        first level is the replica) — duck-typed onto StatusServer as
        the fleet's /profile.json, same as a single Monitor's."""
        from shallowspeed_tpu.telemetry.profiler import merge_profiles

        with self._lock:
            names = self._display_names()
            snaps = {names[r.uid]: prof for r in self.replicas
                     if (prof := r.profile())}
        if not snaps:
            return {"enabled": False}
        return {"enabled": True, **merge_profiles(snaps)}

    def _status_locked(self, now: float) -> dict:
        names = self._display_names()
        merged, rel_err, skipped = self._merged()
        goodputs = [r._status.get("goodput_so_far")
                    for r in self.replicas
                    if isinstance(r._status.get("goodput_so_far"),
                                  (int, float))]
        avails = [r._status.get("availability") for r in self.replicas
                  if isinstance(r._status.get("availability"),
                                (int, float))]
        out = {
            "wall": round(now, 3),
            "fleet": {
                "replicas": len(self.replicas),
                "alive": sum(1 for r in self.replicas if r.alive),
                "sketches": merged.summary(),
                "rel_err": rel_err,
                "goodput_so_far": (round(sum(goodputs) / len(goodputs),
                                         4) if goodputs else None),
                "availability": (round(sum(avails) / len(avails), 4)
                                 if avails else None),
            },
            "replicas": {names[r.uid]: r.summary()
                         for r in self.replicas},
            "slo": [r.status(now) for r in self.rules],
            "alerts": sorted(self.active_alerts.values(),
                             key=lambda a: a.get("slo", "")),
            "stragglers": sorted(self.stragglers.values(),
                                 key=lambda s: (s["replica"],
                                                s["metric"])),
            "worst_ttft": self.worst("ttft_ms"),
            # the fleet's slowest finished request WITH its latency
            # decomposition (round 16): the worst per-replica
            # slowest_request, replica-labelled — "which request,
            # which replica, which component" in one read
            "slowest_request": self._slowest_request(names),
            "counters": dict(self.counters),
        }
        profiling = self._profiling_locked(names)
        if profiling:
            out["profiling"] = profiling
        numerics = self._numerics_locked(names)
        if numerics:
            out["numerics"] = numerics
        memory = self._memory_locked(names)
        if memory:
            out["memory"] = memory
        if skipped:
            out["fleet"]["skipped_mixed_rel_err"] = skipped
        if self.flight is not None:
            out["flight_dumps"] = list(self.flight.dumps)
        return out

    def _profiling_locked(self, names: dict) -> dict | None:
        """The status-view digest of the fleet's profiling plane:
        per-replica sample counts + the hottest frame, so "where is
        host time going, per replica" is one /status.json read (the
        full merged flamegraph lives on /profile.json)."""
        per = {}
        for rep in self.replicas:
            prof = rep.profile()
            if not prof:
                continue
            folded = prof.get("folded") or {}
            top = max(folded.items(), key=lambda kv: kv[1])[0] \
                if folded else None
            ent = {"samples": int(prof.get("samples") or 0)}
            phases = prof.get("phases") or {}
            if phases:
                ent["top_phase"] = max(phases.items(),
                                       key=lambda kv: kv[1])[0]
            if top is not None:
                # the leaf frame is the "where": the full stack is on
                # /profile.json, the status view wants one token
                ent["top_frame"] = top.rsplit(";", 1)[-1]
            per[names[rep.uid]] = ent
        return {"replicas": per} if per else None

    def _numerics_locked(self, names: dict) -> dict | None:
        """The fleet's numerics digest (round 18): worst shadow-parity
        rel-err and overflow fraction across replicas (named, so the
        bad replica is one read away) plus the roster of replicas the
        guard already dropped to bf16 — "is the fp8 rollout healthy
        fleet-wide" without opening N status pages."""
        per = {}
        for rep in self.replicas:
            num = (rep._status or {}).get("numerics")
            if isinstance(num, dict) and num:
                per[names[rep.uid]] = num
        if not per:
            return None

        def worst(field):
            best = None
            for name, num in per.items():
                v = num.get(field)
                if isinstance(v, (int, float)) \
                        and not isinstance(v, bool) \
                        and (best is None or v > best[1]):
                    best = (name, v)
            return ({"replica": best[0], "value": best[1]}
                    if best else None)

        return {
            "replicas": per,
            "worst_parity_loss_rel": worst("num_parity_loss_rel"),
            "worst_overflow": worst("num_overflow_max"),
            "fell_back_bf16": sorted(
                name for name, num in per.items()
                if num.get("num_precision") == "bf16"),
        }

    def _memory_locked(self, names: dict) -> dict | None:
        """The fleet's memory digest (round 20): worst — MINIMUM —
        admission headroom across replicas (named, so the near-OOM
        replica is one read away; the router's placement scoring reads
        the same per-replica serving views), per-replica memory
        observatory snapshots, and the roster of replicas that have
        already recovered a block-exhaustion event."""
        per = {}
        heads = {}
        for rep in self.replicas:
            st = rep._status or {}
            mem = st.get("memory")
            if isinstance(mem, dict) and mem:
                per[names[rep.uid]] = mem
            sv = st.get("serving")
            if isinstance(sv, dict):
                hb = sv.get("headroom_blocks")
                if isinstance(hb, (int, float)) \
                        and not isinstance(hb, bool):
                    heads[names[rep.uid]] = hb
        if not per and not heads:
            return None
        out: dict = {}
        if per:
            out["replicas"] = per
        if heads:
            worst = min(heads.items(), key=lambda kv: kv[1])
            out["headroom_blocks"] = heads
            out["worst_headroom"] = {"replica": worst[0],
                                     "value": worst[1]}
        oomed = sorted(name for name, mem in per.items()
                       if mem.get("last_oom"))
        if oomed:
            out["oom_recovered"] = oomed
        return out

    def _slowest_request(self, names: dict) -> dict | None:
        worst = None
        for rep in self.replicas:
            sr = (rep._status or {}).get("slowest_request")
            if isinstance(sr, dict) \
                    and isinstance(sr.get("e2e_ms"), (int, float)) \
                    and (worst is None
                         or sr["e2e_ms"] > worst["e2e_ms"]):
                worst = {**sr, "replica": names[rep.uid]}
        return worst

    def prometheus(self) -> str:
        """Replica-labelled Prometheus exposition — label values go
        through `prom_escape` (replica names are operator input)."""
        with self._lock:
            names = self._display_names()
            P = "shallowspeed_fleet_"
            lines = [f"# TYPE {P}replicas gauge",
                     f"{P}replicas {len(self.replicas)}",
                     f"# TYPE {P}up gauge"]
            for rep in self.replicas:
                lbl = f'replica="{prom_escape(names[rep.uid])}"'
                lines.append(f"{P}up{{{lbl}}} {1 if rep.alive else 0}")
            per_metric: dict[str, list] = {}
            for rep in self.replicas:
                lbl = prom_escape(names[rep.uid])
                for name, sk in sorted(rep._sketches.items()):
                    if not sk.n:
                        continue
                    per_metric.setdefault(name, []).append((lbl, sk))
            import re as _re

            for name, entries in sorted(per_metric.items()):
                base = "shallowspeed_" + _re.sub(r"[^a-zA-Z0-9_]", "_",
                                                 name)
                lines.append(f"# TYPE {base} summary")
                for lbl, sk in entries:
                    for q in (0.5, 0.95, 0.99):
                        v = sk.quantile(q * 100)
                        lines.append(f'{base}{{replica="{lbl}",'
                                     f'quantile="{q}"}} {v:.6g}')
                    lines.append(f'{base}_sum{{replica="{lbl}"}} '
                                 f'{sk.total:.6g}')
                    lines.append(f'{base}_count{{replica="{lbl}"}} '
                                 f'{sk.n}')
                # native histograms on the SHARED le ladder (round
                # 16): per-replica cumulative buckets sum — the form
                # in which Prometheus fleet quantiles are correct
                for j, (lbl, sk) in enumerate(entries):
                    lines.extend(prom_histogram_lines(
                        base, sk, label=f'replica="{lbl}",',
                        type_line=(j == 0)))
            lines.append(f"# TYPE {P}straggler gauge")
            for _key, rec in sorted(self.stragglers.items()):
                lines.append(
                    f'{P}straggler{{'
                    f'replica="{prom_escape(rec["replica"])}",'
                    f'metric="{prom_escape(rec["metric"])}"}} 1')
            if not self.stragglers:
                lines.append(f"{P}straggler 0")
            lines.append(f"# TYPE {P}alerts_firing gauge")
            lines.append(f"{P}alerts_firing {len(self.active_alerts)}")
            return "\n".join(lines) + "\n"

    # ------------------------------------------------- embedded loop

    def start(self, poll: float = 2.0) -> None:
        """Refresh on a daemon thread every `poll` seconds (the
        embedded mode — GangSupervisor)."""
        if self._thread is not None:
            return
        self._halt.clear()

        def _loop():
            while not self._halt.is_set():
                try:
                    self.refresh()
                except Exception:
                    pass  # a flaky replica must not kill the collector
                self._halt.wait(poll)

        self._thread = threading.Thread(target=_loop,
                                        name="fleet-collector",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        if self._thread is None:
            return
        self._halt.set()
        self._thread.join(timeout=5)
        self._thread = None


# ----------------------------------------------------------- CLI view


def format_fleet_status(status: dict) -> str:
    """Human-readable rendering of one fleet status payload (the
    --fleet terminal view)."""
    fl = status.get("fleet") or {}
    lines = [f"fleet: {fl.get('alive', 0)}/{fl.get('replicas', 0)} "
             f"replicas alive"
             + (f"  goodput {fl['goodput_so_far']:.1%}"
                if fl.get("goodput_so_far") is not None else "")
             + (f"  availability {fl['availability']:.1%}"
                if fl.get("availability") is not None else "")]
    for name, sk in (fl.get("sketches") or {}).items():
        lines.append(
            f"  {name:<12} n={sk['count']:<7} p50 {sk.get('p50')}  "
            f"p95 {sk.get('p95')}  p99 {sk.get('p99')}")
    for name, rep in sorted((status.get("replicas") or {}).items()):
        state = "up" if rep.get("alive") else "DOWN"
        bits = [f"  [{name}] {state}"]
        ls = rep.get("last_step") or {}
        if ls.get("step") is not None:
            bits.append(f"step {ls['step']}")
        for metric in ("step_ms", "ttft_ms"):
            q = (rep.get("quantiles") or {}).get(metric)
            if q:
                bits.append(f"{metric} p50 {q['p50']}")
        num = rep.get("numerics") or {}
        if num.get("num_precision"):
            bits.append(f"precision {num['num_precision']}")
            if num.get("num_parity_loss_rel") is not None:
                bits.append(
                    f"parity {num['num_parity_loss_rel']:.3g}")
            if num.get("last_verdicts"):
                bits.append(
                    f"NUMERICS {','.join(num['last_verdicts'])}")
        if rep.get("error"):
            bits.append(f"error {rep['error']}")
        lines.append("  ".join(bits))
    for s in status.get("slo") or []:
        lines.append(f"  slo {s['slo']:<24} {s['state']:<8} "
                     f"burn fast/slow {s['burn_fast']}/{s['burn_slow']}")
    for a in status.get("alerts") or []:
        lines.append(f"  ALERT {a.get('severity', '?').upper()} "
                     f"{a.get('slo')}")
    for s in status.get("stragglers") or []:
        lines.append(f"  STRAGGLER {s['replica']} {s['metric']} "
                     f"p{s.get('q', STRAGGLER_Q)} {s.get('replica_q')} "
                     f"vs fleet {s.get('fleet_q')} "
                     f"({s.get('ratio')}x)")
    for e in status.get("worst_ttft") or []:
        lines.append(f"  worst ttft: {e['ttft_ms']} ms  "
                     f"request {e.get('id')} @ {e['replica']}")
    num = status.get("numerics")
    if num:
        wp = num.get("worst_parity_loss_rel")
        wo = num.get("worst_overflow")
        bits = [f"numerics: {len(num.get('replicas') or {})} fp8 "
                f"replica(s)"]
        if wp:
            bits.append(f"worst parity {wp['value']:.3g} "
                        f"@ {wp['replica']}")
        if wo:
            bits.append(f"worst overflow {wo['value']:.3g} "
                        f"@ {wo['replica']}")
        lines.append("  " + "  ".join(bits))
        if num.get("fell_back_bf16"):
            lines.append(f"  FELL BACK to bf16: "
                         f"{', '.join(num['fell_back_bf16'])}")
    mem = status.get("memory")
    if mem:
        wh = mem.get("worst_headroom")
        bits = ["memory:"]
        if wh:
            bits.append(f"worst headroom {wh['value']} blocks "
                        f"@ {wh['replica']}")
        if mem.get("oom_recovered"):
            bits.append(f"OOM recovered: "
                        f"{', '.join(mem['oom_recovered'])}")
        if len(bits) > 1:
            lines.append("  " + "  ".join(bits))
    return "\n".join(lines)


def fleet_main(targets, slos: str = "", once: bool = False,
               interval: float = 2.0, port: int | None = None,
               out=print, max_secs=None, log_file=None) -> int:
    """``python -m shallowspeed_tpu.telemetry --fleet t1 t2 ...``:
    aggregate N replicas (http(s):// targets are polled endpoints,
    anything else is a metrics JSONL path) and render the fleet view;
    with --port also serve the fleet /status.json + /metrics. `once`
    renders one refresh and exits (the pre-commit smoke)."""
    urls = [t for t in targets if t.startswith(("http://", "https://"))]
    paths = [t for t in targets if not t.startswith(("http://",
                                                     "https://"))]
    missing = [p for p in paths if not Path(p).exists()]
    if missing and once:
        out(f"--fleet: no such file(s): {', '.join(missing)}")
        return 1
    fc = FleetCollector(urls=urls, paths=paths, slos=slos,
                        log_file=log_file)
    srv = None
    if port is not None:
        from shallowspeed_tpu.telemetry.monitor import StatusServer

        srv = StatusServer(fc, port=port)
        out(f"fleet endpoint: {srv.url('/status.json')} (+ /metrics)")
    t0 = time.time()
    try:
        while True:
            # Ctrl-C most likely lands inside refresh() (an
            # unreachable replica blocks up to its timeout) — the
            # documented clean exit must cover the poll, not just the
            # sleep
            try:
                st = fc.refresh()
                out(f"== fleet @ {time.strftime('%H:%M:%S')} "
                    f"({st['counters']['refreshes']} refresh(es))")
                out(format_fleet_status(st))
                if once or (max_secs is not None
                            and time.time() - t0 >= max_secs):
                    return 0
                time.sleep(interval)
            except KeyboardInterrupt:
                return 0
    finally:
        if srv is not None:
            srv.close()
