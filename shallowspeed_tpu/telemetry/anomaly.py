"""Streaming anomaly detection over training-health series.

Pure host-side stdlib/math — no jax imports, so the detector can run
anywhere (drivers, the elastic supervisor, offline over a metrics
JSONL). The device-side numerics live in `telemetry/health.py`; this
module turns their per-step series into *verdicts*:

- ``nonfinite``   the on-device sentinel fired (NaN/Inf in the grads);
- ``loss_spike``  the loss jumped far outside its recent distribution
                  (robust EWMA z-score — mean AND deviation are
                  exponentially weighted, so one spike does not poison
                  the baseline the way a windowed stddev would);
- ``divergence``  the loss EWMA has risen a sustained fraction above
                  its best level for several consecutive observations
                  (a trajectory that is not coming back);
- ``grad_spike``  same robust z-score over the grad-norm series (the
                  classic precursor — the grad norm spikes a step or
                  two before the loss does);
- ``dead_layer``  a per-group gradient norm has been ~zero for several
                  consecutive observations while the global gradient
                  is alive (a layer group that stopped learning:
                  upstream stop-gradient, zeroed mask, dead ReLU
                  block, or a wiring bug of the kind round 7 found in
                  the pipeline head grads).

`GuardPolicy` maps verdict kinds to actions (``warn`` | ``skip_step``
| ``abort``). The skip itself is enacted on device (the engines gate
the optimizer update on the nonfinite sentinel when built with
``health="guard"`` — `optim.guarded_step`); `abort` is enacted by the
driver (forensic snapshot + labeled exit, the same contract as the
divergence exit train_lm.py already had).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

ACTIONS = ("warn", "skip_step", "fallback_bf16", "abort")


class RobustEWMA:
    """Exponentially weighted mean + mean-absolute-deviation tracker.

    `update(x)` returns the z-score of x against the state BEFORE
    absorbing it (None during warmup or when the deviation is ~0 and
    x equals the mean). The MAD-based scale (x1.4826, the normal
    consistency constant) keeps one outlier from inflating the
    denominator the way a squared deviation would."""

    def __init__(self, alpha: float = 0.05, warmup: int = 8):
        self.alpha = float(alpha)
        self.warmup = int(warmup)
        self.n = 0
        self.mean: float | None = None
        self.dev: float | None = None

    def update(self, x: float) -> float | None:
        x = float(x)
        if not math.isfinite(x):
            return None  # nonfinite has its own verdict; keep the
            #              baseline clean
        z = None
        if self.n >= self.warmup:
            scale = 1.4826 * self.dev + 1e-12
            z = (x - self.mean) / scale
        if self.mean is None:
            self.mean, self.dev = x, 0.0
        else:
            err = abs(x - self.mean)
            self.mean += self.alpha * (x - self.mean)
            self.dev += self.alpha * (err - self.dev)
        self.n += 1
        return z


@dataclass
class Verdict:
    """One detector finding; `action` is attached by the policy."""

    kind: str
    step: int
    detail: str
    severity: str = "warn"
    action: str = "warn"

    def __str__(self) -> str:
        return f"[health] {self.kind} at step {self.step}: {self.detail}"


@dataclass
class GuardPolicy:
    """Verdict kind -> action. The driver maps `--health monitor` to
    all-warn and `--health guard` to the guarded defaults below."""

    nonfinite: str = "warn"
    loss_spike: str = "warn"
    grad_spike: str = "warn"
    divergence: str = "warn"
    dead_layer: str = "warn"
    # numerics-observatory kinds (round 18, telemetry/numerics.py):
    # shadow-parity drift and a collapsed delayed scale. Under guard
    # their action is `fallback_bf16` — the quantized path is the
    # OPTIONAL precision, so the proportionate response is to stop
    # quantizing, not to stop training; the NumericsMonitor escalates
    # a verdict that repeats AFTER the fallback to abort.
    parity_drift: str = "warn"
    scale_collapse: str = "warn"
    # memory-observatory kinds (round 20, telemetry/memory.MemoryWatch):
    # sustained resident-bytes growth and a z-spike step change. Warn
    # in every mode — memory anomalies are diagnosed from the flight
    # dump, not auto-actioned: skipping a step frees nothing, and a
    # bf16 fallback would RAISE residency.
    mem_leak: str = "warn"
    mem_drift: str = "warn"

    def action(self, kind: str) -> str:
        act = getattr(self, kind, "warn")
        assert act in ACTIONS, act
        return act

    @classmethod
    def for_mode(cls, mode: str) -> "GuardPolicy":
        if mode == "guard":
            # the nonfinite skip is compiled into the step; the host
            # policy records it. Divergence still only warns — the
            # heartbeat status (health.HealthMonitor.heartbeat_status)
            # is what escalates a numerically-dead run to the elastic
            # supervisor for a restart from the last good checkpoint.
            return cls(nonfinite="skip_step",
                       parity_drift="fallback_bf16",
                       scale_collapse="fallback_bf16")
        return cls()


class AnomalyDetector:
    """Feeds the loss / grad-norm / per-group series; yields verdicts.

    Thresholds are deliberately conservative defaults: a z of 6 on a
    robust scale is far outside anything a healthy LM loss curve does
    at log-point granularity, and every sustained detector needs
    `patience` consecutive bad observations before it fires."""

    def __init__(self, spike_z: float = 6.0, div_factor: float = 0.2,
                 patience: int = 3, dead_eps: float = 1e-12,
                 alpha: float = 0.05, warmup: int = 8):
        self.spike_z = float(spike_z)
        self.div_factor = float(div_factor)
        self.patience = int(patience)
        self.dead_eps = float(dead_eps)
        self._loss = RobustEWMA(alpha, warmup)
        self._grad = RobustEWMA(alpha, warmup)
        self._best_loss_ewma = math.inf
        self._div_run = 0
        self._dead_runs: dict[str, int] = {}
        self._dead_reported: set[str] = set()

    def observe(self, step: int, loss=None, pack: dict | None = None
                ) -> list[Verdict]:
        out: list[Verdict] = []
        if pack is not None and pack.get("nonfinite", 0) > 0:
            out.append(Verdict(
                "nonfinite", step, severity="error",
                detail=f"{pack['nonfinite']} non-finite gradient "
                       f"entries (grad_norm="
                       f"{pack.get('grad_norm', float('nan'))})"))
        if loss is not None and math.isfinite(float(loss)):
            z = self._loss.update(float(loss))
            if z is not None and z > self.spike_z:
                out.append(Verdict(
                    "loss_spike", step,
                    detail=f"loss {float(loss):.4f} is {z:.1f} robust "
                           f"sigmas above its EWMA "
                           f"{self._loss.mean:.4f}"))
            ewma = self._loss.mean
            self._best_loss_ewma = min(self._best_loss_ewma, ewma)
            if (self._loss.n > self._loss.warmup
                    and ewma > self._best_loss_ewma
                    * (1.0 + self.div_factor)):
                self._div_run += 1
                if self._div_run == self.patience:
                    out.append(Verdict(
                        "divergence", step, severity="error",
                        detail=f"loss EWMA {ewma:.4f} has stayed >"
                               f"{self.div_factor:.0%} above its best "
                               f"{self._best_loss_ewma:.4f} for "
                               f"{self.patience} observations"))
            else:
                self._div_run = 0
        elif loss is not None:
            # a nonfinite LOSS is divergence by definition
            out.append(Verdict(
                "divergence", step, severity="error",
                detail=f"loss is non-finite ({loss})"))
        if pack is not None:
            gn = pack.get("grad_norm")
            if gn is not None and math.isfinite(gn):
                z = self._grad.update(gn)
                if z is not None and z > self.spike_z:
                    out.append(Verdict(
                        "grad_spike", step,
                        detail=f"grad norm {gn:.4g} is {z:.1f} robust "
                               f"sigmas above its EWMA "
                               f"{self._grad.mean:.4g}"))
            out.extend(self._dead_layers(step, pack))
        return out

    def _dead_layers(self, step: int, pack: dict) -> list[Verdict]:
        out = []
        gn = pack.get("grad_norm") or 0.0
        alive = math.isfinite(gn) and gn > self.dead_eps
        for name, g in (pack.get("groups") or {}).items():
            if alive and g <= self.dead_eps * max(1.0, gn):
                run = self._dead_runs.get(name, 0) + 1
                self._dead_runs[name] = run
                if run >= self.patience \
                        and name not in self._dead_reported:
                    self._dead_reported.add(name)
                    out.append(Verdict(
                        "dead_layer", step, severity="error",
                        detail=f"group {name!r} gradient has been ~0 "
                               f"for {run} observations while the "
                               f"global grad norm is {gn:.4g}"))
            else:
                self._dead_runs[name] = 0
                self._dead_reported.discard(name)
        return out
