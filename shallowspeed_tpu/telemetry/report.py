"""RunTelemetry — the driver-facing aggregator.

One object per training run. The engines expose
`telemetry_entrypoints()` (name, jitted fn, ShapeDtypeStruct args —
recorded at their first real step, so the skeletons match what
actually runs); RunTelemetry turns that plus the live process state
into:

- a one-time STATIC report: per-axis collective bytes/calls per step
  (`collectives.py` jaxpr walk) and the static HBM peak prediction
  (`memory.static_peak_bytes`, the analysis memory rule's number);
- per-log-point STEP FIELDS merged into `metrics.StepRates` lines:
  live HBM high-water + the live-vs-static cross-check, implied
  collective GB/s over the closed window, and the recompile counter
  (jit cache sizes beyond the first-step baseline — the class of bug
  the gspmd `pos_emb` placement drift was, PR 1, now visible on every
  step line);
- an end-of-run summary (written into the trace dir next to the spans).

Everything here degrades gracefully: no entrypoints yet -> static
fields appear at the first log point after a step; an engine without
`telemetry_entrypoints` -> step fields reduce to HBM + recompiles.
"""

from __future__ import annotations

import json
import math
from pathlib import Path

import numpy as np

from shallowspeed_tpu.telemetry import collectives, memory

MiB = float(1 << 20)


def percentile(vals, q: float) -> float | None:
    """Nearest-rank percentile (q in [0, 100]) without numpy dtype
    surprises — None on empty input. The ONE quantile definition the
    repo shares: the request-latency summary below, the goodput
    reducer's serving block, the attribution q25 step-time pick, and
    the streaming sketches (`sketch.LogHistogram.quantile`) all use
    this rank rule, so live and offline quantiles can only disagree by
    the sketch's documented rel_err — never by rank convention.

    Rank = floor(q/100 * (n-1) + 0.5): round-HALF-UP. Python's
    round() rounds half to even (banker's), which maps an exact .5
    rank DOWN whenever the lower rank is even — p50 of 18 samples
    would read sample 8, not 9."""
    vals = sorted(float(v) for v in vals)
    if not vals:
        return None
    k = min(len(vals) - 1,
            max(0, math.floor(q / 100.0 * (len(vals) - 1) + 0.5)))
    return vals[k]


def request_summary(recs) -> dict | None:
    """Reduce schema-v6 `"request"` records (dicts with ttft_ms /
    tpot_ms / tokens_* / preempted — the serving engine's completion
    stamps) to the SLO headline: p50/p95 time-to-first-token and
    time-per-output-token, total tokens moved, preemption count.
    Returns None when there are no request records, so training-run
    summaries stay unchanged."""
    recs = [r for r in recs if isinstance(r, dict) and "ttft_ms" in r]
    if not recs:
        return None
    ttft = [r["ttft_ms"] for r in recs
            if isinstance(r.get("ttft_ms"), (int, float))]
    tpot = [r["tpot_ms"] for r in recs
            if isinstance(r.get("tpot_ms"), (int, float))]
    rnd = lambda v: None if v is None else round(v, 3)  # noqa: E731
    return {
        "n_requests": len(recs),
        "ttft_ms_p50": rnd(percentile(ttft, 50)),
        "ttft_ms_p95": rnd(percentile(ttft, 95)),
        "tpot_ms_p50": rnd(percentile(tpot, 50)),
        "tpot_ms_p95": rnd(percentile(tpot, 95)),
        "tokens_in": sum(int(r.get("tokens_in", 0)) for r in recs),
        "tokens_out": sum(int(r.get("tokens_out", 0)) for r in recs),
        "preempted": sum(int(r.get("preempted", 0)) for r in recs),
    }


def request_timeline(source, rid: str | None = None) -> dict:
    """Reconstruct per-request phase timelines from schema-v8
    ``"lifecycle"`` events (`serving/engine.ServingEngine._lifecycle`:
    submit -> queued -> admitted -> prefill chunk k -> decoding ->
    preempted -> requeued -> finished).

    `source` is a metrics-JSONL path or an iterable of parsed records;
    `rid` filters to one request. Returns, per request id:

        {"phases": [{"phase", "wall", "ms_in_prev", ...}, ...],
         "by_phase_ms": {phase: total ms spent IN that phase},
         "complete": started with submit and ended with finished,
         "attempts": cross-engine dispatch attempts merged,
         "e2e_ms": submit -> finished wall span (None if incomplete)}

    Time spent "in" a phase is attributed by the NEXT transition's
    ms_in_prev (or wall delta when absent), so the sum of by_phase_ms
    reconciles with e2e_ms up to stamp rounding — the fleet view's
    worst-ttft exemplar resolves to which PHASE through this.

    One rid's events can span MULTIPLE engine attempts (a failover
    re-dispatch; schema v11 stamps ``attempt``, and a resumed attempt
    opens with ``submit`` carrying the ``resumed`` marker). The
    reduction is keyed on (rid, attempt): each attempt's seq counter
    restarts at 0, so a rid-only sort would interleave two attempts'
    events, and the wall-delta fallback across two PROCESSES' clocks
    would book the cross-attempt gap — arbitrary skew — into a phase.
    Attempts merge in order; no ms is attributed across an attempt
    boundary (the stitched waterfall's rq_failover_gap owns that
    interval, with skew corrected)."""
    if isinstance(source, (str, Path)):
        recs = []
        for line in Path(source).read_text().splitlines():
            if not line.strip():
                continue
            try:
                recs.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    else:
        recs = list(source)
    per: dict[str, dict[int, list]] = {}
    seen_submits: dict[str, int] = {}
    for rec in recs:
        if not isinstance(rec, dict) or rec.get("event") != "lifecycle":
            continue
        r = rec.get("id")
        if not isinstance(r, str) or (rid is not None and r != rid):
            continue
        att = rec.get("attempt")
        if not isinstance(att, int) or isinstance(att, bool):
            # pre-v11 logs: derive the attempt index from the resumed
            # markers — every "submit" after the first opens a new one
            if rec.get("phase") == "submit":
                seen_submits[r] = seen_submits.get(r, -1) + 1
            att = max(0, seen_submits.get(r, 0))
        per.setdefault(r, {}).setdefault(att, []).append(rec)
    out = {}
    for r, attempts in per.items():
        phases = []
        by_phase: dict[str, float] = {}
        # order attempts by index, then walk each attempt's events by
        # its OWN seq counter; the (prev, cur) accounting below never
        # crosses an attempt boundary
        ordered = []
        for att in sorted(attempts):
            events = attempts[att]
            events.sort(key=lambda e: (e.get("seq", 0),
                                       e.get("wall", 0.0)))
            ordered.append(events)
        for events in ordered:
            for prev, cur in zip([None] + events, events):
                entry = {k: cur[k] for k in
                         ("phase", "wall", "ms_in_prev", "prev", "slot",
                          "tick", "chunk", "tokens", "attempt",
                          "resumed", "trace", "blocks") if k in cur}
                phases.append(entry)
                if prev is None:
                    continue
                ms = cur.get("ms_in_prev")
                if not isinstance(ms, (int, float)):
                    w0, w1 = prev.get("wall"), cur.get("wall")
                    ms = ((w1 - w0) * 1e3
                          if isinstance(w0, (int, float))
                          and isinstance(w1, (int, float)) else 0.0)
                name = cur.get("prev", prev.get("phase", "?"))
                by_phase[name] = by_phase.get(name, 0.0) + float(ms)
        complete = bool(phases) and phases[0]["phase"] == "submit" \
            and phases[-1]["phase"] == "finished"
        e2e = None
        if complete and len(ordered) == 1 \
                and isinstance(phases[0].get("wall"), (int, float)) \
                and isinstance(phases[-1].get("wall"), (int, float)):
            # the single-attempt wall span; across attempts the stamps
            # come from different processes' clocks, so the honest e2e
            # is the stitcher's (router-clock) number, not a raw delta
            e2e = round((phases[-1]["wall"] - phases[0]["wall"]) * 1e3,
                        3)
        # v14: prefill the prefix cache skipped, booked EXPLICITLY (a
        # cache-hit request's rq_prefill is honestly fast — the
        # prefill_cached stamps say how many tokens never ran)
        skipped = sum(p.get("tokens", 0) for p in phases
                      if p.get("phase") == "prefill_cached"
                      and isinstance(p.get("tokens"), int))
        out[r] = {"phases": phases,
                  "by_phase_ms": {k: round(v, 3)
                                  for k, v in sorted(by_phase.items())},
                  "complete": complete,
                  "attempts": len(ordered),
                  "skipped_tokens": skipped,
                  "e2e_ms": e2e}
    return out


def request_waterfall(journey: dict) -> dict | None:
    """Reduce one stitched journey (`telemetry/tracing.build_journeys`)
    into the per-request latency waterfall: ``rq_*_ms`` components plus
    matching ``rq_*_frac`` fractions that sum to the measured e2e BY
    CONSTRUCTION — ``rq_unexplained`` is the residual between the
    named segments and the router-measured e2e, so it doubles as the
    stitching-quality alarm (clock misfit or missing streams inflate
    it). None when the journey has no usable e2e."""
    from shallowspeed_tpu.telemetry.tracing import COMPONENTS

    e2e = journey.get("e2e_ms")
    if not isinstance(e2e, (int, float)) or e2e <= 0.0:
        return None
    comps = {name: 0.0 for name in COMPONENTS}
    for seg in journey.get("segments") or ():
        comps[seg["component"]] = (comps.get(seg["component"], 0.0)
                                   + float(seg["ms"]))
    out = {"e2e_ms": round(float(e2e), 3)}
    named = 0.0
    for name in COMPONENTS:
        out[f"{name}_ms"] = round(comps[name], 3)
        out[f"{name}_frac"] = round(comps[name] / e2e, 4)
        named += comps[name]
    out["rq_unexplained_ms"] = round(e2e - named, 3)
    out["rq_unexplained_frac"] = round((e2e - named) / e2e, 4)
    return out


def sds(tree):
    """Shape/dtype skeleton of a pytree (targets.py's `_sds` contract:
    safe to trace, can never alias live buffers)."""
    import jax

    return jax.tree_util.tree_map(
        lambda l: jax.ShapeDtypeStruct(np.shape(l), np.asarray(l).dtype)
        if not hasattr(l, "aval") and not hasattr(l, "dtype")
        else jax.ShapeDtypeStruct(l.shape, l.dtype), tree)


def step_entrypoints(params, opt_state, tok, tgt, step_fn=None,
                     grads_fn=None, update_fn=None, grads=None,
                     eval_fn=None, step_arg: bool = True) -> list:
    """The engines' shared skeleton capture (one call at their first
    TRACED step — the call sites gate on the tracer level, so the
    default `off` path never imports this module): (name, fn, SDS
    args) per compiled entrypoint, step program first. Pass `step_fn`
    for fused-step engines, or `grads_fn`+`update_fn`+`grads` for the
    ZeRO split; `step_arg=False` for the MLP engines whose step fns
    take no step counter."""
    import jax

    tok, tgt = sds(tok), sds(tgt)
    stp = ((jax.ShapeDtypeStruct((), np.uint32),) if step_arg else ())
    if step_fn is not None:
        eps = [{"name": "_step", "fn": step_fn,
                "args": (sds(params), sds(opt_state), tok, tgt, *stp)}]
    else:
        eps = [
            {"name": "_grads", "fn": grads_fn,
             "args": (sds(params), tok, tgt, *stp)},
            {"name": "_update", "fn": update_fn,
             "args": (sds(params), sds(grads), sds(opt_state))},
        ]
    if eval_fn is not None:
        eps.append({"name": "_eval", "fn": eval_fn,
                    "args": (sds(params), tok, tgt)})
    return eps


def record_engine_entrypoints(engine, tok, tgt, grads=None,
                              step_arg: bool = True) -> list:
    """`step_entrypoints` with the engines' conventional attribute
    names resolved in ONE place (fused step: `_step_fn`/`_step`; ZeRO
    split: `_grads_fn`/`_loss_grads_fn` + `_update_fn`; optional
    `_eval_fn`) — every engine's `_record_entrypoints` is a one-line
    call here, so the entrypoint convention cannot drift per engine."""
    step_fn = getattr(engine, "_step_fn", getattr(engine, "_step",
                                                  None))
    grads_fn = update_fn = None
    if step_fn is None:
        grads_fn = (getattr(engine, "_grads_fn", None)
                    or getattr(engine, "_loss_grads_fn", None))
        update_fn = engine._update_fn
    return step_entrypoints(
        engine.params, engine.opt_state, tok, tgt, step_fn=step_fn,
        grads_fn=grads_fn, update_fn=update_fn, grads=grads,
        eval_fn=getattr(engine, "_eval_fn", None), step_arg=step_arg)


def compile_counts(entrypoints) -> dict:
    """name -> live jit-cache size for every entrypoint that exposes
    one (`fn._cache_size`, the same counter analysis' retrace rule
    reads)."""
    out = {}
    for ep in entrypoints:
        size = getattr(ep["fn"], "_cache_size", None)
        if size is not None:
            try:
                out[ep["name"]] = int(size())
            except Exception:
                pass
    return out


class RunTelemetry:
    """Aggregates telemetry for one engine over one training run."""

    def __init__(self, engine, tracer=None, check_tolerance: float = 1.05,
                 dtype: str = "bf16"):
        self.engine = engine
        self.tracer = tracer
        self.tol = check_tolerance
        self.dtype = dtype          # attribution's MXU peak selector
        self._static = None
        self._bubble: dict = {}
        self._span_mark = 0         # tracer seq at the last log point
        self._attrib_scale = None   # frozen self-calibration factor
        self._attrib_cals = 0       # windows the scale was fit on
        # optional goodput.GoodputLedger the driver also stamps —
        # surfaced in run_summary so telemetry.json carries the
        # in-process loss totals next to the waterfall
        self.ledger = None
        # memory observatory (round 20): per-window leak/drift
        # detector over the live-bytes + host-RSS series this
        # telemetry already samples; verdicts ride step lines as
        # mem_verdicts (schema v15)
        self.memwatch = memory.MemoryWatch()
        self._mem_windows = 0

    # -------------------------------------------------------- static

    def _entrypoints(self) -> list:
        fn = getattr(self.engine, "telemetry_entrypoints", None)
        if fn is None:
            return []
        return fn()

    def static_report(self) -> dict | None:
        """Computed once, lazily (needs a step to have run so the
        engines know their batch skeletons). Entrypoints published
        without args (the VM's per-stage executables) count for the
        recompile counter but are skipped here — the VM measures its
        traffic directly (`telemetry_traffic`)."""
        if self._static is not None:
            return self._static
        eps = [ep for ep in self._entrypoints()
               if ep.get("args") is not None]
        if not eps:
            return None
        rep = {}
        for ep in eps:
            try:
                # ONE make_jaxpr per entrypoint, shared by all three
                # accountings — tracing a big pipeline step costs
                # seconds and must not run twice
                import jax

                from shallowspeed_tpu.analysis.walker import peak_bytes

                closed = jax.make_jaxpr(ep["fn"])(*ep["args"])
                traffic = collectives.traffic_of_jaxpr(closed)
                peak = peak_bytes(closed.jaxpr)
            except Exception as e:
                rep[ep["name"]] = {"error": repr(e)[:200]}
                continue
            try:
                # exposure (schema v3) in its own guard: a failure in
                # the newer dataflow walk must not discard the v1/v2
                # traffic/HBM accounting (step_fields tolerates None)
                from shallowspeed_tpu.parallel.overlap import (
                    collective_exposure)

                expo = collective_exposure(closed)
            except Exception:
                expo = None
            try:
                # roofline inputs (schema v4, telemetry/attribution):
                # per-op matmul FLOPs + HBM bytes off the SAME trace
                from shallowspeed_tpu.telemetry.attribution import (
                    roofline_of_jaxpr)

                roof = roofline_of_jaxpr(closed)
            except Exception:
                roof = None
            rep[ep["name"]] = {"collectives": traffic,
                               "static_peak_bytes": peak,
                               "exposure": expo,
                               "roofline": roof}
        self._static = {"entrypoints": rep,
                        "step": eps[0]["name"]}  # first = the step fn
        return self._static

    # ---------------------------------------------------------- steps

    @property
    def bubble(self) -> dict:
        """The bubble fields currently attached to step lines."""
        return dict(self._bubble)

    def set_bubble(self, **fields) -> None:
        """Attach bubble accounting (static fraction and, when a
        calibration or an executed trace produced one, the measured
        fraction) — merged into every subsequent step line."""
        self._bubble.update(fields)

    def step_fields(self, window_secs: float | None = None,
                    steps_in_window: int | None = None) -> dict:
        """The telemetry fields a step line carries."""
        out: dict = {}
        counts = compile_counts(self._entrypoints())
        if counts:
            # an entrypoint's FIRST executable is the expected compile
            # (the analysis retrace rule's n_compiles_expected=1);
            # every executable beyond one is a recompile — the counter
            # the acceptance gate requires to stay 0 after step 1
            out["compiles"] = sum(counts.values())
            out["recompiles"] = sum(max(0, c - 1)
                                    for c in counts.values())
        live = memory.live_hbm_high_water()
        out["hbm_live_mib"] = round(live["max_device_bytes"] / MiB, 2)
        stats = memory.device_memory_stats()
        peaks = [v.get("peak_bytes_in_use") for v in stats.values()
                 if v.get("peak_bytes_in_use")]
        if peaks:
            out["hbm_alloc_peak_mib"] = round(max(peaks) / MiB, 2)
        # schema v15 (memory observatory): decompose the live total by
        # registered owner — the untracked residual is the leak alarm
        # — and feed the leak/drift detector with this window's
        # device + host-RSS samples
        if memory.registered_owners():
            acct = memory.per_owner_accounting()
            out["hbm_owned_mib"] = {
                name: round(b / MiB, 2)
                for name, b in acct["owners"].items()}
            out["hbm_untracked_mib"] = round(
                acct["untracked_bytes"] / MiB, 2)
        rss = memory.host_rss_bytes()
        if rss:
            out["host_rss_mib"] = round(rss / MiB, 2)
        self._mem_windows += 1
        mem_verdicts = self.memwatch.observe(
            self._mem_windows,
            device_bytes=live["max_device_bytes"],
            rss_bytes=rss or None)
        if mem_verdicts:
            out["mem_verdicts"] = [str(v) for v in mem_verdicts]
        static = self.static_report()
        if static is not None:
            step_ep = static["entrypoints"].get(static["step"], {})
            peak = step_ep.get("static_peak_bytes")
            if peak:
                chk = memory.cross_check(live["max_device_bytes"], peak,
                                         self.tol)
                out["hbm_static_mib"] = round(peak / MiB, 2)
                out["hbm_within_bound"] = chk["within_bound"]
            traffic = step_ep.get("collectives")
            if traffic:
                out["coll_bytes_per_step"] = traffic["total_bytes"]
                out["coll_bytes_by_axis"] = {
                    ax: v["bytes"]
                    for ax, v in traffic["per_axis"].items()}
                if window_secs and steps_in_window:
                    gbps = (traffic["total_bytes"] * steps_in_window
                            / window_secs / 1e9)
                    out["coll_gbps"] = round(gbps, 6)
            # schema v3: the step program's dataflow comm exposure
            # (parallel/overlap.collective_exposure) — the fraction of
            # collective bytes with no independent compute to hide
            # under; absent for programs with no jaxpr-level
            # collectives (GSPMD-inserted ones are invisible here)
            expo = step_ep.get("exposure")
            if expo and expo.get("exposed_comm_frac") is not None:
                out["exposed_comm_frac"] = expo["exposed_comm_frac"]
                out["overlap_ratio"] = expo["overlap_ratio"]
                out["overlap"] = bool(getattr(self.engine, "overlap",
                                              None))
        measured = getattr(self.engine, "telemetry_traffic", None)
        if measured is not None:
            out["coll_bytes_measured"] = measured()
        out.update(self._bubble)
        # schema v4: the roofline waterfall — spans level only (the
        # step spans are device-fenced there, so their durations are
        # attributable time; at `steps` they measure dispatch)
        try:
            out.update(self._attribution(window_secs))
        except Exception:
            pass
        return out

    def _attribution(self, window_secs: float | None) -> dict:
        """attrib_* fields for the window just closed: measured fenced
        step time reconciled against the static roofline + exposed
        collective wire time + bubble + the window's host/dispatch gap
        (telemetry/attribution.py)."""
        tr = self.tracer
        if tr is None or tr.level != "spans":
            return {}
        from shallowspeed_tpu.telemetry import attribution as attr

        events = tr.events_since(self._span_mark)
        self._span_mark = tr.event_count
        durs = attr.window_step_spans(events)
        if not durs:
            return {}
        # lower quartile, not median: on a quiet device (TPU) the
        # fenced durations are tight and q25 == the median; on a
        # shared/oversubscribed host the distribution is bimodal
        # (descheduled steps run ~2x slow) and the median flips modes
        # window to window — q25 tracks the repeatable fast mode,
        # which is the quantity whose drift means the PROGRAM got
        # slower (the alarm) rather than the host got busy (noise).
        # Same nearest-rank helper as the request-latency quantiles —
        # step-time and serving percentiles share ONE definition.
        t_step = percentile(durs, 25)
        if t_step <= 0.0:
            return {}
        roof = None
        exposed_bytes = 0
        static = self.static_report()
        if static is not None:
            acc = {"flops_shard": 0, "flops_global": 0,
                   "dot_bytes_shard": 0, "dot_bytes_global": 0,
                   "bytes_shard": 0, "bytes_global": 0}
            have = False
            for name, entry in static["entrypoints"].items():
                if name == "_eval" or "error" in entry:
                    continue  # eval never runs inside a step span
                r = entry.get("roofline")
                if r:
                    have = True
                    for k in acc:
                        acc[k] += r.get(k, 0)
                traffic = entry.get("collectives")
                if traffic:
                    expo = entry.get("exposure") or {}
                    frac = expo.get("exposed_comm_frac")
                    frac = 1.0 if frac is None else float(frac)
                    exposed_bytes += int(traffic["total_bytes"] * frac)
            roof = acc if have else None
        if roof is None:
            # no roofline model (the VM publishes its per-stage
            # executables without arg skeletons; it measures traffic
            # and bubble directly) — an all-"unexplained" waterfall
            # would be noise, not signal
            return {}
        host_gap = None
        if window_secs:
            host_gap = max(0.0, window_secs - sum(durs)) / len(durs)
        bubble = self._bubble.get("bubble_measured",
                                  self._bubble.get("bubble_static"))
        mesh = getattr(self.engine, "mesh", None)
        n_dev = int(getattr(getattr(mesh, "devices", None), "size", 1)
                    or 1)
        rates = attr.device_rates(dtype=self.dtype)
        scale = None
        if roof is not None and rates.get("source") == "calibrated":
            # no published peak for this device (CPU test meshes):
            # probe rates only fix the MXU/HBM split — self-scale the
            # compute component so the calibration window balances by
            # construction, then freeze it; later windows' unexplained
            # measures drift from that baseline (the regression-alarm
            # semantics; absolute roofline truth off-TPU would just
            # measure host-load noise). The fit runs on the first TWO
            # windows and freezes on the second: the first log window
            # usually contains the compile-heavy step 0, and a scale
            # fit against compile time would misread every steady
            # window after it.
            if self._attrib_cals < 2:
                secs = attr.roofline_seconds(roof, rates, n_dev)
                other = ((0.0 if bubble is None else float(bubble))
                         + (0.0 if host_gap is None
                            else host_gap / t_step)
                         + exposed_bytes / rates["ici"] / t_step)
                residual = max(0.05, 1.0 - other) * t_step
                self._attrib_scale = residual / max(
                    secs["mxu_s"] + secs["hbm_s"], 1e-12)
                self._attrib_cals += 1
            scale = self._attrib_scale
        return attr.step_waterfall(
            t_step, roofline=roof, coll_bytes=exposed_bytes,
            bubble_fraction=bubble, host_gap=host_gap,
            n_devices=n_dev, dtype=self.dtype, rates=rates,
            compute_scale=scale)

    # -------------------------------------------------------- summary

    def run_summary(self) -> dict:
        """End-of-run record: static report + final live sample +
        bubble + compile counters + the last health pack (written next
        to the trace)."""
        static = self.static_report()
        live = memory.live_hbm_high_water()
        counts = compile_counts(self._entrypoints())
        snap = getattr(self.engine, "health_snapshot", None)
        out = {
            "engine": type(self.engine).__name__,
            "static": static,
            "hbm_live_mib": round(live["max_device_bytes"] / MiB, 2),
            "compile_counts": counts,
            "bubble": self._bubble or None,
            # the engine's last on-device health pack (grad/param
            # norms, update ratio, nonfinite; telemetry/health.py) —
            # None with health='off' or before the first step
            "health": snap() if snap is not None else None,
            # in-process goodput-ledger totals when the driver stamps
            # one (the cross-restart reduction lives in
            # goodput.run_goodput over the metrics JSONL)
            "goodput_ledger": (
                {"seconds": self.ledger.seconds(),
                 "counts": self.ledger.counts()}
                if self.ledger is not None else None),
        }
        if static is not None:
            peak = static["entrypoints"].get(
                static["step"], {}).get("static_peak_bytes")
            if peak:
                out["hbm_check"] = memory.cross_check(
                    live["max_device_bytes"], peak, self.tol)
        # the final per-owner decomposition (memory observatory):
        # telemetry.json carries who held what at the end of the run
        if memory.registered_owners():
            out["hbm_owners"] = memory.per_owner_accounting()
        return out

    def write_summary(self, trace_dir) -> Path:
        path = Path(trace_dir) / "telemetry.json"
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.run_summary(), indent=2,
                                   default=str))
        return path
