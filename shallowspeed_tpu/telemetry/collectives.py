"""Collective traffic accounting — bytes and calls per mesh axis.

Walks the jaxpr of a compiled entrypoint (the SAME traversal
`analysis/walker.py` does for the lint rules) and totals, per mesh
axis, the bytes each collective moves per call of the program plus the
call counts — with loop trip multipliers applied, which the lint walk
does not need: a `psum` inside a `lax.scan` over n_mu microbatches
runs n_mu times per step, and that factor is exactly what a
bytes-per-step number must include.

Byte convention: the LOCAL operand bytes entering the collective
(summed over its array operands), i.e. the per-device payload handed
to the ICI — the number a bandwidth model multiplies by the axis's
algorithm factor. The per-primitive algorithm factors (ring all-gather
moves (n-1)/n * global bytes, etc.) are deliberately NOT applied: the
report states what the program hands the fabric, joined at log points
with measured step time into an implied achieved GB/s.

Trip counts: `scan` multiplies by its `length` param; `while` is
unbounded — counted once and flagged `approximate`; `cond` takes the
max over branches (one branch runs) and flags approximate when
branches differ.
"""

from __future__ import annotations

import jax

from shallowspeed_tpu.analysis.walker import (_as_jaxpr, aval_bytes,
                                              sub_jaxprs)

# collective primitive -> the eqn param naming its mesh axes
# (mirrors analysis.rules._COLLECTIVES, minus axis_index which moves
# no data)
_COLLECTIVES = {
    "psum": "axes", "pmin": "axes", "pmax": "axes",
    "ppermute": "axis_name", "pbroadcast": "axis_name",
    "all_gather": "axis_name", "reduce_scatter": "axis_name",
    "psum_scatter": "axis_name", "all_to_all": "axis_name",
    "pgather": "axes",
}


def _axis_names(axes) -> tuple:
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _operand_bytes(eqn) -> int:
    return sum(aval_bytes(v.aval) for v in eqn.invars
               if not isinstance(v, jax.core.Literal))


def _scan_length(eqn) -> int | None:
    n = eqn.params.get("length")
    return int(n) if n is not None else None


def collective_traffic(fn, *args) -> dict:
    """Per-axis collective traffic of one call of `fn(*args)` (args may
    be ShapeDtypeStructs — nothing executes; tracing only).

    Returns {"per_axis": {axis: {"bytes", "calls"}},
             "per_primitive": {prim: {"bytes", "calls"}},
             "total_bytes", "approximate"}.
    Bytes are per device per program call (see module docstring).
    """
    return traffic_of_jaxpr(jax.make_jaxpr(fn)(*args))


def traffic_of_jaxpr(closed) -> dict:
    """`collective_traffic` on an already-traced ClosedJaxpr — callers
    holding one (report.py shares a single trace between this and the
    memory estimate; tracing a big pipeline step costs seconds)."""
    acc_axis: dict[str, dict] = {}
    acc_prim: dict[str, dict] = {}
    state = {"approx": False}

    def add(table, key, nbytes, trips):
        slot = table.setdefault(key, {"bytes": 0, "calls": 0})
        slot["bytes"] += nbytes * trips
        slot["calls"] += trips

    def walk(jaxpr, trips: int):
        j = _as_jaxpr(jaxpr)
        for eqn in j.eqns:
            name = eqn.primitive.name
            key = _COLLECTIVES.get(name)
            if key is not None:
                nbytes = _operand_bytes(eqn)
                axes = _axis_names(eqn.params.get(key)) or ("?",)
                for ax in axes:
                    add(acc_axis, ax, nbytes, trips)
                add(acc_prim, name, nbytes, trips)
                continue
            subs = sub_jaxprs(eqn)
            if not subs:
                continue
            if name == "scan":
                n = _scan_length(eqn)
                if n is None:
                    state["approx"] = True
                    n = 1
                for s in subs:
                    walk(s, trips * n)
            elif name == "while":
                state["approx"] = True
                for s in subs:
                    walk(s, trips)
            elif name == "cond":
                # one branch runs: keep the heaviest branch's totals
                # (collective-identical branches — the engines' gated
                # pipeline phases — are exact; otherwise approximate)
                snap_ax = {k: dict(v) for k, v in acc_axis.items()}
                snap_pr = {k: dict(v) for k, v in acc_prim.items()}
                best = None
                totals = []
                for s in subs:
                    trial_ax = {k: dict(v) for k, v in snap_ax.items()}
                    trial_pr = {k: dict(v) for k, v in snap_pr.items()}
                    acc_axis.clear(); acc_axis.update(trial_ax)
                    acc_prim.clear(); acc_prim.update(trial_pr)
                    walk(s, trips)
                    tot = sum(v["bytes"] for v in acc_axis.values())
                    totals.append(tot)
                    if best is None or tot > best[0]:
                        best = (tot,
                                {k: dict(v) for k, v in acc_axis.items()},
                                {k: dict(v) for k, v in acc_prim.items()})
                if len(set(totals)) > 1:
                    state["approx"] = True
                acc_axis.clear(); acc_axis.update(best[1])
                acc_prim.clear(); acc_prim.update(best[2])
            else:
                for s in subs:
                    walk(s, trips)

    walk(closed.jaxpr, 1)
    return {
        "per_axis": {k: dict(v) for k, v in sorted(acc_axis.items())},
        "per_primitive": {k: dict(v)
                          for k, v in sorted(acc_prim.items())},
        # per-primitive sum: a psum over ('dp','sp') is ONE payload (it
        # appears under both axes in per_axis for attribution)
        "total_bytes": sum(v["bytes"] for v in acc_prim.values()),
        "approximate": state["approx"],
    }
