"""Runtime telemetry — structured, attributable time for every engine.

The reference's observability is bare `print` (SURVEY §5,
`/root/reference/train.py:135-137`); `metrics.py` made runs
machine-comparable but only at end-of-window granularity. This package
makes the *inside* of a step visible without xprof:

- `trace`        low-overhead span API (`tracer().span("fwd", step=s)`)
                 with host wall-clock and, at the `spans` level, device
                 time via `block_until_ready` fences at phase
                 boundaries; exports JSONL and Chrome-trace/Perfetto.
- `bubble`       pipeline bubble accounting: executed schedule traces
                 (or a two-point step-time calibration for the fused
                 engines) replayed against `parallel/verify.py`'s
                 static makespan tables.
- `collectives`  per-mesh-axis traffic (bytes, call counts) derived
                 from the same jaxpr walk `analysis/walker.py` does,
                 joined with measured step time at log points.
- `memory`       live HBM high-water via `jax.live_arrays()` / device
                 memory stats, cross-checked against the static
                 prediction `analysis/rules.py`'s memory rule uses;
                 round 20 adds the memory observatory: an ownership
                 registry (`register_owner`) decomposing resident bytes
                 per owner, host RSS, `forensics()` OOM dumps, and the
                 `MemoryWatch` leak/drift detector.
- `report`       `RunTelemetry`: the driver-facing aggregator that
                 turns all of the above plus retrace/recompile counters
                 into per-step-line fields.
- `health`       on-device training-health pack (grad/param norms,
                 update ratio, nonfinite sentinel) computed INSIDE
                 every engine's compiled step, plus the host-side
                 `HealthMonitor` + guarded-step policy (round 7).
- `anomaly`      streaming detectors over the health series: robust
                 EWMA z-scores (loss/grad spikes), divergence,
                 dead-layer; verdict -> action policy.
- `sketch`       mergeable log-bucketed histogram sketches — streaming
                 p50/p95/p99 in constant memory, serialized as
                 schema-v7 "monitor" events, merged across processes.
- `monitor`      the live telemetry plane (round 12): /status.json +
                 /metrics endpoints (--monitor-port), SLO burn-rate
                 alerts (--slo), anomaly flight recorder
                 (--flight-recorder), and the --live JSONL tailer.
- `fleet`        fleet observability (round 13): `FleetCollector`
                 aggregates N replicas (polled endpoints and/or
                 tailed JSONLs) into merged quantiles, fleet SLO burn,
                 per-replica breakdown, straggler detection (schema-v8
                 "straggler" events), and a replica-labelled
                 /status.json + /metrics of its own (--fleet).
- `python -m shallowspeed_tpu.telemetry --validate f.jsonl ...`
                 schema gate for committed `docs_runs/*.jsonl` traces
                 (pre-commit hook); `--live f.jsonl [--once]` renders
                 the live status view of a growing metrics file.

Levels: `off` (no-ops — no fences, no buffers), `steps` (host
timestamps only; the async dispatch pipeline is preserved), `spans`
(device fences at span exits: accurate attributed time, serialized
dispatch — the documented measurement mode).
"""

# trace has no jax/numpy imports at module level; the heavier modules
# (collectives/memory/report pull in jax + analysis.walker) resolve
# lazily so `python -m shallowspeed_tpu.telemetry --validate` — the
# pre-commit hook — stays a millisecond stdlib-only run.
from shallowspeed_tpu.telemetry.trace import (  # noqa: F401
    Tracer, configure, tracer)

_LAZY = {
    "static_bubble": "bubble", "trace_bubble": "bubble",
    "two_point_bubble": "bubble",
    "collective_traffic": "collectives",
    "device_memory_stats": "memory", "live_hbm_high_water": "memory",
    # memory observatory (round 20): per-owner HBM accounting, host
    # RSS, OOM forensics, leak/drift watch
    "register_owner": "memory", "unregister_owner": "memory",
    "clear_owners": "memory", "registered_owners": "memory",
    "per_owner_accounting": "memory", "top_live_arrays": "memory",
    "host_rss_bytes": "memory", "forensics": "memory",
    "MemoryWatch": "memory",
    "RunTelemetry": "report",
    # training health (round 7): on-device numerics pack + host monitor
    "HealthMonitor": "health", "grad_health": "health",
    "update_health": "health", "merge_packs": "health",
    "fetch_pack": "health",
    "AnomalyDetector": "anomaly", "GuardPolicy": "anomaly",
    "RobustEWMA": "anomaly", "Verdict": "anomaly",
    # time attribution + goodput + bench regression gate (round 9)
    "step_waterfall": "attribution", "roofline_of_jaxpr": "attribution",
    "device_rates": "attribution",
    "GoodputLedger": "goodput", "run_goodput": "goodput",
    "check_trajectory": "regress", "load_trajectory": "regress",
    # live telemetry plane (round 12): streaming sketches, /status +
    # /metrics endpoints, SLO burn-rate alerts, flight recorder
    "LogHistogram": "sketch", "MetricSketches": "sketch",
    "Monitor": "monitor", "StatusServer": "monitor",
    "FlightRecorder": "monitor", "SloRule": "monitor",
    "parse_slos": "monitor", "FileTailer": "monitor",
    "PortInUseError": "monitor", "prom_escape": "monitor",
    # fleet observability (round 13): multi-replica collector,
    # straggler detection, fleet endpoints
    "FleetCollector": "fleet", "Replica": "fleet",
    "format_fleet_status": "fleet",
    "request_timeline": "report",
    # distributed request tracing (round 16): trace context, the
    # cross-process stitcher, the per-request latency waterfall
    "new_trace_id": "tracing", "new_span_id": "tracing",
    "stitch": "tracing", "goodput_block": "tracing",
    "PHASE_COMPONENT": "tracing",
    "request_waterfall": "report",
    # continuous profiling plane (round 17): always-on host sampler,
    # span-tagged phase attribution, trigger-armed capture windows,
    # the single jax.profiler entry point, flamegraph reduction
    "SamplingProfiler": "profiler", "ProfilerPlane": "profiler",
    "CaptureWindow": "profiler", "device_trace_ctx": "profiler",
    "profiler_tag": "profiler", "merge_profiles": "profiler",
    "flame_tree": "profiler", "profile_main": "profiler",
}


def __getattr__(name):
    mod = _LAZY.get(name)
    if mod is None:
        raise AttributeError(name)
    import importlib

    return getattr(importlib.import_module(
        f"shallowspeed_tpu.telemetry.{mod}"), name)
