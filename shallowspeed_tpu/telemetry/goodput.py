"""Run-level goodput ledger — where every wall-clock second went.

`metrics.StepRates` answers *how fast were the steps*; this module
answers *how much of the run was steps at all*. The ledger is a stream
of `{"event": "ledger", "kind": ..., "seconds"/"count": ...}` lines in
the SAME metrics JSONL the step lines live in — so it survives process
death, spans supervisor restarts (`elastic.py` stamps restart downtime
into the same file), and a single reducer (`run_goodput`) can replay
the whole history into

    goodput = productive-step-time / wall-clock

with a named loss breakdown: init, checkpoint restore/save, validation
pauses, data-prefetch stalls, guarded skipped steps, compile (derived
from the first window's excess over the steady step rate),
replayed-from-checkpoint steps (derived from step numbers that re-run
after a restart), and restart downtime (measured from the wall gap
between one process's last line and the next's run_start); recompile
counts are itemized alongside.

Two classes of ledger kind:

- **excluded** kinds are pauses `StepRates` removes from its
  throughput windows (val, ckpt_save, restore, init, telemetry,
  calibration). Because every `StepRates.pause(seconds, kind=...)`
  call also stamps the ledger, window-sum + excluded-ledger-seconds ==
  wall clock BY CONSTRUCTION — the step-rate windows and the ledger
  can never disagree (pinned by tests/test_goodput.py).
- **in-window** kinds annotate time that stays inside the windows but
  is not productive (data_stall seconds; skipped_steps counts, priced
  at the steady per-step rate by the reducer; recompiles counts,
  itemized — their wall cost already shows in the step rate).

Schema v5 (round 10): the supervisor's restart stamps carry the
failure class it diagnosed (crash / hang / numeric / corrupt_ckpt),
and the reducer folds them into per-class **MTTR** (mean
detection-to-respawn seconds) plus run **availability**
(1 - downtime/wall); injected chaos faults (`"fault"` events,
shallowspeed_tpu/chaos.py) are tallied alongside so a drill's report
names what was injected next to what it cost.
"""

from __future__ import annotations

import json

# Pause kinds StepRates excludes from its throughput windows. Anything
# else noted with seconds is treated as an in-window loss.
# `shadow_parity` (schema v13) is the numerics observatory's frozen
# master-precision oracle step — diagnostic compute, not training, so
# its seconds are itemized as a named loss rather than counted
# productive.
EXCLUDED_KINDS = ("init", "restore", "val", "ckpt_save", "telemetry",
                  "calibration", "pause", "shadow_parity")


class GoodputLedger:
    """Stamps ledger events into a MetricsLogger (or just accumulates
    in-process totals when `metrics` is None)."""

    def __init__(self, metrics=None):
        self.metrics = metrics
        self._seconds: dict[str, float] = {}
        self._counts: dict[str, int] = {}

    def note(self, kind: str, seconds: float | None = None,
             count: int | None = None, **extra) -> None:
        fields: dict = {}
        if seconds is not None:
            self._seconds[kind] = (self._seconds.get(kind, 0.0)
                                   + float(seconds))
            fields["seconds"] = round(float(seconds), 6)
        if count is not None:
            self._counts[kind] = self._counts.get(kind, 0) + int(count)
            fields["count"] = int(count)
        if self.metrics is not None:
            self.metrics.log(event="ledger", kind=kind, **fields,
                             **extra)

    def seconds(self) -> dict:
        return dict(self._seconds)

    def counts(self) -> dict:
        return dict(self._counts)

    def excluded_seconds(self) -> float:
        return sum(v for k, v in self._seconds.items()
                   if k in EXCLUDED_KINDS)


def stamp_ledger_line(path, kind: str, **fields) -> None:
    """Append one ledger line to a metrics JSONL from OUTSIDE the
    training process (the elastic supervisor's restart stamps). Best
    effort — a supervisor must never die on a full disk."""
    import time

    rec = {"event": "ledger", "kind": kind,
           "wall": round(time.time(), 3), **fields}
    try:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass


# ------------------------------------------------------------ reducer


def _parse(path) -> list[dict]:
    from shallowspeed_tpu.telemetry.schema import parse_metrics_jsonl

    return parse_metrics_jsonl(path)


def _wall(rec, stanza_start_wall) -> float | None:
    if isinstance(rec.get("wall"), (int, float)):
        return float(rec["wall"])
    if stanza_start_wall is not None and isinstance(rec.get("t"),
                                                   (int, float)):
        return stanza_start_wall + float(rec["t"])
    return None


def run_goodput(path, extra_paths=()) -> dict:
    """Reduce one metrics JSONL (one run, possibly spanning supervisor
    restarts) to the goodput report. Returns

        {"wall_clock_s", "productive_s", "goodput", "accounted_frac",
         "losses": {kind: seconds}, "counts": {...}, "per_step_s",
         "stanzas"}

    `accounted_frac` = (productive + sum(losses)) / wall_clock — the
    acceptance bar is >= 0.95 on a kill/resume run; anything below
    that means time went somewhere the ledger has no name for.

    `extra_paths` (schema v11): additional per-process JSONLs — a
    router log's replica files — joined BY TRACE ID into the
    ``tracing`` block (per-request latency waterfalls, p50/p95 per
    component, worst-``rq_unexplained`` exemplars); the wall-clock /
    ledger reduction above stays scoped to the primary file.
    """
    recs = _parse(path)
    # split into stanzas at run_start lines
    stanzas: list[dict] = []
    for rec in recs:
        if rec["event"] == "run_start" or not stanzas:
            stanzas.append({"start": rec if rec["event"] == "run_start"
                            else None, "lines": []})
        stanzas[-1]["lines"].append(rec)
    losses: dict[str, float] = {}
    counts: dict[str, int] = {"restarts": max(0, len(stanzas) - 1),
                              "replayed_steps": 0, "skipped_steps": 0,
                              "recompiles": 0}
    # MTTR per failure class (schema v5): the supervisor's
    # restart_downtime stamps each carry the class it diagnosed and
    # the detection-to-respawn seconds it measured directly — reduce
    # them to count/total/mean per class. Fault-injection stamps are
    # tallied alongside so a chaos drill's report names what was
    # injected next to what it cost.
    mttr: dict[str, dict] = {}
    faults: dict[str, int] = {}
    # schema v6: serving request-completion stamps reduce to the SLO
    # percentiles (p50/p95 ttft and tpot) — a serving run's metrics
    # JSONL answers "how fast were the requests" through the same
    # reducer that answers "where did the wall clock go"
    request_recs = [r for r in recs if r.get("event") == "request"]
    for rec in recs:
        if rec.get("event") == "fault" and isinstance(rec.get("kind"),
                                                      str):
            faults[rec["kind"]] = faults.get(rec["kind"], 0) + 1
        if rec.get("event") != "ledger":
            continue
        if rec.get("kind") in ("restart_downtime", "poison_step_abort",
                               "supervisor_abort") \
                and isinstance(rec.get("fail_class"), str):
            cls = rec["fail_class"]
            m = mttr.setdefault(cls, {"count": 0, "total_s": 0.0})
            if rec.get("kind") == "restart_downtime":
                m["count"] += 1
                if isinstance(rec.get("seconds"), (int, float)):
                    m["total_s"] += float(rec["seconds"])
            else:
                m[rec["kind"]] = m.get(rec["kind"], 0) + 1
    for m in mttr.values():
        m["total_s"] = round(m["total_s"], 3)
        m["mttr_s"] = (round(m["total_s"] / m["count"], 3)
                       if m["count"] else None)

    def add_loss(kind, secs):
        if secs > 0:
            losses[kind] = losses.get(kind, 0.0) + secs

    # pass 1: walls, step lines, ledger events, per-step rate samples
    rate_samples: list[float] = []
    for st in stanzas:
        start = st["start"] or {}
        st["w0"] = _wall(start, None) if st["start"] else None
        walls = [w for w in (_wall(r, st["w0"]) for r in st["lines"])
                 if w is not None]
        st["first_wall"] = walls[0] if walls else None
        # the crash-gap measurement wants the last line the CHILD
        # wrote ("t" is the process-relative stamp only the child's
        # MetricsLogger adds); a supervisor restart stamp appended
        # after the child died must not shrink the measured downtime
        child_walls = [w for r, w in
                       zip(st["lines"],
                           (_wall(r, st["w0"]) for r in st["lines"]))
                       if w is not None and "t" in r]
        st["last_wall"] = (child_walls[-1] if child_walls
                           else walls[-1] if walls else None)
        st["steps"] = [(r["step"], _wall(r, st["w0"]))
                       for r in st["lines"] if r["event"] == "step"]
        st["ledger"] = [(r.get("kind", "?"), r, _wall(r, st["w0"]))
                        for r in st["lines"] if r["event"] == "ledger"]
        st["start_step"] = int(start.get("start_step", 0) or 0)
        # excluded pause seconds between two walls (for window math)
        ex = [(w, float(r.get("seconds", 0.0)))
              for k, r, w in st["ledger"]
              if k in EXCLUDED_KINDS and w is not None]

        def pauses_between(lo, hi, ex=ex):
            return sum(s for w, s in ex if lo < w <= hi)

        st["pauses_between"] = pauses_between
        for (s1, w1), (s2, w2) in zip(st["steps"], st["steps"][1:]):
            if w1 is None or w2 is None or s2 <= s1:
                continue
            rate_samples.append(
                max(0.0, w2 - w1 - pauses_between(w1, w2)) / (s2 - s1))
    per_step = (float(sorted(rate_samples)[len(rate_samples) // 2])
                if rate_samples else None)

    # pass 2: productive time, compile excess, replay, ledger losses
    productive = 0.0
    high_water = -1
    for i, st in enumerate(stanzas):
        for kind, rec, _w in st["ledger"]:
            secs = rec.get("seconds")
            if isinstance(secs, (int, float)):
                # downtime is re-measured from the wall gap below; the
                # supervisor's own stamp is kept as a cross-check total
                if kind != "restart_downtime":
                    add_loss(kind, float(secs))
            cnt = rec.get("count")
            if isinstance(cnt, (int, float)) and kind in counts:
                counts[kind] += int(cnt)
            elif isinstance(cnt, (int, float)):
                counts[kind] = counts.get(kind, 0) + int(cnt)
        if not st["steps"]:
            high_water = max(high_water, st["start_step"] - 1)
            continue
        s_first, w_first = st["steps"][0]
        s_last, w_last = st["steps"][-1]
        r0 = st["start_step"]
        # steady stepping time between the stanza's step lines
        stepping = 0.0
        if w_first is not None and w_last is not None:
            stepping = max(0.0, w_last - w_first
                           - st["pauses_between"](w_first, w_last))
        # the first segment: run_start -> first step line holds init/
        # restore (itemized above), the steps up to s_first, and the
        # compile excess
        steps_first = max(0, s_first - r0 + 1)
        if per_step is not None and st["first_wall"] is not None \
                and w_first is not None:
            seg = max(0.0, w_first - st["first_wall"]
                      - st["pauses_between"](st["first_wall"], w_first))
            expected = steps_first * per_step
            add_loss("compile", max(0.0, seg - expected))
            stepping += min(seg, expected)
        # replayed steps: work re-run below the previous high-water
        if i > 0 and per_step is not None:
            replayed = max(0, min(high_water, s_last) - r0 + 1)
            counts["replayed_steps"] += replayed
            replay_s = min(replayed * per_step, stepping)
            add_loss("replay", replay_s)
            stepping -= replay_s
        high_water = max(high_water, s_last)
        productive += stepping
        # crash gap to the next stanza = restart downtime (measured)
        nxt = stanzas[i + 1] if i + 1 < len(stanzas) else None
        if nxt is not None and st["last_wall"] is not None \
                and nxt["first_wall"] is not None:
            add_loss("restart_downtime",
                     max(0.0, nxt["first_wall"] - st["last_wall"]))
    # in-window annotated losses come out of productive time
    for kind in ("data_stall",):
        productive -= min(productive, losses.get(kind, 0.0))
    if per_step is not None and counts.get("skipped_steps"):
        skip_s = counts["skipped_steps"] * per_step
        add_loss("skipped_steps", min(skip_s, productive))
        productive -= min(skip_s, productive)

    first = next((s["first_wall"] for s in stanzas
                  if s["first_wall"] is not None), None)
    last = next((s["last_wall"] for s in reversed(stanzas)
                 if s["last_wall"] is not None), None)
    wall = (last - first) if first is not None and last is not None \
        else 0.0
    accounted = productive + sum(losses.values())
    downtime = losses.get("restart_downtime", 0.0)
    return {
        "wall_clock_s": round(wall, 3),
        "productive_s": round(productive, 3),
        "goodput": round(productive / wall, 4) if wall > 0 else None,
        # availability = the run was UP (stepping or pausing inside a
        # live process), as opposed to down between a failure and its
        # recovered successor — the SLA-shaped number MTTR feeds
        "availability": (round(1.0 - min(wall, downtime) / wall, 4)
                         if wall > 0 else None),
        "accounted_frac": (round(min(1.0, accounted / wall), 4)
                           if wall > 0 else None),
        "losses": {k: round(v, 3) for k, v in sorted(losses.items())},
        "counts": counts,
        "mttr": mttr,
        "faults": faults,
        "per_step_s": (round(per_step, 6) if per_step is not None
                       else None),
        "stanzas": len(stanzas),
        # None for training runs (no request events) — the serving
        # block appears only when the JSONL carries schema-v6 stamps
        "requests": _request_block(request_recs),
        # None without schema-v7 monitor snapshots — the merged
        # streaming-sketch quantiles, cross-checked against the exact
        # offline percentiles above (same rank rule; they may differ
        # only by the sketch's recorded rel_err)
        "monitor": _monitor_block(stanzas, request_recs),
        # None without schema-v12 profile snapshots — the host
        # sampling profiler's story: where HOST time went, by tagged
        # phase and hottest frames (last snapshot per stanza, merged
        # labelled across restarts like the monitor sketches)
        "profiling": _profiling_block(stanzas),
        # None without schema-v8 lifecycle events — aggregate
        # per-phase request time (where did request latency go:
        # queued vs prefill vs decoding vs preempted)
        "lifecycle": _lifecycle_block(recs),
        # None without schema-v10 routing events — the fleet block: a
        # ROUTER's log reduces to per-replica MTTR, fleet
        # availability, failover/breaker/scale tallies (the router
        # process itself never restarts, so the per-replica
        # restart_downtime stamps — not stanza gaps — carry the
        # fleet's downtime story)
        "fleet": _fleet_block(recs, wall),
        # None without schema-v11 trace-context lifecycle events —
        # the per-request latency waterfalls, skew-corrected and
        # joined by trace id across this file + extra_paths
        # (telemetry/tracing.goodput_block; `recs` forwarded so the
        # primary log is parsed once, not twice)
        "tracing": _tracing_block([path, *extra_paths], recs),
        # None without schema-v13 num_* step fields — the numerics
        # observatory's run story: worst clamp fractions, the scale
        # floor, shadow-parity extremes, verdicts fired, and whether
        # the run ended on the bf16 fallback
        "numerics": _numerics_block(recs),
        # None without schema-v14 prefix-cache fields — the prefix
        # caching story: hit rate across requests, prefill tokens the
        # shared-block mappings skipped, and the last tick's cold-list
        # / index gauges
        "prefix": _prefix_block(recs, request_recs),
        # None without schema-v15 memory fields — the memory
        # observatory's run story: worst headroom the capacity plane
        # saw, recovered OOM events, the final per-owner
        # decomposition, and any leak/drift verdicts fired
        "memory": _memory_block(recs),
    }


def _prefix_block(recs, request_recs) -> dict | None:
    """Reduce schema-v14 prefix-cache fields: per-request
    `prefix_hit_blocks`/`prefill_skipped_tokens` tallies plus the last
    "generate" tick's `prefix_hit_rate`/`cold_blocks`/`prefix_blocks`
    gauges. None when the run never served with the prefix cache on."""
    reqs = [r for r in request_recs
            if isinstance(r.get("prefix_hit_blocks"), int)
            and not isinstance(r.get("prefix_hit_blocks"), bool)]
    gens = [r for r in recs if r.get("event") == "generate"
            and isinstance(r.get("prefix_hit_rate"), (int, float))]
    if not reqs and not gens:
        return None
    hits = sum(1 for r in reqs if r["prefix_hit_blocks"] > 0)
    skipped = sum(int(r.get("prefill_skipped_tokens") or 0)
                  for r in reqs)
    prefilled = sum(int(r.get("tokens_in") or 0) for r in reqs)
    out = {
        "requests_observed": len(reqs),
        "requests_hit": hits,
        "hit_rate": round(hits / len(reqs), 4) if reqs else None,
        "hit_blocks": sum(r["prefix_hit_blocks"] for r in reqs),
        "prefill_skipped_tokens": skipped,
        # what fraction of submitted prompt tokens never re-prefilled
        "skipped_frac": (round(skipped / prefilled, 4)
                         if prefilled > 0 else None),
    }
    if gens:
        last = gens[-1]
        out["cold_blocks"] = (int(last["cold_blocks"])
                              if isinstance(last.get("cold_blocks"), int)
                              else None)
        out["prefix_blocks"] = (int(last["prefix_blocks"])
                                if isinstance(last.get("prefix_blocks"),
                                              int) else None)
    return out


def _memory_block(recs) -> dict | None:
    """Reduce schema-v15 memory fields to the run's memory story:
    the capacity plane's worst (minimum) admission headroom across
    "generate" ticks, the recovered-OOM ledger tally, the last step's
    per-owner decomposition + untracked residual, peak host RSS, and
    every mem_leak/mem_drift verdict fired. None when the run carries
    no memory-observatory fields at all."""
    gens = [r for r in recs if r.get("event") == "generate"
            and isinstance(r.get("headroom_blocks"), int)
            and not isinstance(r.get("headroom_blocks"), bool)]
    ooms = [r for r in recs if r.get("event") == "ledger"
            and r.get("kind") == "oom"]
    steps = [r for r in recs if r.get("event") == "step"
             and ("hbm_owned_mib" in r or "host_rss_mib" in r
                  or "mem_verdicts" in r)]
    if not gens and not ooms and not steps:
        return None
    out: dict = {}
    if gens:
        worst = min(gens, key=lambda r: r["headroom_blocks"])
        out["worst_headroom_blocks"] = int(worst["headroom_blocks"])
        last = gens[-1]
        out["final_headroom_blocks"] = int(last["headroom_blocks"])
        if isinstance(last.get("live_blocks"), int):
            out["final_live_blocks"] = last["live_blocks"]
    if ooms:
        out["oom_events"] = len(ooms)
        worst_oom = max(ooms,
                        key=lambda r: int(r.get("requested") or 0))
        out["worst_oom"] = {
            k: worst_oom[k] for k in ("requested", "free", "cold",
                                      "live", "id", "tick")
            if k in worst_oom}
    if steps:
        last = steps[-1]
        if isinstance(last.get("hbm_owned_mib"), dict):
            out["owners_mib"] = last["hbm_owned_mib"]
        if isinstance(last.get("hbm_untracked_mib"), (int, float)):
            out["untracked_mib"] = last["hbm_untracked_mib"]
        rss = [r["host_rss_mib"] for r in steps
               if isinstance(r.get("host_rss_mib"), (int, float))]
        if rss:
            out["peak_host_rss_mib"] = round(max(rss), 2)
        verdicts = [v for r in steps
                    for v in (r.get("mem_verdicts") or [])]
        if verdicts:
            out["verdicts"] = [str(v) for v in verdicts]
    return out or None


def _numerics_block(recs) -> dict | None:
    """Reduce schema-v13 ``num_*`` step fields to the run's numerics
    story. Worst-case reductions on purpose: the question --goodput
    answers here is "did the quantized path ever misbehave", so a
    single bad step must survive the reduction."""
    steps = [r for r in recs if r.get("event") == "step"
             and ("num_scale_min" in r or "num_precision" in r)]
    if not steps:
        return None

    def worst(key, fn=max):
        vals = [r[key] for r in steps
                if isinstance(r.get(key), (int, float))]
        return fn(vals) if vals else None

    verdicts: dict[str, int] = {}
    for r in steps:
        for kind in (r.get("num_verdicts") or ()):
            if isinstance(kind, str):
                verdicts[kind] = verdicts.get(kind, 0) + 1
    shadow = worst("num_shadow_total")
    fellback = any(r.get("num_precision") == "bf16" for r in steps)
    return {
        "steps_observed": len(steps),
        "steps_fp8": sum(1 for r in steps
                         if r.get("num_precision") == "fp8"),
        "overflow_max": worst("num_overflow_max"),
        "underflow_max": worst("num_underflow_max"),
        "scale_min": worst("num_scale_min", min),
        "amax_max": worst("num_amax_max"),
        "parity_loss_rel_max": worst("num_parity_loss_rel"),
        "parity_grad_relmax_max": worst("num_parity_grad_relmax"),
        "shadow_samples": int(shadow) if shadow is not None else 0,
        "verdicts": verdicts,
        "final_precision": (str(steps[-1]["num_precision"])
                            if isinstance(steps[-1].get("num_precision"),
                                          str) else None),
        "fell_back_bf16": fellback,
    }


def _tracing_block(paths, first_recs) -> dict | None:
    from shallowspeed_tpu.telemetry.tracing import goodput_block

    try:
        return goodput_block(paths, first_recs=first_recs)
    except OSError:
        return None


def _fleet_block(recs, wall: float) -> dict | None:
    """Reduce schema-v10 routing events + replica-stamped ledger lines
    to the fleet report: routes/failovers/scale decisions, breaker
    trips, per-replica MTTR (from the router's replica-labelled
    restart_downtime stamps), per-replica and mean fleet availability.
    A replica never seen down is fully available; the denominator is
    the router log's wall span (the router observed the whole fleet
    for that long)."""
    routes = fails = 0
    scale = {"up": 0, "drain": 0, "down": 0}
    trips = 0
    names: set[str] = set()
    mttr: dict[str, dict] = {}
    for rec in recs:
        ev = rec.get("event")
        if ev == "route":
            routes += 1
            names.add(str(rec.get("replica")))
        elif ev == "failover":
            fails += 1
            names.add(str(rec.get("replica")))
        elif ev == "scale":
            action = str(rec.get("action"))
            scale[action] = scale.get(action, 0) + 1
            if isinstance(rec.get("replica"), str):
                names.add(rec["replica"])
        elif ev == "ledger" and isinstance(rec.get("replica"), str):
            names.add(rec["replica"])
            if rec.get("kind") == "breaker" \
                    and rec.get("state") == "open":
                trips += 1
            if rec.get("kind") == "restart_downtime" \
                    and isinstance(rec.get("seconds"), (int, float)):
                m = mttr.setdefault(rec["replica"],
                                    {"count": 0, "total_s": 0.0})
                m["count"] += 1
                m["total_s"] += float(rec["seconds"])
    if not (routes or fails or any(scale.values())):
        return None
    names.discard("?")
    for m in mttr.values():
        m["total_s"] = round(m["total_s"], 3)
        m["mttr_s"] = round(m["total_s"] / m["count"], 3)
    avail = {}
    for name in sorted(names):
        down = mttr.get(name, {}).get("total_s", 0.0)
        avail[name] = (round(1.0 - min(down, wall) / wall, 4)
                       if wall > 0 else None)
    vals = [a for a in avail.values() if a is not None]
    return {
        "replicas": sorted(names),
        "routes": routes,
        "failovers": fails,
        "breaker_trips": trips,
        "scale": {k: v for k, v in scale.items() if v},
        "mttr": mttr,
        "availability": avail,
        "fleet_availability": (round(sum(vals) / len(vals), 4)
                               if vals else None),
    }


def _request_block(request_recs) -> dict | None:
    from shallowspeed_tpu.telemetry.report import request_summary

    return request_summary(request_recs)


def _lifecycle_block(recs) -> dict | None:
    """Reduce schema-v8 lifecycle events to run-level phase
    accounting: total ms the fleet's requests spent in each phase —
    the 'which phase' half of the exemplar linkage (the fleet view
    names which request/replica; this names where its time went)."""
    if not any(r.get("event") == "lifecycle" for r in recs):
        return None
    from shallowspeed_tpu.telemetry.report import request_timeline

    timelines = request_timeline(recs)
    by_phase: dict[str, float] = {}
    for tl in timelines.values():
        for phase, ms in tl["by_phase_ms"].items():
            by_phase[phase] = by_phase.get(phase, 0.0) + ms
    return {"requests": len(timelines),
            "complete": sum(1 for tl in timelines.values()
                            if tl["complete"]),
            "by_phase_ms": {k: round(v, 3)
                            for k, v in sorted(by_phase.items())}}


def _profiling_block(stanzas) -> dict | None:
    """Reduce schema-v12 ``"profile"`` events to the run's host-time
    story. Snapshots are cumulative, so the last per stanza is that
    process's total (the monitor-block convention); multiple stanzas
    merge replica/stanza-labelled via the profiler's own reducer."""
    last: dict[str, dict] = {}
    for k, st in enumerate(stanzas):
        snaps = [r for r in st["lines"] if r.get("event") == "profile"]
        if not snaps:
            continue
        label = next((r["replica"] for r in st["lines"]
                      if r.get("event") == "run_start"
                      and isinstance(r.get("replica"), str)), f"s{k}")
        if label in last:
            label = f"{label}#{k}"
        last[label] = snaps[-1]
    if not last:
        return None
    from shallowspeed_tpu.telemetry.profiler import (OTHER_KEY,
                                                     merge_profiles)

    if len(last) == 1:
        (snap,) = last.values()
        folded = dict(snap.get("folded") or {})
        if snap.get("other"):
            folded[OTHER_KEY] = (folded.get(OTHER_KEY, 0)
                                 + int(snap["other"]))
        merged = {"samples": int(snap.get("samples") or 0),
                  "step_samples": int(snap.get("step_samples") or 0),
                  "phases": dict(snap.get("phases") or {}),
                  "folded": folded}
    else:
        merged = merge_profiles(last)
        folded = dict(merged["folded"])
    phases = {name: n for name, n
              in sorted((merged.get("phases") or {}).items(),
                        key=lambda kv: -kv[1])}
    top = [{"frame": stack.rsplit(";", 1)[-1], "samples": int(n)}
           for stack, n in sorted(folded.items(),
                                  key=lambda kv: -kv[1])[:3]
           if not stack.endswith(OTHER_KEY)]
    return {"snapshots": len(last),
            "samples": int(merged.get("samples") or 0),
            "step_samples": int(merged.get("step_samples") or 0),
            "phases": phases, "top_frames": top}


def _monitor_block(stanzas, request_recs) -> dict | None:
    """Merge each stanza's LAST schema-v7 ``"monitor"`` snapshot (a
    process's sketches are cumulative, so its last snapshot is its
    total; summing the last per stanza is the whole run) and
    cross-check the merged sketch quantiles against the exact offline
    request percentiles. `within_bound` uses the sketch's own recorded
    rel_err — the live/offline parity contract the acceptance pins."""
    from shallowspeed_tpu.telemetry.report import percentile
    from shallowspeed_tpu.telemetry.sketch import MetricSketches

    last_snaps = []
    for st in stanzas:
        snaps = [r for r in st["lines"] if r.get("event") == "monitor"
                 and isinstance(r.get("sketches"), dict)]
        if snaps:
            last_snaps.append(snaps[-1])
    if not last_snaps:
        return None
    # bucket indices are only comparable on ONE gamma grid
    # (LogHistogram.merge raises on a rel_err mismatch) — snapshots
    # from mixed-precision producers (two builds/configs in one
    # supervised history) reduce to the LARGEST same-rel_err group
    # and the report says how many were left out, instead of the
    # reducer crashing on a schema-valid file
    by_err: dict[float, list] = {}
    for s in last_snaps:
        by_err.setdefault(float(s.get("rel_err", 0.01)), []).append(s)
    rel_err, group = max(by_err.items(), key=lambda kv: len(kv[1]))
    merged = MetricSketches(rel_err=rel_err)
    n_merged = 0
    for snap in group:
        try:
            merged.merge_dict(snap["sketches"])
            n_merged += 1
        except (ValueError, TypeError):
            # a hand-edited snapshot whose per-sketch rel_err
            # disagrees with its own header; skip it, keep reducing
            continue
    if not n_merged:
        return None
    out = {"snapshots": n_merged, "rel_err": rel_err,
           "quantiles": merged.summary()}
    if n_merged < len(last_snaps):
        out["skipped_mixed_rel_err"] = len(last_snaps) - n_merged
    parity = {}
    for name in ("ttft_ms", "tpot_ms"):
        exact_vals = [r[name] for r in request_recs
                      if isinstance(r.get(name), (int, float))]
        sk = merged.sketches.get(name)
        if not exact_vals or sk is None or not sk.n:
            continue
        for q in (50, 95):
            exact = percentile(exact_vals, q)
            live = sk.quantile(q)
            parity[f"{name}_p{q}"] = {
                "sketch": round(live, 3), "exact": round(exact, 3),
                "within_bound": abs(live - exact)
                <= rel_err * abs(exact) + 1e-9,
            }
    if parity:
        out["parity"] = parity
    return out


def format_report(rep: dict) -> str:
    """Human-readable goodput report (the --goodput CLI surface)."""
    lines = [
        f"wall clock     {rep['wall_clock_s']:>10.2f} s",
        f"productive     {rep['productive_s']:>10.2f} s   "
        f"goodput {rep['goodput'] if rep['goodput'] is not None else '—'}",
    ]
    wall = rep["wall_clock_s"] or 1.0
    for kind, secs in sorted(rep["losses"].items(),
                             key=lambda kv: -kv[1]):
        lines.append(f"  - {kind:<18} {secs:>8.2f} s  "
                     f"({secs / wall:6.1%})")
    extra = {k: v for k, v in rep["counts"].items() if v}
    if extra:
        lines.append(f"counts: {extra}")
    for cls, m in sorted(rep.get("mttr", {}).items()):
        aborts = {k: v for k, v in m.items()
                  if k.endswith("_abort") and v}
        lines.append(
            f"mttr[{cls:<12}] {m['count']} recover(ies), mean "
            f"{m['mttr_s'] if m['mttr_s'] is not None else '—'} s"
            + (f"  {aborts}" if aborts else ""))
    if rep.get("faults"):
        lines.append(f"injected faults: {rep['faults']}")
    req = rep.get("requests")
    if req:
        def ms(v):
            return "—" if v is None else f"{v:.1f}"

        lines.append(
            f"requests {req['n_requests']}  "
            f"ttft p50/p95 {ms(req['ttft_ms_p50'])}/"
            f"{ms(req['ttft_ms_p95'])} ms  "
            f"tpot p50/p95 {ms(req['tpot_ms_p50'])}/"
            f"{ms(req['tpot_ms_p95'])} ms  "
            f"tokens {req['tokens_in']}->{req['tokens_out']}  "
            f"preempted {req['preempted']}")
    pfx = rep.get("prefix")
    if pfx:
        hr = pfx.get("hit_rate")
        sf = pfx.get("skipped_frac")
        lines.append(
            f"prefix cache: {pfx['requests_hit']}/"
            f"{pfx['requests_observed']} request(s) hit"
            + (f" ({hr:.0%})" if hr is not None else "")
            + f", {pfx['prefill_skipped_tokens']} prefill "
            f"token(s) skipped"
            + (f" ({sf:.0%} of prompt tokens)" if sf is not None else "")
            + (f", {pfx['cold_blocks']} cold block(s)"
               if pfx.get("cold_blocks") is not None else ""))
    mem = rep.get("memory")
    if mem:
        bits = []
        if mem.get("worst_headroom_blocks") is not None:
            bits.append(f"worst headroom "
                        f"{mem['worst_headroom_blocks']} blocks")
        if mem.get("oom_events"):
            oo = mem.get("worst_oom") or {}
            bits.append(
                f"{mem['oom_events']} recovered OOM(s)"
                + (f" (worst: need {oo['requested']}, "
                   f"{oo.get('free', 0)} free + {oo.get('cold', 0)} "
                   f"cold)" if "requested" in oo else ""))
        if mem.get("untracked_mib") is not None:
            bits.append(f"untracked {mem['untracked_mib']} MiB")
        if mem.get("peak_host_rss_mib") is not None:
            bits.append(f"host rss peak {mem['peak_host_rss_mib']} MiB")
        if bits:
            lines.append("memory: " + "  ".join(bits))
        if mem.get("owners_mib"):
            top = sorted(mem["owners_mib"].items(),
                         key=lambda kv: -kv[1])[:4]
            lines.append("  owners: " + "  ".join(
                f"{k} {v} MiB" for k, v in top))
        if mem.get("verdicts"):
            lines.append(f"  MEMORY verdicts: {mem['verdicts']}")
    lc = rep.get("lifecycle")
    if lc:
        top = sorted(lc["by_phase_ms"].items(),
                     key=lambda kv: -kv[1])[:4]
        lines.append(
            f"lifecycle ({lc['complete']}/{lc['requests']} complete): "
            + "  ".join(f"{k} {v:.0f} ms" for k, v in top))
    fl = rep.get("fleet")
    if fl:
        lines.append(
            f"fleet [{', '.join(fl['replicas'])}]: "
            f"{fl['routes']} route(s), {fl['failovers']} failover(s), "
            f"{fl['breaker_trips']} breaker trip(s)"
            + (f", scale {fl['scale']}" if fl["scale"] else ""))
        for name, m in sorted(fl["mttr"].items()):
            lines.append(
                f"  mttr[{name:<8}] {m['count']} recover(ies), mean "
                f"{m['mttr_s']} s   availability "
                f"{fl['availability'].get(name)}")
        if fl["fleet_availability"] is not None:
            lines.append(
                f"  fleet availability {fl['fleet_availability']:.2%}")
    tr = rep.get("tracing")
    if tr:
        comps = "  ".join(
            f"{name[3:]} {c['p50_ms']:.0f}/{c['p95_ms']:.0f}"
            for name, c in tr["components"].items())
        lines.append(
            f"tracing ({tr['requests']} request(s), e2e p50 "
            f"{tr['e2e_p50_ms']:.0f} ms) p50/p95 ms: {comps}")
        worst = tr["worst_unexplained"][0] \
            if tr["worst_unexplained"] else None
        if worst and abs(worst["rq_unexplained_ms"]) >= 1.0:
            lines.append(
                f"  worst unexplained: request {worst['id']} "
                f"({worst['rq_unexplained_ms']:.1f} ms of "
                f"{worst['e2e_ms']:.1f} ms e2e)")
    mon = rep.get("monitor")
    if mon:
        qs = mon["quantiles"]
        parts = [f"{name} p50/p95 {sk.get('p50')}/{sk.get('p95')}"
                 for name, sk in qs.items()
                 if name in ("step_ms", "ttft_ms", "tpot_ms")]
        lines.append(f"monitor sketches ({mon['snapshots']} snapshot(s)"
                     f", rel_err {mon['rel_err']}): "
                     + "  ".join(parts))
        bad = [k for k, v in mon.get("parity", {}).items()
               if not v["within_bound"]]
        if bad:
            lines.append(f"  WARNING: sketch/offline parity out of "
                         f"bound: {bad}")
    num = rep.get("numerics")
    if num:
        def g(v):
            return "—" if v is None else f"{v:.3g}"

        lines.append(
            f"numerics ({num.get('steps_fp8', num['steps_observed'])} "
            f"fp8 / {num['steps_observed']} observed step(s), "
            f"{num['shadow_samples']} shadow sample(s)): "
            f"overflow max {g(num['overflow_max'])}  "
            f"underflow max {g(num['underflow_max'])}  "
            f"scale min {g(num['scale_min'])}  "
            f"parity loss/grad {g(num['parity_loss_rel_max'])}/"
            f"{g(num['parity_grad_relmax_max'])}")
        if num["verdicts"] or num["fell_back_bf16"]:
            lines.append(
                f"  verdicts: {num['verdicts'] or '{}'}"
                + (f"  FELL BACK to bf16 (final precision "
                   f"{num['final_precision']})"
                   if num["fell_back_bf16"] else ""))
    prof = rep.get("profiling")
    if prof and prof["samples"]:
        tot = prof["samples"]
        parts = [f"{name} {n / tot:.0%}"
                 for name, n in list(prof["phases"].items())[:4]]
        lines.append(
            f"profiling ({tot} host sample(s), {prof['snapshots']} "
            f"snapshot(s)): " + "  ".join(parts))
        if prof["top_frames"]:
            hot = prof["top_frames"][0]
            lines.append(f"  hottest frame: {hot['frame']} "
                         f"({hot['samples'] / tot:.0%})")
    if rep.get("availability") is not None:
        lines.append(f"availability {rep['availability']:.2%}")
    lines.append(f"accounted {rep['accounted_frac'] if rep['accounted_frac'] is not None else '—'}"
                 f" of wall clock over {rep['stanzas']} process(es)")
    return "\n".join(lines)
