"""Training-health pack — on-device numerics monitors for every engine.

PR 2 gave the system eyes on the *hardware* (spans, bubble, HBM,
collectives, recompiles); this module watches the *model*: a NaN'd
gradient, a diverging loss, or a dead layer otherwise surfaces only as
a corrupted loss line many steps later. Production stacks earn the
"healthy optimizer trajectory" assumption the schedule papers make with
in-step numerics monitors and guarded updates — exactly what this
provides.

Device side (`grad_health` / `update_health`): computed INSIDE the
engines' compiled train steps — global and per-group gradient/param L2
norms, the update-to-param ratio, and non-finite counts — returned as
one small extra output pytree, so the health pack adds **zero extra jit
entrypoints and zero recompiles** (the step executable simply grows a
few scalar outputs; pinned by `tests/test_health.py`'s compile-count
tests, the same counter the analysis retrace rule reads).

Reductions are correct on every mesh, through ONE rule that holds on
both jax generations (VMA and pre-VMA shard_map alike, unlike VMA
introspection): the pack is computed on the engine's fully REDUCED
gradients, and each per-leaf statistic is `psum`'d over exactly the
mesh axes that leaf's PartitionSpec *shards* — the one piece of truth
every engine already owns. Concretely:

- dp / sp data axes: the reduced grads are replicated across them, so
  a replicated leaf's local statistic IS the global one (no psum, no
  double count);
- fsdp / zero-2 dp-scattered grads: the leaf's spec carries 'dp', each
  device's shard-local sum-of-squares psums over 'dp' to the exact
  global norm (shards partition the leaf);
- pp (compiled pipelines): block leaves' specs carry 'pp', so the psum
  spans stages and the pack is globally correct in-program — including
  zb and interleaved-vpp stacked layouts, whose permuted block stacks
  still partition the parameter set over 'pp';
- tp / ep: Megatron/expert-sharded leaves' specs carry those axes and
  their shard-sums likewise partition the leaf;
- pp (the interpreted VM): stages are separate executables — each
  stage computes a LOCAL pack and the driver merges them
  (`merge_packs`);
- GSPMD-jit engines (no shard_map): pass no specs — plain `jnp`
  reductions are already global; XLA inserts the collectives.

Host side: `HealthMonitor` aggregates the per-step packs, runs the
streaming anomaly detector (`telemetry/anomaly.py` — robust EWMA
z-scores over the loss and grad-norm series), attaches policy actions
(warn | skip_step | abort) to its verdicts, merges health fields into
every step line (`metrics.StepRates(health=...)`), and feeds a
liveness/health status into the elastic supervisor's heartbeat file so
a numerically-dead run restarts from the last good checkpoint, not
just a hung one (`elastic.write_heartbeat` / `read_heartbeat`).

The skip itself is compiled into the step: `--health guard` gates the
optimizer update on `nonfinite == 0` through
`optim._Optimizer.guarded_step`, leaving params and optimizer state
bit-identical on a skipped step.
"""

from __future__ import annotations

MODES = ("off", "monitor", "guard")


def _group_of(path) -> str:
    """Stable leaf-group name from a tree path's first component: list
    engines (the MLP family's per-layer param lists) group per layer,
    dict engines (the transformer family) per component (tok_emb /
    blocks / head / ...). Coarse on purpose — the groups feed the
    dead-layer detector and per-group norms on step lines, not a full
    per-tensor dump."""
    from jax import tree_util as jtu

    key = path[0]
    if isinstance(key, jtu.SequenceKey):
        return f"layer{key.idx}"
    if isinstance(key, jtu.DictKey):
        return str(key.key)
    if isinstance(key, jtu.GetAttrKey):
        return str(key.name)
    return str(key)


def spec_axes(specs) -> list:
    """Flattened per-leaf tuples of mesh axis names a PartitionSpec
    pytree shards (the axes a leaf's statistic must psum over); pass
    the result as `grad_health`/`update_health`'s axes list."""
    from jax.sharding import PartitionSpec as P

    def axes_of(spec):
        used = []
        for e in spec:
            if e is None:
                continue
            for a in (e if isinstance(e, (tuple, list)) else (e,)):
                if a not in used:
                    used.append(a)
        return tuple(used)

    import jax

    return [axes_of(s) for s in jax.tree_util.tree_leaves(
        specs, is_leaf=lambda x: isinstance(x, P))]


def _reduced_sq(x, axes):
    """Sum of squares of one leaf (f32 accumulation), psum'd over the
    leaf's sharded axes (none outside shard_map / for replicated
    leaves)."""
    import jax
    import jax.numpy as jnp

    sq = jnp.sum(jnp.square(x.astype(jnp.float32)))
    if axes:
        sq = jax.lax.psum(sq, tuple(axes))
    return sq


def grad_health(params, grads, grad_axes=None, param_axes=None) -> dict:
    """The traced health pack: global + per-group gradient norms, the
    param norm, and the non-finite count, as a small pytree of f32/i32
    scalars. Call INSIDE the compiled step, on the engine's fully
    REDUCED grads (post-psum / post-scatter). `grad_axes`/`param_axes`:
    flattened per-leaf sharded-axis tuples (`spec_axes` of the specs
    the values leave the program with); None = all leaves replicated /
    GSPMD-global."""
    import jax
    import jax.numpy as jnp

    flat = jax.tree_util.tree_flatten_with_path(grads)[0]
    gax = grad_axes or [()] * len(flat)
    gsq = jnp.float32(0.0)
    nf = jnp.int32(0)
    groups: dict = {}
    for (path, g), axes in zip(flat, gax):
        sq = _reduced_sq(g, axes)
        n = jnp.sum(~jnp.isfinite(g)).astype(jnp.int32)
        if axes:
            n = jax.lax.psum(n, tuple(axes))
        gsq = gsq + sq
        nf = nf + n
        name = _group_of(path)
        groups[name] = groups.get(name, jnp.float32(0.0)) + sq
    p_leaves = jax.tree_util.tree_leaves(params)
    pax = param_axes or [()] * len(p_leaves)
    psq = jnp.float32(0.0)
    for p, axes in zip(p_leaves, pax):
        psq = psq + _reduced_sq(p, axes)
    return {
        "grad_norm": jnp.sqrt(gsq),
        "param_norm": jnp.sqrt(psq),
        "nonfinite": nf,
        "groups": {k: jnp.sqrt(v) for k, v in groups.items()},
    }


def update_health(pack: dict, params, new_params, param_axes=None,
                  skipped=None) -> dict:
    """Finish the pack after the optimizer update: the update-to-param
    ratio ||new - old|| / ||old|| (0 on a skipped step — the skip is
    visible in the series), plus the `skipped` flag under guard."""
    import jax
    import jax.numpy as jnp

    leaves = jax.tree_util.tree_leaves(params)
    pax = param_axes or [()] * len(leaves)
    dsq = jnp.float32(0.0)
    for old, new, axes in zip(leaves,
                              jax.tree_util.tree_leaves(new_params),
                              pax):
        dsq = dsq + _reduced_sq(
            new.astype(jnp.float32) - old.astype(jnp.float32), axes)
    pack = dict(pack)
    pack["update_ratio"] = jnp.sqrt(dsq) / (pack["param_norm"] + 1e-12)
    if skipped is not None:
        pack["skipped"] = jnp.asarray(skipped).astype(jnp.int32)
    return pack


def param_l2(tree):
    """Global L2 of a pytree (f32 accumulation, no psums) — shared by
    the split-update programs (zero.py, the VM's _opt) so the norm
    convention cannot drift per call site."""
    import jax
    import jax.numpy as jnp

    t = jnp.float32(0.0)
    for l in jax.tree_util.tree_leaves(tree):
        t = t + jnp.sum(jnp.square(l.astype(jnp.float32)))
    return jnp.sqrt(t)


def note_step(engine, pack) -> None:
    """Record one step's pack on `engine`: stores `last_health` and
    lazily updates device-side CUMULATIVE counters (one tiny add per
    step, no host sync) — a transient guarded skip or nonfinite step
    between log points must reach the next snapshot even though
    `last_health` itself is overwritten every step."""
    import jax.numpy as jnp

    cum = getattr(engine, "_health_cum", None)
    nf_step = (pack["nonfinite"] > 0).astype(jnp.int32)
    new = {"nonfinite_steps_total":
           nf_step if cum is None
           else cum["nonfinite_steps_total"] + nf_step}
    if "skipped" in pack:
        prev = 0 if cum is None else cum.get("skipped_total", 0)
        new["skipped_total"] = prev + pack["skipped"]
    engine._health_cum = new
    engine.last_health = pack


def engine_snapshot(engine) -> dict | None:
    """The engines' shared `health_snapshot` body: last pack + the
    cumulative counters, fetched as one host dict."""
    if engine.last_health is None:
        return None
    cum = getattr(engine, "_health_cum", None) or {}
    return fetch_pack({**engine.last_health, **cum})


# --------------------------------------------------------- host side


def fetch_pack(pack) -> dict | None:
    """Device pack -> plain-python dict (one host sync; call at log
    points only, like every other telemetry fetch)."""
    if pack is None:
        return None
    import jax
    import numpy as np

    host = jax.device_get(pack)
    out = {
        "grad_norm": float(host["grad_norm"]),
        "param_norm": float(host["param_norm"]),
        "nonfinite": int(host["nonfinite"]),
        "groups": {k: float(v) for k, v in host["groups"].items()},
    }
    for k in ("update_ratio",):
        if k in host:
            out[k] = float(host[k])
    for k in ("skipped", "skipped_total", "nonfinite_steps_total"):
        if k in host:
            out[k] = int(host[k])
    # fp8 delayed-scaling bookkeeping (fp8.Fp8TrainEngine): per-layer
    # activation absmax, the scale it produced, and (round 18) the
    # clamp fractions at each quantize — the numerics pack the
    # NumericsMonitor reduces host-side
    for k in ("fp8_amax", "fp8_scale", "fp8_overflow", "fp8_underflow"):
        if k in host:
            out[k] = [float(v) for v in np.asarray(host[k]).ravel()]
    return out


def merge_packs(packs: list) -> dict | None:
    """Driver-side merge of per-STAGE host packs (the interpreted VM's
    pp stages are separate executables; zb/vpp pipelines hand the
    driver one pack per logical stage). Norms combine as
    sqrt(sum-of-squares) — stages partition the parameter set — counts
    sum, groups get a stage prefix, and the global update ratio is
    recovered from the per-stage (ratio, param_norm) pairs."""
    packs = [p for p in packs if p]
    if not packs:
        return None
    import math

    gsq = sum(p["grad_norm"] ** 2 for p in packs)
    psq = sum(p["param_norm"] ** 2 for p in packs)
    out = {
        "grad_norm": math.sqrt(gsq),
        "param_norm": math.sqrt(psq),
        "nonfinite": sum(p["nonfinite"] for p in packs),
        "groups": {f"s{i}.{k}": v for i, p in enumerate(packs)
                   for k, v in p["groups"].items()},
    }
    if all("update_ratio" in p for p in packs):
        dsq = sum((p["update_ratio"] * p["param_norm"]) ** 2
                  for p in packs)
        out["update_ratio"] = math.sqrt(dsq) / (math.sqrt(psq) + 1e-12)
    if any("skipped" in p for p in packs):
        # stages skip in lockstep (one global ok); any stage's flag
        out["skipped"] = max(p.get("skipped", 0) for p in packs)
    return out


class HealthMonitor:
    """Host-side aggregator: per-step health packs in, verdicts and
    step-line fields out.

    `observe(step, loss, pack)` runs the anomaly detector and returns
    the (policy-annotated) verdicts for this observation; the driver
    decides what an `abort` action does (the convention is a forensic
    snapshot + labeled SystemExit, like the existing divergence exit).
    `step_fields()` is merged into step lines by
    `metrics.StepRates(health=...)`; `heartbeat_status()` feeds the
    elastic supervisor ("ok" or "dead <reason>" — a dead status makes
    the supervisor kill and restart the run from the last good
    checkpoint instead of waiting for the hang timeout)."""

    def __init__(self, policy=None, dead_after: int = 3, **detector_kw):
        from shallowspeed_tpu.telemetry.anomaly import (AnomalyDetector,
                                                        GuardPolicy)

        self.detector = AnomalyDetector(**detector_kw)
        self.policy = policy or GuardPolicy()
        self.dead_after = dead_after
        self.skipped_total = 0
        self.nonfinite_steps = 0
        self._consec_nonfinite = 0
        self._prev_nf_total = 0
        self.dead_reason: str | None = None
        self._last: dict = {}
        self._verdicts_since_log: list = []

    def observe(self, step: int, loss, pack: dict | None) -> list:
        """One observation (typically per log point — the packs are
        computed every step on device; fetching them is the host sync).
        Returns this observation's verdicts with `action` set."""
        from shallowspeed_tpu.telemetry.anomaly import Verdict

        verdicts = self.detector.observe(step, loss=loss, pack=pack)
        if pack is not None:
            self._last = dict(pack)
            # prefer the engines' device-side CUMULATIVE counters
            # (health.note_step): a transient skip/nonfinite step
            # between log points is counted even though the last pack
            # in the window is clean
            if "skipped_total" in pack:
                self.skipped_total = pack["skipped_total"]
            elif pack.get("skipped"):
                self.skipped_total += 1
            if "nonfinite_steps_total" in pack:
                delta = pack["nonfinite_steps_total"] \
                    - self._prev_nf_total
                self._prev_nf_total = pack["nonfinite_steps_total"]
                self.nonfinite_steps = pack["nonfinite_steps_total"]
                bad_window = delta > 0
                if bad_window and pack.get("nonfinite", 0) == 0:
                    # the event happened mid-window; the detector only
                    # saw the clean last pack — surface it anyway
                    verdicts.append(Verdict(
                        "nonfinite", step, severity="error",
                        detail=f"{delta} step(s) since the last log "
                               f"point had non-finite gradients"))
            else:
                bad_window = pack.get("nonfinite", 0) > 0
                if bad_window:
                    self.nonfinite_steps += 1
            if bad_window:
                self._consec_nonfinite += 1
            else:
                self._consec_nonfinite = 0
        for v in verdicts:
            v.action = self.policy.action(v.kind)
        if self._consec_nonfinite >= self.dead_after:
            self.dead_reason = (f"nonfinite gradients for "
                                f"{self._consec_nonfinite} consecutive "
                                f"observations")
        elif any(v.kind == "divergence" for v in verdicts):
            self.dead_reason = "loss divergence"
        self._verdicts_since_log.extend(verdicts)
        return verdicts

    def step_fields(self) -> dict:
        """Health fields for the next step line (schema.py types them);
        drains the verdict window."""
        out: dict = {}
        p = self._last
        if p:
            out["health_grad_norm"] = round(p.get("grad_norm", 0.0), 6)
            out["health_param_norm"] = round(p.get("param_norm", 0.0), 6)
            if "update_ratio" in p:
                out["health_update_ratio"] = round(p["update_ratio"], 9)
            out["health_nonfinite"] = int(p.get("nonfinite", 0))
        out["health_skipped_total"] = self.skipped_total
        verdicts = self._verdicts_since_log
        self._verdicts_since_log = []
        if verdicts:
            out["health_verdicts"] = [v.kind for v in verdicts]
        return out

    def heartbeat_status(self) -> str:
        return f"dead {self.dead_reason}" if self.dead_reason else "ok"

    def unhealthy(self) -> bool:
        """Whether the run's state, as of the last observed pack, is
        one a checkpoint must NOT capture: non-finite gradients or a
        dead verdict. The drivers gate saves on this — checkpointing a
        poisoned iterate would turn the recovery stack's restore point
        into the very state it needs to recover FROM (found by the
        round-10 chaos NaN-storm drill)."""
        return bool(self.dead_reason) or self._consec_nonfinite > 0
