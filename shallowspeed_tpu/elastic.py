"""Failure detection and elastic recovery — the one subsystem the
reference lacks outright (SURVEY §5: "any rank failure kills the mpirun
job; no retry/respawn/timeout logic anywhere").

TPU-native elasticity is CHECKPOINT-based, not rank-respawn-based: a
single-controller JAX job either runs or it doesn't (there is no
per-rank membership to patch up, unlike MPI), so recovery means
"restart the process and resume from the last good checkpoint". The
pieces:

- **In-loop failure detection** (already in the drivers): divergence
  gets a labeled SystemExit + forensic snapshot (train_lm.py), the
  post-run replica sync-assert catches silent corruption (utils.py),
  and `--heartbeat-file` gives an external liveness signal.
- **`Supervisor`** (this module): runs the training command as a child
  process and restarts it on failure with exponential backoff, up to a
  restart budget. With `--auto-resume` in the child's argv, every
  restart continues from `checkpoint.latest(save_dir)` — the crash
  costs at most `--save-every` steps of work. A restart budget that
  REFILLS after a healthy run-time window (like torchelastic's
  max_restarts semantics) distinguishes a flaky infrastructure blip
  from a deterministic crash loop.
- **Hang detection**: if the child's heartbeat file (touched at every
  log point) goes stale for longer than `hang_timeout`, the child is
  killed and the restart policy takes over — covering wedged device
  queues / deadlocked input pipelines that would never exit on their
  own.

CLI:

    python -m shallowspeed_tpu.elastic --max-restarts 3 \
        --hang-timeout 600 -- \
        python train_lm.py --save-dir ck --auto-resume ...

The `--` separates supervisor flags from the training command. The
supervisor injects `--heartbeat-file` automatically when hang detection
is on and the command does not already carry one.
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field


# --------------------------------------------------- heartbeat status
#
# The heartbeat file is liveness AND health (round 7): its mtime is the
# liveness clock (a stale file means a hung step loop, as before), and
# its CONTENT is the health verdict — "ok", or "dead <reason>" when the
# driver's HealthMonitor (telemetry/health.py) concludes the run is
# numerically dead (sustained non-finite gradients, loss divergence).
# A dead status makes the supervisor kill and restart the run from the
# last good checkpoint IMMEDIATELY — a numerically-dead run beats
# steadily (the loop is not hung), so the hang timeout would never
# fire, and every further step is wasted work. Plain `touch`ed (empty)
# heartbeat files remain valid "ok" beats.


def _argv_log_file(argv: list[str]) -> str | None:
    """The child command's --log-file value, if any — the metrics
    JSONL the supervisor's goodput-ledger stamps land in. Accepts
    both the two-token form and --log-file=PATH."""
    for i, arg in enumerate(argv):
        if arg == "--log-file" and i + 1 < len(argv):
            return argv[i + 1]
        if arg.startswith("--log-file="):
            return arg.split("=", 1)[1]
    return None


def write_heartbeat(path, status: str = "ok") -> None:
    """One beat: refresh the mtime and record the health status."""
    with open(path, "w") as f:
        f.write(status)


def read_heartbeat_status(path) -> str:
    """The file's health status ("ok" for empty/missing/unreadable —
    liveness is the mtime's job, not this one's)."""
    try:
        with open(path) as f:
            status = f.read(256).strip()
    except OSError:
        return "ok"
    return status or "ok"


@dataclass
class RestartPolicy:
    """Budgeted restarts with exponential backoff.

    `max_restarts` failures are tolerated; each backoff doubles from
    `backoff` up to `backoff_max`. A child that stayed up longer than
    `healthy_after` seconds refills the budget and resets the backoff —
    a long-running job that hits one bad preemption a day should never
    exhaust its budget."""

    max_restarts: int = 3
    backoff: float = 5.0
    backoff_max: float = 300.0
    healthy_after: float = 600.0

    _used: int = field(default=0, init=False)
    _next_backoff: float = field(default=0.0, init=False)

    def __post_init__(self):
        self._next_backoff = self.backoff

    def record_run(self, run_seconds: float) -> None:
        if run_seconds >= self.healthy_after:
            self._used = 0
            self._next_backoff = self.backoff

    def next_restart(self) -> float | None:
        """Delay before the next restart, or None when the budget is
        exhausted."""
        if self._used >= self.max_restarts:
            return None
        self._used += 1
        delay = self._next_backoff
        self._next_backoff = min(self._next_backoff * 2, self.backoff_max)
        return delay


class Supervisor:
    """Run `argv` as a child process; restart on failure per `policy`;
    kill-and-restart on heartbeat staleness when `hang_timeout` is set."""

    def __init__(self, argv: list[str], policy: RestartPolicy | None = None,
                 hang_timeout: float | None = None,
                 heartbeat_file: str | None = None,
                 poll_interval: float = 1.0,
                 log=print, ledger_file: str | None = None):
        self.argv = list(argv)
        self.policy = policy or RestartPolicy()
        self.hang_timeout = hang_timeout
        self.poll_interval = poll_interval
        self.log = log
        # goodput ledger (round 9): restart downtime is stamped into
        # the SAME metrics JSONL the child writes, so the goodput
        # reducer sees the whole history in one file. Default: the
        # child's own --log-file when it has one.
        self.ledger_file = ledger_file or _argv_log_file(self.argv)
        self._owned_hb = False  # did WE mkstemp it (then we unlink it)
        if hang_timeout is not None and heartbeat_file is None:
            if "--heartbeat-file" in self.argv:
                heartbeat_file = self.argv[
                    self.argv.index("--heartbeat-file") + 1]
            else:
                fd, heartbeat_file = tempfile.mkstemp(prefix="hb_")
                os.close(fd)
                self.argv += ["--heartbeat-file", heartbeat_file]
                self._owned_hb = True
        self.heartbeat_file = heartbeat_file

    # ------------------------------------------------------------ child

    def _run_once(self) -> tuple[int, float]:
        """One child run. Returns (exit code, run seconds); a hang kill
        reports exit code -9."""
        t0 = time.monotonic()
        if self.heartbeat_file:
            # a fresh child gets a fresh liveness clock AND a fresh
            # health status — a leftover 'dead ...' from the previous
            # child would otherwise be re-read ~1 poll after spawn
            # (long before the restarted child's first log-point beat)
            # and kill every restart until the budget is exhausted
            try:
                write_heartbeat(self.heartbeat_file, "ok")
            except OSError:
                pass
        child = subprocess.Popen(self.argv)
        # staleness floor: if the heartbeat file disappears mid-run
        # (deleted, tmpfs wipe), measure staleness from the last KNOWN
        # beat — child start at worst — instead of silently disabling
        # hang detection for the rest of the child's life (ADVICE r2)
        hb_seen = time.time()
        while True:
            code = child.poll()
            if code is not None:
                return code, time.monotonic() - t0
            if self.heartbeat_file:
                status = read_heartbeat_status(self.heartbeat_file)
                if status.startswith("dead"):
                    # numerically dead, not hung: the loop still beats,
                    # so the hang timeout (if any) would never fire —
                    # restart from the last good checkpoint now. This
                    # check needs only a heartbeat file, NOT a hang
                    # timeout.
                    self.log(f"[elastic] health verdict {status!r} — "
                             f"killing child {child.pid} for a "
                             f"checkpoint restart")
                    child.send_signal(signal.SIGKILL)
                    child.wait()
                    return -9, time.monotonic() - t0
            if self.hang_timeout is not None:
                try:
                    hb_seen = max(hb_seen,
                                  os.path.getmtime(self.heartbeat_file))
                except OSError:
                    pass
                stale = time.time() - hb_seen
                if stale > self.hang_timeout:
                    self.log(f"[elastic] heartbeat stale {stale:.0f}s > "
                             f"{self.hang_timeout}s — killing child "
                             f"{child.pid}")
                    child.send_signal(signal.SIGKILL)
                    child.wait()
                    return -9, time.monotonic() - t0
            time.sleep(self.poll_interval)

    # ------------------------------------------------------------- loop

    def _cleanup_heartbeats(self) -> None:
        """Unlink heartbeat tmpfiles THIS supervisor created (never a
        caller-provided file). Subclasses with different heartbeat
        ownership override this one hook."""
        if self._owned_hb and self.heartbeat_file:
            try:
                os.unlink(self.heartbeat_file)
            except OSError:
                pass

    def run(self) -> int:
        """Supervise until the child exits 0 or the restart budget is
        exhausted; returns the final exit code."""
        try:
            return self._supervise()
        finally:
            self._cleanup_heartbeats()

    def _supervise(self) -> int:
        attempt = 0
        while True:
            attempt += 1
            self.log(f"[elastic] attempt {attempt}: {' '.join(self.argv)}")
            code, secs = self._run_once()
            t_dead = time.monotonic()
            if code == 0:
                self.log(f"[elastic] child finished cleanly after "
                         f"{secs:.0f}s")
                return 0
            self.policy.record_run(secs)
            delay = self.policy.next_restart()
            if delay is None:
                self.log(f"[elastic] child failed (exit {code}) and the "
                         f"restart budget is exhausted; giving up")
                return code if code > 0 else 1
            self.log(f"[elastic] child failed (exit {code}) after "
                     f"{secs:.0f}s; restarting in {delay:.1f}s")
            time.sleep(delay)
            if self.ledger_file:
                # stamp the restart downtime (kill-to-respawn, i.e.
                # backoff + detection latency) into the child's
                # metrics JSONL — goodput.run_goodput itemizes it, and
                # cross-checks it against the wall gap the child
                # stanzas themselves show
                from shallowspeed_tpu.telemetry.goodput import (
                    stamp_ledger_line)

                stamp_ledger_line(
                    self.ledger_file, "restart_downtime",
                    seconds=round(time.monotonic() - t_dead, 3),
                    attempt=attempt, exit_code=code)


class GangSupervisor(Supervisor):
    """Multi-controller (multi-process) supervision — the round-4
    answer to "elastic recovery is single-process-scoped".

    A JAX multi-controller job has no per-rank membership repair: the
    compiled programs bake the topology (every collective assumes all N
    processes), and the coordinator offers no rejoin for a dead peer —
    losing ONE process wedges the rest. TPU-native recovery is
    therefore GANG restart-from-checkpoint: any child exiting nonzero,
    or any child's heartbeat going stale, kills the WHOLE gang, and the
    shared restart budget relaunches all N from `checkpoint.latest`
    (`--auto-resume` in the child command). This is one host's
    supervisor; on a multi-host pod each host runs one GangSupervisor
    over its local processes with the same command and a shared
    coordinator address — a host that loses its gang exits nonzero and
    the pod scheduler (which owns cross-host membership) restarts the
    job, the same layered contract torchelastic uses.

    Env injection per child i: JAX_COORDINATOR_ADDRESS (a fresh local
    port per attempt unless pinned — a dead coordinator's socket may
    linger in TIME_WAIT), JAX_NUM_PROCESSES=N, JAX_PROCESS_ID=i. The
    drivers' `distributed.initialize()` picks these up."""

    def __init__(self, argv: list[str], n_procs: int,
                 policy: RestartPolicy | None = None,
                 hang_timeout: float | None = None,
                 coordinator: str | None = None,
                 poll_interval: float = 1.0, log=print,
                 ledger_file: str | None = None):
        # deliberately NOT calling super().__init__: the heartbeat is
        # per-child here (N files, injected per process)
        self.argv = list(argv)
        self.n = int(n_procs)
        assert self.n >= 1
        self.policy = policy or RestartPolicy()
        self.hang_timeout = hang_timeout
        self.coordinator = coordinator
        self.poll_interval = poll_interval
        self.log = log
        # gang note: a shared --log-file would interleave N processes'
        # stanzas; restart stamps still help process 0's file
        self.ledger_file = ledger_file or _argv_log_file(self.argv)
        self.heartbeat_files = []
        if hang_timeout is not None:
            assert "--heartbeat-file" not in self.argv, (
                "gang mode injects one heartbeat file per process; "
                "drop the explicit --heartbeat-file")
            for i in range(self.n):
                fd, path = tempfile.mkstemp(prefix=f"hb{i}_")
                os.close(fd)
                self.heartbeat_files.append(path)

    def _cleanup_heartbeats(self) -> None:
        # gang mode owns all N injected tmpfiles; a host running
        # repeated gangs must not accumulate them
        for path in self.heartbeat_files:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _free_port(self) -> int:
        import socket

        with socket.socket() as s:
            s.bind(("localhost", 0))
            return s.getsockname()[1]

    def _kill_gang(self, children) -> None:
        for c in children:
            if c.poll() is None:
                c.send_signal(signal.SIGKILL)
        for c in children:
            c.wait()

    def _run_once(self) -> tuple[int, float]:
        t0 = time.monotonic()
        coord = self.coordinator or f"localhost:{self._free_port()}"
        children = []
        # any exception ANYWHERE here (failed spawn, SIGINT in the
        # monitor loop) must not leave members running: they would
        # re-touch their heartbeat files after run()'s cleanup unlinked
        # them, leaking both tmpfiles and orphaned training processes
        try:
            for i in range(self.n):
                argv = list(self.argv)
                if self.heartbeat_files:
                    # fresh clock AND fresh status per attempt (see
                    # Supervisor._run_once: a leftover 'dead' would
                    # kill every restarted gang within one poll)
                    try:
                        write_heartbeat(self.heartbeat_files[i], "ok")
                    except OSError:
                        pass
                    argv += ["--heartbeat-file", self.heartbeat_files[i]]
                env = {**os.environ,
                       "JAX_COORDINATOR_ADDRESS": coord,
                       "JAX_NUM_PROCESSES": str(self.n),
                       "JAX_PROCESS_ID": str(i)}
                children.append(subprocess.Popen(argv, env=env))
            hb_seen = [time.time()] * self.n
            while True:
                codes = [c.poll() for c in children]
                if any(c is not None and c != 0 for c in codes):
                    bad = next(i for i, c in enumerate(codes)
                               if c is not None and c != 0)
                    self.log(f"[elastic] gang member {bad} exited "
                             f"{codes[bad]} — killing the gang")
                    self._kill_gang(children)
                    return codes[bad], time.monotonic() - t0
                if all(c == 0 for c in codes):
                    return 0, time.monotonic() - t0
                if self.hang_timeout is not None:
                    for i, hb in enumerate(self.heartbeat_files):
                        if codes[i] == 0:
                            continue  # finished members stop beating
                        status = read_heartbeat_status(hb)
                        if status.startswith("dead"):
                            self.log(f"[elastic] gang member {i} "
                                     f"health verdict {status!r} — "
                                     f"killing the gang for a "
                                     f"checkpoint restart")
                            self._kill_gang(children)
                            return -9, time.monotonic() - t0
                        try:
                            hb_seen[i] = max(hb_seen[i],
                                             os.path.getmtime(hb))
                        except OSError:
                            pass
                        stale = time.time() - hb_seen[i]
                        if stale > self.hang_timeout:
                            self.log(f"[elastic] gang member {i} "
                                     f"heartbeat stale {stale:.0f}s > "
                                     f"{self.hang_timeout}s — killing "
                                     f"the gang")
                            self._kill_gang(children)
                            return -9, time.monotonic() - t0
                time.sleep(self.poll_interval)
        except BaseException:
            self._kill_gang(children)
            raise


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.elastic",
        description="Restart-on-failure supervisor with checkpoint-based "
                    "recovery (pair with --save-dir/--auto-resume)")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--backoff", type=float, default=5.0)
    ap.add_argument("--backoff-max", type=float, default=300.0)
    ap.add_argument("--healthy-after", type=float, default=600.0,
                    help="a run this long refills the restart budget")
    ap.add_argument("--hang-timeout", type=float, default=None,
                    help="kill the child if its heartbeat file goes "
                         "stale this long (seconds)")
    ap.add_argument("--procs", type=int, default=1,
                    help="gang mode: launch N multi-controller "
                         "processes of the command (JAX_COORDINATOR_"
                         "ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID "
                         "injected); any member failure restarts the "
                         "whole gang from checkpoint")
    ap.add_argument("--coordinator", default=None,
                    help="pin the gang's coordinator address "
                         "(default: a fresh localhost port per attempt)")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- training command")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no training command given (separate it with --)")
    policy = RestartPolicy(
        max_restarts=args.max_restarts, backoff=args.backoff,
        backoff_max=args.backoff_max, healthy_after=args.healthy_after)
    if args.procs > 1:
        sup = GangSupervisor(cmd, args.procs, policy,
                             hang_timeout=args.hang_timeout,
                             coordinator=args.coordinator)
    else:
        sup = Supervisor(cmd, policy, hang_timeout=args.hang_timeout)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
