"""Failure detection and elastic recovery — the one subsystem the
reference lacks outright (SURVEY §5: "any rank failure kills the mpirun
job; no retry/respawn/timeout logic anywhere").

TPU-native elasticity is CHECKPOINT-based, not rank-respawn-based: a
single-controller JAX job either runs or it doesn't (there is no
per-rank membership to patch up, unlike MPI), so recovery means
"restart the process and resume from the last good checkpoint". The
pieces:

- **In-loop failure detection** (already in the drivers): divergence
  gets a labeled SystemExit + forensic snapshot (train_lm.py), the
  post-run replica sync-assert catches silent corruption (utils.py),
  and `--heartbeat-file` gives an external liveness signal.
- **`Supervisor`** (this module): runs the training command as a child
  process and restarts it on failure with exponential backoff, up to a
  restart budget. With `--auto-resume` in the child's argv, every
  restart continues from `checkpoint.latest(save_dir)` — the crash
  costs at most `--save-every` steps of work. A restart budget that
  REFILLS after a healthy run-time window (like torchelastic's
  max_restarts semantics) distinguishes a flaky infrastructure blip
  from a deterministic crash loop.
- **Hang detection**: if the child's heartbeat file (touched at every
  log point) goes stale for longer than `hang_timeout`, the child is
  killed and the restart policy takes over — covering wedged device
  queues / deadlocked input pipelines that would never exit on their
  own.
- **Failure-class supervision** (round 10): every failure is classed
  (crash / hang / numeric / corrupt_ckpt — see `FAIL_CLASSES`), each
  class backs off on its own jittered exponential stream, the SAME
  step failing twice in a row is flagged as a poison step (labeled
  abort + forensic snapshot instead of a budget-burning crash loop),
  kills are SIGTERM-with-grace before SIGKILL so the child can flush
  its ledger tail, and each detection-to-respawn interval is stamped
  into the goodput ledger with its class — `--goodput` reduces those
  stamps to per-class MTTR and run availability. `--chaos` exports a
  deterministic fault plan (`shallowspeed_tpu.chaos`) to the children
  for staging drills of exactly this machinery.

CLI:

    python -m shallowspeed_tpu.elastic --max-restarts 3 \
        --hang-timeout 600 -- \
        python train_lm.py --save-dir ck --auto-resume ...

The `--` separates supervisor flags from the training command. The
supervisor injects `--heartbeat-file` automatically when hang detection
is on and the command does not already carry one.
"""

from __future__ import annotations

import argparse
import json
import os
import random
import signal
import subprocess
import sys
import tempfile
import time
from dataclasses import dataclass, field

# Failure classes the supervisor distinguishes (round 10). Each class
# has its own detection signal, its own backoff stream, and its own
# MTTR bucket in the goodput ledger:
#   crash        child exited nonzero (or died to an outside signal)
#   hang         heartbeat mtime went stale -> we killed it
#   numeric      heartbeat status said "dead <reason>" -> we killed it
#   corrupt_ckpt child exited EXIT_CORRUPT_CKPT (restore found no
#                verified checkpoint under a strict --resume)
FAIL_CLASSES = ("crash", "hang", "numeric", "corrupt_ckpt")

# Exit-code convention between the drivers and the supervisor: a child
# that cannot restore ANY verified checkpoint exits with EX_DATAERR so
# the supervisor can class the failure as checkpoint corruption rather
# than a generic crash.
EXIT_CORRUPT_CKPT = 65


def classify_exit(code: int) -> str | None:
    """Failure class of a child exit code: None for a clean exit,
    "corrupt_ckpt" for the EXIT_CORRUPT_CKPT contract, "crash" for
    everything else (nonzero exits AND outside signals). The one
    exit-code taxonomy shared by the training supervisors here and
    the serving fleet router (serving/router.ReplicaProc)."""
    if code == 0:
        return None
    if code == EXIT_CORRUPT_CKPT:
        return "corrupt_ckpt"
    return "crash"


# --------------------------------------------------- heartbeat status
#
# The heartbeat file is liveness AND health (round 7): its mtime is the
# liveness clock (a stale file means a hung step loop, as before), and
# its CONTENT is the health verdict — "ok", or "dead <reason>" when the
# driver's HealthMonitor (telemetry/health.py) concludes the run is
# numerically dead (sustained non-finite gradients, loss divergence).
# A dead status makes the supervisor kill and restart the run from the
# last good checkpoint IMMEDIATELY — a numerically-dead run beats
# steadily (the loop is not hung), so the hang timeout would never
# fire, and every further step is wasted work. Plain `touch`ed (empty)
# heartbeat files remain valid "ok" beats.


def install_sigterm_exit() -> bool:
    """Driver-side half of the --term-grace contract: convert SIGTERM
    into SystemExit(143) so the training loop's finally blocks run —
    the prefetcher closes, the tracer flushes, and the metrics JSONL
    tail (the goodput ledger the reducer reads) lands on disk — before
    the supervisor's SIGKILL deadline. Returns False outside the main
    thread (signal handlers are main-thread-only), where the default
    terminate semantics stand."""
    def _to_exit(signum, frame):
        raise SystemExit(143)

    try:
        signal.signal(signal.SIGTERM, _to_exit)
        return True
    except ValueError:
        return False


def _argv_log_file(argv: list[str]) -> str | None:
    """The child command's --log-file value, if any — the metrics
    JSONL the supervisor's goodput-ledger stamps land in. Accepts
    both the two-token form and --log-file=PATH."""
    for i, arg in enumerate(argv):
        if arg == "--log-file" and i + 1 < len(argv):
            return argv[i + 1]
        if arg.startswith("--log-file="):
            return arg.split("=", 1)[1]
    return None


def _set_argv_log_file(argv: list[str], path: str) -> list[str]:
    """A copy of `argv` with its --log-file value replaced (both
    forms) — gang mode rewrites the shared file to per-member files
    so N children's stanzas never interleave in one JSONL."""
    out = list(argv)
    for i, arg in enumerate(out):
        if arg == "--log-file" and i + 1 < len(out):
            out[i + 1] = path
            return out
        if arg.startswith("--log-file="):
            out[i] = f"--log-file={path}"
            return out
    return out + ["--log-file", path]


def write_heartbeat(path, status: str = "ok") -> None:
    """One beat: refresh the mtime and record the health status."""
    with open(path, "w") as f:
        f.write(status)


def read_heartbeat_status(path) -> str:
    """The file's health status ("ok" for empty/missing/unreadable —
    liveness is the mtime's job, not this one's)."""
    try:
        with open(path) as f:
            status = f.read(256).strip()
    except OSError:
        return "ok"
    return status or "ok"


@dataclass
class RestartPolicy:
    """Budgeted restarts with per-failure-class jittered exponential
    backoff.

    `max_restarts` failures are tolerated (one shared budget — a run
    dying N ways is still dying); each class's backoff doubles
    independently from `backoff` up to `backoff_max`, so one slow-to-
    detect hang does not inflate the next crash's restart latency.
    `jitter` stretches each delay by up to that fraction, drawn from a
    seeded stream (deterministic for tests, decorrelated across
    supervisors in a fleet — the thundering-herd standard). A child
    that stayed up longer than `healthy_after` seconds refills the
    budget and resets every backoff — a long-running job that hits one
    bad preemption a day should never exhaust its budget."""

    max_restarts: int = 3
    backoff: float = 5.0
    backoff_max: float = 300.0
    healthy_after: float = 600.0
    jitter: float = 0.0
    seed: int = 0

    _used: int = field(default=0, init=False)
    _next_backoff: float = field(default=0.0, init=False)
    _class_backoff: dict = field(default_factory=dict, init=False)
    _rng: random.Random = field(default=None, init=False)

    def __post_init__(self):
        self._next_backoff = self.backoff
        self._rng = random.Random(self.seed)

    def record_run(self, run_seconds: float) -> None:
        if run_seconds >= self.healthy_after:
            self._used = 0
            self._next_backoff = self.backoff
            self._class_backoff.clear()

    def next_restart(self, fail_class: str | None = None
                     ) -> float | None:
        """Delay before the next restart, or None when the budget is
        exhausted. With a `fail_class`, the doubling is tracked per
        class; without one, the legacy shared stream is used."""
        if self._used >= self.max_restarts:
            return None
        self._used += 1
        if fail_class is None:
            delay = self._next_backoff
            self._next_backoff = min(self._next_backoff * 2,
                                     self.backoff_max)
        else:
            delay = self._class_backoff.get(fail_class, self.backoff)
            self._class_backoff[fail_class] = min(delay * 2,
                                                  self.backoff_max)
        if self.jitter:
            delay *= 1.0 + self.jitter * self._rng.random()
        return delay


class Supervisor:
    """Run `argv` as a child process; restart on failure per `policy`;
    kill-and-restart on heartbeat staleness when `hang_timeout` is set."""

    def __init__(self, argv: list[str], policy: RestartPolicy | None = None,
                 hang_timeout: float | None = None,
                 heartbeat_file: str | None = None,
                 poll_interval: float = 1.0,
                 log=print, ledger_file: str | None = None,
                 term_grace: float = 5.0,
                 child_env: dict | None = None,
                 monitor_port: int | None = None, slo: str = ""):
        self.argv = list(argv)
        self.policy = policy or RestartPolicy()
        self.hang_timeout = hang_timeout
        self.poll_interval = poll_interval
        self.log = log
        # live aggregation (round 12): with a monitor port, the
        # supervisor tails the child's metrics JSONL (which spans
        # every restart stanza — including our own restart_downtime
        # stamps) into a telemetry/monitor.Monitor and serves
        # /status.json + /metrics for the WHOLE supervised history,
        # surviving the children that produced it
        self.monitor_port = monitor_port
        self.slo = slo or ""
        # kill path (round 10): SIGTERM with a grace window before
        # SIGKILL, so the child's handler can flush its metrics-JSONL
        # tail (the goodput ledger the reducer reads) — a bare
        # hang-SIGKILL used to truncate it mid-teardown. 0 disables.
        self.term_grace = term_grace
        # extra child environment (the chaos plan's env propagation)
        self.child_env = dict(child_env or {})
        # poison-step detection: the SAME step failing twice in a row
        # is a deterministic crash, not an infrastructure blip —
        # restarting would burn the whole budget replaying into the
        # same wall
        self._poison_step: int | None = None
        self._poison_count = 0
        # goodput ledger (round 9): restart downtime is stamped into
        # the SAME metrics JSONL the child writes, so the goodput
        # reducer sees the whole history in one file. Default: the
        # child's own --log-file when it has one.
        self.ledger_file = ledger_file or _argv_log_file(self.argv)
        self._owned_hb = False  # did WE mkstemp it (then we unlink it)
        if hang_timeout is not None and heartbeat_file is None:
            if "--heartbeat-file" in self.argv:
                heartbeat_file = self.argv[
                    self.argv.index("--heartbeat-file") + 1]
            else:
                fd, heartbeat_file = tempfile.mkstemp(prefix="hb_")
                os.close(fd)
                self.argv += ["--heartbeat-file", heartbeat_file]
                self._owned_hb = True
        self.heartbeat_file = heartbeat_file

    # ------------------------------------------------------------ child

    def _terminate(self, child) -> None:
        """SIGTERM, wait `term_grace` seconds for a voluntary exit (the
        drivers convert SIGTERM to SystemExit so their finally blocks
        flush the ledger tail), then SIGKILL what remains."""
        if child.poll() is not None:
            return
        if self.term_grace and self.term_grace > 0:
            child.send_signal(signal.SIGTERM)
            try:
                child.wait(timeout=self.term_grace)
                return
            except subprocess.TimeoutExpired:
                pass
        child.send_signal(signal.SIGKILL)
        child.wait()

    def _spawn(self, argv):
        self._mark_log()
        env = ({**os.environ, **self.child_env} if self.child_env
               else None)
        return subprocess.Popen(argv, env=env)

    def _run_once(self) -> tuple[int, float, str | None]:
        """One child run. Returns (exit code, run seconds, failure
        class) — class None on a clean exit; a hang/health kill
        reports exit code -9."""
        t0 = time.monotonic()
        if self.heartbeat_file:
            # a fresh child gets a fresh liveness clock AND a fresh
            # health status — a leftover 'dead ...' from the previous
            # child would otherwise be re-read ~1 poll after spawn
            # (long before the restarted child's first log-point beat)
            # and kill every restart until the budget is exhausted
            try:
                write_heartbeat(self.heartbeat_file, "ok")
            except OSError:
                pass
        child = self._spawn(self.argv)
        # staleness floor: if the heartbeat file disappears mid-run
        # (deleted, tmpfs wipe), measure staleness from the last KNOWN
        # beat — child start at worst — instead of silently disabling
        # hang detection for the rest of the child's life (ADVICE r2)
        hb_seen = time.time()
        while True:
            code = child.poll()
            if code is not None:
                return code, time.monotonic() - t0, classify_exit(code)
            if self.heartbeat_file:
                status = read_heartbeat_status(self.heartbeat_file)
                if status.startswith("dead"):
                    # numerically dead, not hung: the loop still beats,
                    # so the hang timeout (if any) would never fire —
                    # restart from the last good checkpoint now. This
                    # check needs only a heartbeat file, NOT a hang
                    # timeout.
                    self.log(f"[elastic] health verdict {status!r} — "
                             f"killing child {child.pid} for a "
                             f"checkpoint restart")
                    self._terminate(child)
                    return -9, time.monotonic() - t0, "numeric"
            if self.hang_timeout is not None:
                try:
                    hb_seen = max(hb_seen,
                                  os.path.getmtime(self.heartbeat_file))
                except OSError:
                    pass
                stale = time.time() - hb_seen
                if stale > self.hang_timeout:
                    self.log(f"[elastic] heartbeat stale {stale:.0f}s > "
                             f"{self.hang_timeout}s — killing child "
                             f"{child.pid}")
                    self._terminate(child)
                    return -9, time.monotonic() - t0, "hang"
            time.sleep(self.poll_interval)

    # ------------------------------------------------------------- loop

    def _cleanup_heartbeats(self) -> None:
        """Unlink heartbeat tmpfiles THIS supervisor created (never a
        caller-provided file). Subclasses with different heartbeat
        ownership override this one hook."""
        if self._owned_hb and self.heartbeat_file:
            try:
                os.unlink(self.heartbeat_file)
            except OSError:
                pass

    def _start_monitor(self):
        """(monitor, server, tailer) for --monitor-port, or Nones.
        The tailer feeds the whole ledger file from byte 0 — a
        supervisor attached mid-run aggregates the stanzas already on
        disk, then follows."""
        if self.monitor_port is None or not self.ledger_file:
            if self.monitor_port is not None:
                self.log("[elastic] --monitor-port needs the child "
                         "command to carry --log-file (the metrics "
                         "JSONL to aggregate); monitoring disabled")
            return None, None, None
        from shallowspeed_tpu.telemetry.monitor import (FileTailer,
                                                        Monitor,
                                                        StatusServer)

        mon = Monitor(slos=self.slo, flight=0, derive_steps=True,
                      snapshot_every=0)
        srv = StatusServer(mon, port=self.monitor_port)
        tailer = FileTailer(self.ledger_file, mon)
        tailer.start()
        self.log(f"[elastic] monitor: {srv.url('/status.json')} "
                 f"(+ /metrics) over {self.ledger_file}")
        return mon, srv, tailer

    def run(self) -> int:
        """Supervise until the child exits 0 or the restart budget is
        exhausted; returns the final exit code."""
        mon, srv, tailer = self._start_monitor()
        try:
            return self._supervise()
        finally:
            if tailer is not None:
                tailer.stop()
            if srv is not None:
                srv.close()
            self._cleanup_heartbeats()

    def _last_logged_step(self) -> int | None:
        """The last step THIS child's metrics JSONL stanza recorded —
        the poison-step detector's evidence. Reads only lines written
        after the child spawned (`_log_mark`, set at spawn): a
        replacement that died during init, before logging anything new,
        must read as 'no step', not as a repeat of its predecessor's
        last step — otherwise a preemption storm looks like a poison
        step and gets a spurious permanent abort."""
        if not self.ledger_file:
            return None
        mark = getattr(self, "_log_mark", 0)
        try:
            with open(self.ledger_file, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - 262144, mark))
                tail = f.read().decode(errors="replace")
        except OSError:
            return None
        step = None
        for line in tail.splitlines():
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and rec.get("event") == "step" \
                    and isinstance(rec.get("step"), int):
                step = rec["step"]
        return step

    def _mark_log(self) -> None:
        """Remember where the metrics file ends as this child spawns —
        the poison detector only credits lines written after this."""
        try:
            self._log_mark = (os.path.getsize(self.ledger_file)
                              if self.ledger_file else 0)
        except OSError:
            self._log_mark = 0

    def _check_poison(self) -> int | None:
        """Track the step each failed child died at; the same step
        twice IN A ROW means the crash is deterministic (a poison
        batch / poisoned state) — replaying it a third time would just
        burn the budget into the same wall. Returns the poison step."""
        step = self._last_logged_step()
        if step is not None and step == self._poison_step:
            self._poison_count += 1
        else:
            self._poison_step, self._poison_count = step, 1
        if step is not None and self._poison_count >= 2:
            return step
        return None

    def _stamp(self, kind: str, **fields) -> None:
        if self.ledger_file:
            from shallowspeed_tpu.telemetry.goodput import (
                stamp_ledger_line)

            stamp_ledger_line(self.ledger_file, kind, **fields)

    def _forensics(self, step: int, fail_class, code) -> str | None:
        """Freeze the evidence of a poison-step abort next to the
        metrics file: what step, what class, what the log tail said —
        the thing an on-call human wants BEFORE the next restart
        overwrites the scene."""
        if not self.ledger_file:
            return None
        path = f"{self.ledger_file}.poison_step_{step}.json"
        tail = ""
        try:
            with open(self.ledger_file, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - 16384))
                tail = f.read().decode(errors="replace")
        except OSError:
            pass
        hb = (read_heartbeat_status(self.heartbeat_file)
              if self.heartbeat_file else None)
        try:
            with open(path, "w") as f:
                json.dump({"poison_step": step,
                           "fail_class": fail_class,
                           "exit_code": code,
                           "argv": self.argv,
                           "heartbeat_status": hb,
                           "metrics_tail": tail.splitlines()[-40:]},
                          f, indent=1)
        except OSError:
            return None
        return path

    def _supervise(self) -> int:
        attempt = 0
        while True:
            attempt += 1
            self.log(f"[elastic] attempt {attempt}: {' '.join(self.argv)}")
            code, secs, fail_class = self._run_once()
            t_dead = time.monotonic()
            if code == 0:
                self.log(f"[elastic] child finished cleanly after "
                         f"{secs:.0f}s")
                return 0
            self.policy.record_run(secs)
            poison = self._check_poison()
            if poison is not None:
                # deterministic failure: label it, freeze forensics,
                # abort — do NOT burn the budget in a crash loop
                snap = self._forensics(poison, fail_class, code)
                self.log(f"[elastic] poison step {poison}: the same "
                         f"step failed twice in a row (class "
                         f"{fail_class}, exit {code}) — aborting"
                         + (f"; forensic snapshot {snap}" if snap
                            else ""))
                self._stamp("poison_step_abort", step=poison,
                            fail_class=fail_class, exit_code=code)
                return code if code > 0 else 1
            delay = self.policy.next_restart(fail_class)
            if delay is None:
                self.log(f"[elastic] child failed (exit {code}, class "
                         f"{fail_class}) and the restart budget is "
                         f"exhausted; giving up")
                self._stamp("supervisor_abort", fail_class=fail_class,
                            exit_code=code)
                return code if code > 0 else 1
            self.log(f"[elastic] child failed (exit {code}, class "
                     f"{fail_class}) after {secs:.0f}s; restarting in "
                     f"{delay:.1f}s")
            time.sleep(delay)
            # stamp the restart downtime (detection-to-respawn: kill
            # latency + backoff) into the child's metrics JSONL —
            # goodput.run_goodput itemizes it, cross-checks it against
            # the wall gap the child stanzas themselves show, and
            # reduces the per-class stamps to MTTR figures
            self._stamp("restart_downtime",
                        seconds=round(time.monotonic() - t_dead, 3),
                        attempt=attempt, exit_code=code,
                        fail_class=fail_class)


class GangSupervisor(Supervisor):
    """Multi-controller (multi-process) supervision — the round-4
    answer to "elastic recovery is single-process-scoped".

    A JAX multi-controller job has no per-rank membership repair: the
    compiled programs bake the topology (every collective assumes all N
    processes), and the coordinator offers no rejoin for a dead peer —
    losing ONE process wedges the rest. TPU-native recovery is
    therefore GANG restart-from-checkpoint: any child exiting nonzero,
    or any child's heartbeat going stale, kills the WHOLE gang, and the
    shared restart budget relaunches all N from `checkpoint.latest`
    (`--auto-resume` in the child command). This is one host's
    supervisor; on a multi-host pod each host runs one GangSupervisor
    over its local processes with the same command and a shared
    coordinator address — a host that loses its gang exits nonzero and
    the pod scheduler (which owns cross-host membership) restarts the
    job, the same layered contract torchelastic uses.

    Env injection per child i: JAX_COORDINATOR_ADDRESS (a fresh local
    port per attempt unless pinned — a dead coordinator's socket may
    linger in TIME_WAIT), JAX_NUM_PROCESSES=N, JAX_PROCESS_ID=i. The
    drivers' `distributed.initialize()` picks these up."""

    def __init__(self, argv: list[str], n_procs: int,
                 policy: RestartPolicy | None = None,
                 hang_timeout: float | None = None,
                 coordinator: str | None = None,
                 poll_interval: float = 1.0, log=print,
                 ledger_file: str | None = None,
                 term_grace: float = 5.0,
                 child_env: dict | None = None,
                 monitor_port: int | None = None, slo: str = ""):
        # deliberately NOT calling super().__init__: the heartbeat is
        # per-child here (N files, injected per process)
        self.argv = list(argv)
        self.n = int(n_procs)
        assert self.n >= 1
        self.policy = policy or RestartPolicy()
        self.hang_timeout = hang_timeout
        self.coordinator = coordinator
        self.poll_interval = poll_interval
        self.log = log
        self.term_grace = term_grace
        self.child_env = dict(child_env or {})
        self.monitor_port = monitor_port
        self.slo = slo or ""
        self.heartbeat_file = None  # per-member files; see below
        self._poison_step = None
        self._poison_count = 0
        # gang monitoring (round 13): with a monitor port and a
        # --log-file on the command, each member gets its OWN metrics
        # file (<base>.r<i> — a shared file would interleave N
        # processes' stanzas into an unreducible JSONL) and ONE
        # telemetry/fleet.FleetCollector grows over all of them:
        # merged quantiles, per-member breakdown, straggler detection
        # across the gang. Supervisor ledger stamps (restart downtime,
        # poison forensics) land in member 0's file, which stays the
        # poison detector's evidence too.
        self.member_log_files: list[str] = []
        base = ledger_file or _argv_log_file(self.argv)
        if monitor_port is not None and base:
            self.member_log_files = [f"{base}.r{i}"
                                     for i in range(self.n)]
            base = self.member_log_files[0]
        self.ledger_file = base
        self.heartbeat_files = []
        if hang_timeout is not None:
            assert "--heartbeat-file" not in self.argv, (
                "gang mode injects one heartbeat file per process; "
                "drop the explicit --heartbeat-file")
            for i in range(self.n):
                fd, path = tempfile.mkstemp(prefix=f"hb{i}_")
                os.close(fd)
                self.heartbeat_files.append(path)

    def _cleanup_heartbeats(self) -> None:
        # gang mode owns all N injected tmpfiles; a host running
        # repeated gangs must not accumulate them
        for path in self.heartbeat_files:
            try:
                os.unlink(path)
            except OSError:
                pass

    def _start_monitor(self):
        """Gang aggregation: one FleetCollector over every member's
        metrics file, served on --monitor-port as the fleet's own
        /status.json + /metrics (replica-labelled) — per-member
        quantiles, merged fleet quantiles, straggler detection across
        the gang. Returns (collector, server, collector): the
        collector doubles as the stoppable tailer in run()'s
        teardown."""
        if self.monitor_port is None:
            return None, None, None
        if not self.member_log_files:
            self.log("[elastic] --monitor-port needs the gang command "
                     "to carry --log-file (the metrics JSONL to "
                     "aggregate per member); monitoring disabled")
            return None, None, None
        from shallowspeed_tpu.telemetry.fleet import FleetCollector
        from shallowspeed_tpu.telemetry.monitor import StatusServer

        fc = FleetCollector(paths=self.member_log_files,
                            labels=[f"r{i}" for i in range(self.n)],
                            slos=self.slo)
        srv = StatusServer(fc, port=self.monitor_port)
        fc.start(poll=max(0.5, float(self.poll_interval)))
        self.log(f"[elastic] fleet monitor: "
                 f"{srv.url('/status.json')} (+ /metrics) over "
                 f"{self.n} member file(s)")
        return fc, srv, fc

    def _free_port(self) -> int:
        import socket

        with socket.socket() as s:
            s.bind(("localhost", 0))
            return s.getsockname()[1]

    def _kill_gang(self, children) -> None:
        """SIGTERM the whole gang at once, give every member the one
        shared grace window to flush, then SIGKILL the stragglers."""
        live = [c for c in children if c.poll() is None]
        if self.term_grace and self.term_grace > 0:
            for c in live:
                c.send_signal(signal.SIGTERM)
            deadline = time.monotonic() + self.term_grace
            for c in live:
                try:
                    c.wait(timeout=max(0.05,
                                       deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    pass
        for c in children:
            if c.poll() is None:
                c.send_signal(signal.SIGKILL)
        for c in children:
            c.wait()

    def _run_once(self) -> tuple[int, float, str | None]:
        t0 = time.monotonic()
        self._mark_log()  # poison detector: credit only this gang's lines
        coord = self.coordinator or f"localhost:{self._free_port()}"
        children = []
        # any exception ANYWHERE here (failed spawn, SIGINT in the
        # monitor loop) must not leave members running: they would
        # re-touch their heartbeat files after run()'s cleanup unlinked
        # them, leaking both tmpfiles and orphaned training processes
        try:
            for i in range(self.n):
                argv = list(self.argv)
                if self.member_log_files:
                    argv = _set_argv_log_file(argv,
                                              self.member_log_files[i])
                if self.heartbeat_files:
                    # fresh clock AND fresh status per attempt (see
                    # Supervisor._run_once: a leftover 'dead' would
                    # kill every restarted gang within one poll)
                    try:
                        write_heartbeat(self.heartbeat_files[i], "ok")
                    except OSError:
                        pass
                    argv += ["--heartbeat-file", self.heartbeat_files[i]]
                env = {**os.environ, **self.child_env,
                       "JAX_COORDINATOR_ADDRESS": coord,
                       "JAX_NUM_PROCESSES": str(self.n),
                       "JAX_PROCESS_ID": str(i)}
                children.append(subprocess.Popen(argv, env=env))
            hb_seen = [time.time()] * self.n
            while True:
                codes = [c.poll() for c in children]
                if any(c is not None and c != 0 for c in codes):
                    bad = next(i for i, c in enumerate(codes)
                               if c is not None and c != 0)
                    self.log(f"[elastic] gang member {bad} exited "
                             f"{codes[bad]} — killing the gang")
                    self._kill_gang(children)
                    return (codes[bad], time.monotonic() - t0,
                            classify_exit(codes[bad]) or "crash")
                if all(c == 0 for c in codes):
                    return 0, time.monotonic() - t0, None
                if self.hang_timeout is not None:
                    for i, hb in enumerate(self.heartbeat_files):
                        if codes[i] == 0:
                            continue  # finished members stop beating
                        status = read_heartbeat_status(hb)
                        if status.startswith("dead"):
                            self.log(f"[elastic] gang member {i} "
                                     f"health verdict {status!r} — "
                                     f"killing the gang for a "
                                     f"checkpoint restart")
                            self._kill_gang(children)
                            return -9, time.monotonic() - t0, "numeric"
                        try:
                            hb_seen[i] = max(hb_seen[i],
                                             os.path.getmtime(hb))
                        except OSError:
                            pass
                        stale = time.time() - hb_seen[i]
                        if stale > self.hang_timeout:
                            self.log(f"[elastic] gang member {i} "
                                     f"heartbeat stale {stale:.0f}s > "
                                     f"{self.hang_timeout}s — killing "
                                     f"the gang")
                            self._kill_gang(children)
                            return -9, time.monotonic() - t0, "hang"
                time.sleep(self.poll_interval)
        except BaseException:
            self._kill_gang(children)
            raise


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m shallowspeed_tpu.elastic",
        description="Restart-on-failure supervisor with checkpoint-based "
                    "recovery (pair with --save-dir/--auto-resume)")
    ap.add_argument("--max-restarts", type=int, default=3)
    ap.add_argument("--backoff", type=float, default=5.0)
    ap.add_argument("--backoff-max", type=float, default=300.0)
    ap.add_argument("--healthy-after", type=float, default=600.0,
                    help="a run this long refills the restart budget")
    ap.add_argument("--hang-timeout", type=float, default=None,
                    help="kill the child if its heartbeat file goes "
                         "stale this long (seconds)")
    ap.add_argument("--term-grace", type=float, default=5.0,
                    help="kill path: SIGTERM first and wait this long "
                         "for the child to flush its metrics/ledger "
                         "tail before SIGKILL (0 = straight SIGKILL)")
    ap.add_argument("--jitter", type=float, default=0.1,
                    help="stretch each restart backoff by up to this "
                         "fraction (seeded; decorrelates a fleet of "
                         "supervisors restarting off one outage)")
    ap.add_argument("--chaos", default="",
                    help="fault-injection plan for the CHILDREN "
                         "(shallowspeed_tpu.chaos DSL or JSON path), "
                         "exported via SHALLOWSPEED_CHAOS — a staging "
                         "drill of the recovery stack")
    ap.add_argument("--chaos-state", default="",
                    help="directory for the chaos plan's fired-fault "
                         "markers; MUST survive restarts for faults "
                         "to fire once per run (required with --chaos)")
    ap.add_argument("--chaos-seed", type=int, default=0)
    ap.add_argument("--procs", type=int, default=1,
                    help="gang mode: launch N multi-controller "
                         "processes of the command (JAX_COORDINATOR_"
                         "ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID "
                         "injected); any member failure restarts the "
                         "whole gang from checkpoint")
    ap.add_argument("--coordinator", default=None,
                    help="pin the gang's coordinator address "
                         "(default: a fresh localhost port per attempt)")
    ap.add_argument("--monitor-port", type=int, default=None,
                    help="serve /status.json + /metrics for the whole "
                         "supervised history (tails the child's "
                         "--log-file across restarts; 0 = free port). "
                         "With --procs N the gang's --log-file is "
                         "rewritten per member (<base>.r<i>) and one "
                         "fleet collector (telemetry/fleet) serves "
                         "merged quantiles, per-member breakdown, and "
                         "straggler events across the gang")
    ap.add_argument("--slo", default="",
                    help="SLOs evaluated over the aggregated stream "
                         "(telemetry/monitor DSL, e.g. "
                         "'ttft_p95_ms<500,availability>0.99')")
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="-- training command")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no training command given (separate it with --)")
    policy = RestartPolicy(
        max_restarts=args.max_restarts, backoff=args.backoff,
        backoff_max=args.backoff_max, healthy_after=args.healthy_after,
        # per-process entropy: N supervisors restarting off one shared
        # outage must draw DIFFERENT jitter streams, or the jitter
        # decorrelates nothing (a fixed default seed would re-sync the
        # herd); tests that need determinism build RestartPolicy
        # directly with an explicit seed
        jitter=args.jitter, seed=os.getpid())
    child_env = None
    if args.chaos:
        if not args.chaos_state:
            ap.error("--chaos needs --chaos-state (fired-fault markers "
                     "must survive restarts, or every restarted child "
                     "re-fires every fault)")
        from shallowspeed_tpu.chaos import FaultPlan

        plan = FaultPlan.parse(args.chaos, seed=args.chaos_seed,
                               state_dir=args.chaos_state)
        child_env = {k: v for k, v in plan.export_env().items()
                     if k.startswith("SHALLOWSPEED_CHAOS")}
    if args.procs > 1:
        sup = GangSupervisor(cmd, args.procs, policy,
                             hang_timeout=args.hang_timeout,
                             coordinator=args.coordinator,
                             term_grace=args.term_grace,
                             child_env=child_env,
                             monitor_port=args.monitor_port,
                             slo=args.slo)
    else:
        sup = Supervisor(cmd, policy, hang_timeout=args.hang_timeout,
                         term_grace=args.term_grace,
                         child_env=child_env,
                         monitor_port=args.monitor_port,
                         slo=args.slo)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
