"""Optimizers as pure pytree transforms.

Capability parity with the reference's stateless SGD
(`/root/reference/shallowspeed/optimizer.py:4-13`, `param.data -= lr * grad`),
re-designed functionally: `step(params, grads, state) -> (params, state)` is a
pure function that jits and shards like any other part of the training step
(optax-style, but self-contained). Momentum-SGD, Adam, AdamW, learning-rate
schedules, and global-norm gradient clipping are additions beyond the
reference surface.

Every optimizer accepts `lr` as either a float or a schedule — a callable
`t -> lr` evaluated on the (0-based) step counter carried in the optimizer
state, traced into the compiled step so the schedule runs on-device. A
`grad_clip` argument applies global-norm clipping before the update.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp

tree_map = jax.tree_util.tree_map

LR = Union[float, Callable[[jax.Array], jax.Array]]

# ------------------------------------------------------------- schedules


def constant(peak: float, warmup: int = 0, total: int = 0, end: float = 0.0):
    """Constant schedule. Signature-compatible with warmup_linear/
    warmup_cosine (warmup/total/end accepted and ignored) so call sites can
    construct any SCHEDULES entry uniformly."""
    return lambda t: jnp.asarray(peak, jnp.float32)


def warmup_linear(peak: float, warmup: int, total: int, end: float = 0.0):
    """Linear 0 -> peak over `warmup` steps, then linear peak -> end at
    `total` steps (clamped after)."""
    def sched(t):
        t = jnp.asarray(t, jnp.float32)
        up = peak * t / max(warmup, 1)
        frac = jnp.clip((t - warmup) / max(total - warmup, 1), 0.0, 1.0)
        down = peak + (end - peak) * frac
        return jnp.where(t < warmup, up, down)

    return sched


def warmup_cosine(peak: float, warmup: int, total: int, end: float = 0.0):
    """Linear 0 -> peak over `warmup` steps, then cosine peak -> end at
    `total` steps (clamped after). The standard LM-pretraining schedule."""
    def sched(t):
        t = jnp.asarray(t, jnp.float32)
        up = peak * t / max(warmup, 1)
        frac = jnp.clip((t - warmup) / max(total - warmup, 1), 0.0, 1.0)
        down = end + (peak - end) * 0.5 * (1 + jnp.cos(math.pi * frac))
        return jnp.where(t < warmup, up, down)

    return sched


SCHEDULES = {"constant": constant, "linear": warmup_linear,
             "cosine": warmup_cosine}

# -------------------------------------------------------------- clipping


def _varying_axes(x, axes: tuple) -> tuple:
    """The subset of `axes` the value actually varies over (shard_map VMA
    typing). A leaf invariant over an axis is already fully reduced there
    — psumming it would count it axis-size times. Refuses to guess when
    VMA introspection is unavailable: a silent wrong norm (replicated
    leaves counted axis-size times) is worse than an error."""
    try:
        vma = jax.typeof(x).vma
    except Exception as e:
        raise RuntimeError(
            "global_norm with mesh axes needs shard_map VMA introspection "
            "(jax.typeof(...).vma) to tell sharded gradient leaves from "
            "replicated ones; this jax version does not expose it") from e
    return tuple(a for a in axes if a in vma)


def global_norm(grads: Any, axes: tuple = ()) -> jax.Array:
    """L2 norm over every leaf of the gradient pytree (f32 accumulation).

    `axes`: mesh axis names to `lax.psum` squared sums over — required
    when called inside `shard_map` with grads *sharded* over those axes
    (e.g. per-stage grads over 'pp' in the pipeline engines), so the norm
    is the true global one, not the local shard's. Per-leaf variance is
    respected: a pytree mixing pp-sharded block grads with replicated
    (already-reduced) embedding grads sums each exactly once."""
    leaves = jax.tree_util.tree_leaves(grads)
    total = jnp.float32(0.0)
    for l in leaves:
        sq = jnp.sum(jnp.square(l.astype(jnp.float32)))
        ax = _varying_axes(sq, axes) if axes else ()
        if ax:
            sq = jax.lax.psum(sq, ax)
        total = total + sq
    return jnp.sqrt(total)


def clip_by_global_norm(grads: Any, max_norm: float,
                        axes: tuple = ()) -> Any:
    """Scale the whole pytree so its global norm is at most `max_norm`."""
    norm = global_norm(grads, axes)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-12))
    return tree_map(lambda g: (g * scale).astype(g.dtype), grads)


# ------------------------------------------------------------ optimizers


class _Optimizer:
    """Shared lr/schedule/clip plumbing.

    `clip_axes` (class default `()`): mesh axis names whose shards must be
    psum-combined for the clipping norm. Engines that trace `step` inside a
    `shard_map` where grads are *sharded* (not invariant) set this on their
    private copy of the optimizer (see `SPMDPipelineEngine`); with grads
    replicated or under GSPMD-jit the default is already the global norm."""

    clip_axes: tuple = ()

    def __init__(self, lr: LR, grad_clip: float | None = None):
        self.lr = lr
        self.grad_clip = grad_clip

    def _lr_at(self, t) -> jax.Array:
        if callable(self.lr):
            return jnp.asarray(self.lr(t), jnp.float32)
        return jnp.asarray(self.lr, jnp.float32)

    def _prep(self, grads: Any) -> Any:
        if self.grad_clip is not None:
            return clip_by_global_norm(grads, self.grad_clip, self.clip_axes)
        return grads


class SGD(_Optimizer):
    """Plain SGD. Reference: `optimizer.py:4-13`. Stateless with a static
    lr (exactly the reference's shape); carries a step counter only when
    driven by a schedule."""

    def init(self, params: Any) -> Any:
        if callable(self.lr):
            return {"t": jnp.zeros((), jnp.int32)}
        return ()

    def step(self, params: Any, grads: Any, state: Any = ()):
        grads = self._prep(grads)
        sched = callable(self.lr)
        t = state["t"] if sched else jnp.zeros((), jnp.int32)
        lr = self._lr_at(t)
        # update math may promote to f32 (lr is a strong f32 scalar, grads
        # may be f32 master-dtype); params keep their own dtype
        new = tree_map(lambda p, g: (p - lr * g).astype(p.dtype),
                       params, grads)
        return new, ({"t": t + 1} if sched else state)


class MomentumSGD(_Optimizer):
    """SGD with classical momentum (addition beyond the reference)."""

    def __init__(self, lr: LR, momentum: float = 0.9,
                 grad_clip: float | None = None):
        super().__init__(lr, grad_clip)
        self.momentum = momentum

    def init(self, params: Any) -> Any:
        vel = tree_map(jnp.zeros_like, params)
        if callable(self.lr):
            return {"v": vel, "t": jnp.zeros((), jnp.int32)}
        return vel

    def step(self, params: Any, grads: Any, state: Any):
        grads = self._prep(grads)
        sched = callable(self.lr)
        vel0 = state["v"] if sched else state
        t = state["t"] if sched else jnp.zeros((), jnp.int32)
        lr = self._lr_at(t)
        vel = tree_map(lambda v, g: (self.momentum * v + g).astype(v.dtype),
                       vel0, grads)
        new = tree_map(lambda p, v: (p - lr * v).astype(p.dtype),
                       params, vel)
        return new, ({"v": vel, "t": t + 1} if sched else vel)


class Adam(_Optimizer):
    """Adam (addition; matches the reference's PyTorch-DDP baseline script,
    `scripts/DDP_PyTorch_MNIST.py`, which trains with torch Adam)."""

    def __init__(self, lr: LR, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, grad_clip: float | None = None):
        super().__init__(lr, grad_clip)
        self.b1, self.b2, self.eps = b1, b2, eps

    weight_decay = 0.0  # AdamW overrides; keeps `_update` shared

    def init(self, params: Any) -> Any:
        return {"m": tree_map(jnp.zeros_like, params),
                "v": tree_map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def step(self, params: Any, grads: Any, state: Any):
        grads = self._prep(grads)
        lr = self._lr_at(state["t"])  # schedule indexed 0-based
        t = state["t"] + 1
        m = tree_map(
            lambda m_, g: (self.b1 * m_ + (1 - self.b1) * g).astype(m_.dtype),
            state["m"], grads)
        v = tree_map(
            lambda v_, g: (self.b2 * v_
                           + (1 - self.b2) * g * g).astype(v_.dtype),
            state["v"], grads)
        tf = t.astype(jnp.float32)
        bc1 = 1 - self.b1 ** tf
        bc2 = 1 - self.b2 ** tf
        wd = self.weight_decay
        new = tree_map(
            lambda p, m_, v_: (p - lr * ((m_ / bc1) /
                                         (jnp.sqrt(v_ / bc2) + self.eps)
                                         + wd * p)).astype(p.dtype),
            params, m, v)
        return new, {"m": m, "v": v, "t": t}


class AdamW(Adam):
    """Adam with decoupled weight decay (Loshchilov & Hutter, 2019):
    the decay term `wd * p` joins the update *after* the moment estimate,
    scaled by lr — torch.optim.AdamW semantics."""

    def __init__(self, lr: LR, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, weight_decay: float = 0.01,
                 grad_clip: float | None = None):
        super().__init__(lr, b1, b2, eps, grad_clip)
        self.weight_decay = weight_decay


OPTIMIZERS = {"sgd": SGD, "momentum": MomentumSGD, "adam": Adam,
              "adamw": AdamW}
