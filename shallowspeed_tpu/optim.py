"""Optimizers as pure pytree transforms.

Capability parity with the reference's stateless SGD
(`/root/reference/shallowspeed/optimizer.py:4-13`, `param.data -= lr * grad`),
re-designed functionally: `step(params, grads, state) -> (params, state)` is a
pure function that jits and shards like any other part of the training step
(optax-style, but self-contained). Momentum-SGD, Adam, AdamW, learning-rate
schedules, and global-norm gradient clipping are additions beyond the
reference surface.

Every optimizer accepts `lr` as either a float or a schedule — a callable
`t -> lr` evaluated on the (0-based) step counter carried in the optimizer
state, traced into the compiled step so the schedule runs on-device. A
`grad_clip` argument applies global-norm clipping before the update.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Union

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec

tree_map = jax.tree_util.tree_map

LR = Union[float, Callable[[jax.Array], jax.Array]]

# ------------------------------------------------------------- schedules


def constant(peak: float, warmup: int = 0, total: int = 0, end: float = 0.0):
    """Constant schedule. Signature-compatible with warmup_linear/
    warmup_cosine (warmup/total/end accepted and ignored) so call sites can
    construct any SCHEDULES entry uniformly."""
    return lambda t: jnp.asarray(peak, jnp.float32)


def warmup_linear(peak: float, warmup: int, total: int, end: float = 0.0):
    """Linear 0 -> peak over `warmup` steps, then linear peak -> end at
    `total` steps (clamped after)."""
    def sched(t):
        t = jnp.asarray(t, jnp.float32)
        up = peak * t / max(warmup, 1)
        frac = jnp.clip((t - warmup) / max(total - warmup, 1), 0.0, 1.0)
        down = peak + (end - peak) * frac
        return jnp.where(t < warmup, up, down)

    return sched


def warmup_cosine(peak: float, warmup: int, total: int, end: float = 0.0):
    """Linear 0 -> peak over `warmup` steps, then cosine peak -> end at
    `total` steps (clamped after). The standard LM-pretraining schedule."""
    def sched(t):
        t = jnp.asarray(t, jnp.float32)
        up = peak * t / max(warmup, 1)
        frac = jnp.clip((t - warmup) / max(total - warmup, 1), 0.0, 1.0)
        down = end + (peak - end) * 0.5 * (1 + jnp.cos(math.pi * frac))
        return jnp.where(t < warmup, up, down)

    return sched


SCHEDULES = {"constant": constant, "linear": warmup_linear,
             "cosine": warmup_cosine}

# -------------------------------------------------------------- clipping


def _varying_axes(x, axes: tuple) -> tuple:
    """The subset of `axes` the value actually varies over (shard_map VMA
    typing). A leaf invariant over an axis is already fully reduced there
    — psumming it would count it axis-size times. Refuses to guess when
    VMA introspection is unavailable: a silent wrong norm (replicated
    leaves counted axis-size times) is worse than an error."""
    try:
        vma = jax.typeof(x).vma
    except Exception as e:
        raise RuntimeError(
            "global_norm with mesh axes needs shard_map VMA introspection "
            "(jax.typeof(...).vma) to tell sharded gradient leaves from "
            "replicated ones; this jax version does not expose it") from e
    return tuple(a for a in axes if a in vma)


def global_norm(grads: Any, axes: tuple = ()) -> jax.Array:
    """L2 norm over every leaf of the gradient pytree (f32 accumulation).

    `axes`: mesh axis names to `lax.psum` squared sums over — required
    when called inside `shard_map` with grads *sharded* over those axes
    (e.g. per-stage grads over 'pp' in the pipeline engines), so the norm
    is the true global one, not the local shard's. Per-leaf variance is
    respected: a pytree mixing pp-sharded block grads with replicated
    (already-reduced) embedding grads sums each exactly once."""
    leaves = jax.tree_util.tree_leaves(grads)
    total = jnp.float32(0.0)
    for l in leaves:
        sq = jnp.sum(jnp.square(l.astype(jnp.float32)))
        ax = _varying_axes(sq, axes) if axes else ()
        if ax:
            sq = jax.lax.psum(sq, ax)
        total = total + sq
    return jnp.sqrt(total)


def clip_by_global_norm(grads: Any, max_norm: float,
                        axes: tuple = ()) -> Any:
    """Scale the whole pytree so its global norm is at most `max_norm`."""
    norm = global_norm(grads, axes)
    scale = jnp.minimum(1.0, max_norm / (norm + 1e-12))
    return tree_map(lambda g: (g * scale).astype(g.dtype), grads)




# ------------------------------------------------------------ optimizers


class _Optimizer:
    """Shared lr/schedule/clip plumbing.

    `clip_axes` (class default `()`): mesh axis names whose shards must be
    psum-combined for the clipping norm. Engines that trace `step` inside a
    `shard_map` where grads are *sharded* (not invariant) set this on their
    private copy of the optimizer (see `SPMDPipelineEngine`); with grads
    replicated or under GSPMD-jit the default is already the global norm."""

    clip_axes: tuple = ()

    def __init__(self, lr: LR, grad_clip: float | None = None):
        self.lr = lr
        self.grad_clip = grad_clip

    def _lr_at(self, t) -> jax.Array:
        if callable(self.lr):
            return jnp.asarray(self.lr(t), jnp.float32)
        return jnp.asarray(self.lr, jnp.float32)

    def _prep(self, grads: Any) -> Any:
        if self.grad_clip is not None:
            return clip_by_global_norm(grads, self.grad_clip, self.clip_axes)
        return grads

    def guarded_step(self, params: Any, grads: Any, state: Any, ok):
        """`step` with the whole update gated on the traced bool `ok`
        (shape (), e.g. the health pack's `nonfinite == 0` sentinel):
        when `ok` is False every parameter AND optimizer-state leaf —
        moments, step counters, schedule state — is the old value,
        bit-identical, so a skipped step is indistinguishable from
        never having run. This is the `skip_step` guard the health
        layer (`telemetry/health.py`) compiles into the engines' train
        steps; it lives here, next to `_prep`'s clipping, because both
        gate the update on the same global gradient statistics."""
        new_p, new_s = self.step(params, grads, state)

        def keep(new, old):
            return jnp.where(ok, new, old)

        return (tree_map(keep, new_p, params),
                tree_map(keep, new_s, state))

    def map_state_trees(self, state: Any, fn) -> Any:
        """Apply `fn` — a params-shaped-tree -> params-shaped-tree
        transform (e.g. an engine's stack/unstack between its layout and
        the canonical checkpoint layout) — to every params-shaped moment
        tree inside `state`, passing scalars (step counters) through.

        This is the seam that makes optimizer state engine-agnostic in
        checkpoints (`checkpoint.py`): an engine that can re-layout its
        params can re-layout exactly-params-shaped moments with the SAME
        transform. Default: no params-shaped trees (stateless SGD).
        Optimizers whose state is NOT params-shaped (Adafactor's factored
        vr/vc) raise ValueError — callers fall back to re-initializing.
        """
        return state


class SGD(_Optimizer):
    """Plain SGD. Reference: `optimizer.py:4-13`. Stateless with a static
    lr (exactly the reference's shape); carries a step counter only when
    driven by a schedule."""

    def init(self, params: Any) -> Any:
        if callable(self.lr):
            return {"t": jnp.zeros((), jnp.int32)}
        return ()

    def step(self, params: Any, grads: Any, state: Any = ()):
        grads = self._prep(grads)
        sched = callable(self.lr)
        t = state["t"] if sched else jnp.zeros((), jnp.int32)
        lr = self._lr_at(t)
        # update math may promote to f32 (lr is a strong f32 scalar, grads
        # may be f32 master-dtype); params keep their own dtype
        new = tree_map(lambda p, g: (p - lr * g).astype(p.dtype),
                       params, grads)
        return new, ({"t": t + 1} if sched else state)


class MomentumSGD(_Optimizer):
    """SGD with classical momentum (addition beyond the reference)."""

    def __init__(self, lr: LR, momentum: float = 0.9,
                 grad_clip: float | None = None):
        super().__init__(lr, grad_clip)
        self.momentum = momentum

    def init(self, params: Any) -> Any:
        vel = tree_map(jnp.zeros_like, params)
        if callable(self.lr):
            return {"v": vel, "t": jnp.zeros((), jnp.int32)}
        return vel

    def step(self, params: Any, grads: Any, state: Any):
        grads = self._prep(grads)
        sched = callable(self.lr)
        vel0 = state["v"] if sched else state
        t = state["t"] if sched else jnp.zeros((), jnp.int32)
        lr = self._lr_at(t)
        vel = tree_map(lambda v, g: (self.momentum * v + g).astype(v.dtype),
                       vel0, grads)
        new = tree_map(lambda p, v: (p - lr * v).astype(p.dtype),
                       params, vel)
        return new, ({"v": vel, "t": t + 1} if sched else vel)

    def map_state_trees(self, state: Any, fn) -> Any:
        if isinstance(state, dict) and "v" in state:
            return {"v": fn(state["v"]), "t": state["t"]}
        return fn(state)


class Adam(_Optimizer):
    """Adam (addition; matches the reference's PyTorch-DDP baseline script,
    `scripts/DDP_PyTorch_MNIST.py`, which trains with torch Adam)."""

    def __init__(self, lr: LR, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, grad_clip: float | None = None):
        super().__init__(lr, grad_clip)
        self.b1, self.b2, self.eps = b1, b2, eps

    weight_decay = 0.0  # AdamW overrides; keeps `_update` shared

    def init(self, params: Any) -> Any:
        return {"m": tree_map(jnp.zeros_like, params),
                "v": tree_map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def step(self, params: Any, grads: Any, state: Any):
        grads = self._prep(grads)
        lr = self._lr_at(state["t"])  # schedule indexed 0-based
        t = state["t"] + 1
        m = tree_map(
            lambda m_, g: (self.b1 * m_ + (1 - self.b1) * g).astype(m_.dtype),
            state["m"], grads)
        v = tree_map(
            lambda v_, g: (self.b2 * v_
                           + (1 - self.b2) * g * g).astype(v_.dtype),
            state["v"], grads)
        tf = t.astype(jnp.float32)
        bc1 = 1 - self.b1 ** tf
        bc2 = 1 - self.b2 ** tf
        wd = self.weight_decay
        new = tree_map(
            lambda p, m_, v_: (p - lr * ((m_ / bc1) /
                                         (jnp.sqrt(v_ / bc2) + self.eps)
                                         + wd * p)).astype(p.dtype),
            params, m, v)
        return new, {"m": m, "v": v, "t": t}

    def map_state_trees(self, state: Any, fn) -> Any:
        return {"m": fn(state["m"]), "v": fn(state["v"]), "t": state["t"]}


class AdamW(Adam):
    """Adam with decoupled weight decay (Loshchilov & Hutter, 2019):
    the decay term `wd * p` joins the update *after* the moment estimate,
    scaled by lr — torch.optim.AdamW semantics."""

    def __init__(self, lr: LR, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8, weight_decay: float = 0.01,
                 grad_clip: float | None = None):
        super().__init__(lr, b1, b2, eps, grad_clip)
        self.weight_decay = weight_decay


class Adafactor(_Optimizer):
    """Adafactor (Shazeer & Stern, 2018) — the TPU-era memory-efficient
    optimizer: matrix leaves store FACTORED second moments (a row vector +
    a column vector instead of a full matrix; O(n+m) not O(nm) state), so
    the optimizer footprint all but vanishes next to Adam's 2x params.
    Composes with ZeRO-1/2 like any other state (the factored vectors
    shard over dp too) — together the two give DeepSpeed-style memory
    scaling with a fraction of the bytes to shard in the first place.

    Implementation notes:
    - leaves with ndim >= 2 factor their TRAILING two dims; leading dims
      (stacked pipeline blocks (L, d, k·d), MoE experts (E, d, ff)) stay
      elementwise, so every engine's parameter layout factors usefully.
    - ndim <= 1 leaves (biases, norms) keep a full second moment.
    - beta2 follows the paper's schedule 1 - t^(-0.8); updates are
      RMS-clipped at `clip_threshold`; with `scale_parameter` the step is
      multiplied by max(eps_scale, RMS(param)) — the paper's relative
      step — so `lr` plays the role of the relative step size.
    - no first moment by default (`beta1=0.0` — the memory point);
      set beta1 > 0 to trade memory for momentum.
    - the per-leaf RMS statistics (clip, parameter scale) are computed
      over whatever the leaf IS where the step runs: under model-sharded
      shard_map engines (pp-stacked blocks) that is the local shard —
      a standard, benign approximation (the paper's statistics are
      per-matrix heuristics to begin with); under GSPMD engines the
      statistics are exact.
    """

    def __init__(self, lr: LR, beta1: float = 0.0, decay_pow: float = 0.8,
                 eps: float = 1e-30, eps_scale: float = 1e-3,
                 clip_threshold: float = 1.0, scale_parameter: bool = True,
                 weight_decay: float = 0.0,
                 grad_clip: float | None = None):
        super().__init__(lr, grad_clip)
        self.beta1 = beta1
        self.decay_pow = decay_pow
        self.eps = eps
        self.eps_scale = eps_scale
        self.clip_threshold = clip_threshold
        self.scale_parameter = scale_parameter
        self.weight_decay = weight_decay

    @staticmethod
    def _factored(p) -> bool:
        """Factor the trailing two dims — iff they are unsharded. The
        row/col statistics REDUCE those dims, so a mesh axis living there
        would make the statistics shard-local (wrong under shard_map) or
        force extra collectives (under GSPMD); such leaves (e.g. Megatron
        column/row-sharded matrices) keep a full second moment instead.
        Leading stacked dims (pipeline blocks (L, ...), MoE experts
        (E, ...)) may be sharded freely — their axes survive into vr/vc."""
        if p.ndim < 2:
            return False
        sh = getattr(p, "sharding", None)
        if isinstance(sh, NamedSharding):
            spec = list(sh.spec) + [None] * (p.ndim - len(sh.spec))
            return spec[-1] is None and spec[-2] is None
        return True

    def _slot(self, p):
        if self._factored(p):
            # the factored zeros inherit the parameter's placement on the
            # surviving (leading) dims — a pp-stacked (L, d, k) block
            # leaf yields P('pp', ...)-sharded vr/vc — which is what lets
            # the sharded engines read optimizer-state specs off the
            # leaves
            vr = jnp.zeros(p.shape[:-1], jnp.float32)
            vc = jnp.zeros(p.shape[:-2] + p.shape[-1:], jnp.float32)
            sh = getattr(p, "sharding", None)
            if isinstance(sh, NamedSharding):
                spec = list(sh.spec) + [None] * (p.ndim - len(sh.spec))
                vr = jax.device_put(
                    vr, NamedSharding(sh.mesh, PartitionSpec(*spec[:-1])))
                vc = jax.device_put(
                    vc, NamedSharding(sh.mesh,
                                      PartitionSpec(*spec[:-2]
                                                    + spec[-1:])))
            slot = {"vr": vr, "vc": vc}
        else:
            slot = {"v": jnp.zeros_like(p, jnp.float32)}
        if self.beta1 > 0.0:
            slot["m"] = jnp.zeros_like(p, jnp.float32)
        return slot

    def init(self, params: Any) -> Any:
        leaves, tdef = jax.tree_util.tree_flatten(params)
        return {"slots": tuple(self._slot(p) for p in leaves),
                "t": jnp.zeros((), jnp.int32)}

    def step(self, params: Any, grads: Any, state: Any):
        grads = self._prep(grads)
        lr = self._lr_at(state["t"])
        t = state["t"] + 1
        beta2 = 1.0 - t.astype(jnp.float32) ** (-self.decay_pow)

        p_leaves, tdef = jax.tree_util.tree_flatten(params)
        g_leaves = jax.tree_util.tree_leaves(grads)
        new_p, new_slots = [], []
        for p, g, slot in zip(p_leaves, g_leaves, state["slots"]):
            gf = g.astype(jnp.float32)
            g2 = gf * gf + self.eps
            slot = dict(slot)
            # branch on the slot's structure (decided at init, where real
            # shardings are visible), never on the traced param
            if "vr" in slot:
                vr = beta2 * slot["vr"] + (1 - beta2) * g2.mean(axis=-1)
                vc = beta2 * slot["vc"] + (1 - beta2) * g2.mean(axis=-2)
                slot["vr"], slot["vc"] = vr, vc
                # v̂ = (vr / mean(vr)) ⊗ vc — the rank-1 reconstruction
                rfac = vr / vr.mean(axis=-1, keepdims=True)
                u = gf * jax.lax.rsqrt(rfac[..., :, None]
                                       * vc[..., None, :])
            else:
                v = beta2 * slot["v"] + (1 - beta2) * g2
                slot["v"] = v
                u = gf * jax.lax.rsqrt(v)
            # RMS clip: tame early steps when the moment estimate is cold
            rms_u = jnp.sqrt(jnp.mean(u * u))
            u = u / jnp.maximum(1.0, rms_u / self.clip_threshold)
            a = lr
            if self.scale_parameter:
                a = a * jnp.maximum(
                    self.eps_scale,
                    jnp.sqrt(jnp.mean(jnp.square(p.astype(jnp.float32)))))
            if self.beta1 > 0.0:
                m = self.beta1 * slot["m"] + (1 - self.beta1) * u
                slot["m"] = m
                u = m
            # decay with the same parameter-scaled step as the main
            # update: under scale_parameter the schedule lr is a
            # *relative* step size, so decay strength must track RMS(p)
            # too or leaves with small/large RMS decay disproportionately
            upd = a * u + a * self.weight_decay * p.astype(jnp.float32)
            new_p.append((p.astype(jnp.float32) - upd).astype(p.dtype))
            new_slots.append(slot)
        return (jax.tree_util.tree_unflatten(tdef, new_p),
                {"slots": tuple(new_slots), "t": t})

    def map_state_trees(self, state: Any, fn) -> Any:
        raise ValueError(
            "Adafactor state is factored (per-leaf vr/vc vectors keyed to "
            "the flattened engine params), not params-shaped; it cannot "
            "be re-laid-out by a params-tree transform. Engines whose "
            "layout IS canonical interchange it directly.")


OPTIMIZERS = {"sgd": SGD, "momentum": MomentumSGD, "adam": Adam,
              "adamw": AdamW, "adafactor": Adafactor}


# ------------------------------------------------------------------- EMA


@partial(jax.jit, donate_argnums=(0,))
def ema_update(ema: Any, params: Any, decay) -> Any:
    """One exponential-moving-average step: ema <- d*ema + (1-d)*params.

    Pure elementwise pytree transform: works on ANY engine's live params
    (replicated, ZeRO/FSDP-sharded, pipeline-stacked) because the output
    inherits each leaf's sharding; the old ema buffer is donated, so the
    running average costs one params-sized buffer total. Engines stay
    untouched — the driver owns the averaging (and evaluates/samples by
    temporarily swapping the averaged tree in)."""
    d = jnp.float32(decay)
    return tree_map(
        lambda e, p: (d * e + (1.0 - d) * p.astype(jnp.float32))
        .astype(e.dtype), ema, params)


def ema_init(params: Any) -> Any:
    """Start the average AT the current params (standard warm init —
    an all-zeros start would bias early evals toward zero)."""
    return tree_map(lambda p: p + 0, params)  # copy, keeps sharding
