"""Optimizers as pure pytree transforms.

Capability parity with the reference's stateless SGD
(`/root/reference/shallowspeed/optimizer.py:4-13`, `param.data -= lr * grad`),
re-designed functionally: `step(params, grads, state) -> (params, state)` is a
pure function that jits and shards like any other part of the training step
(optax-style, but self-contained). Momentum-SGD and Adam are additions beyond
the reference surface.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

tree_map = jax.tree_util.tree_map


class SGD:
    """Plain SGD. Reference: `optimizer.py:4-13`."""

    def __init__(self, lr: float):
        self.lr = lr

    def init(self, params: Any) -> Any:
        return ()

    def step(self, params: Any, grads: Any, state: Any = ()):
        new = tree_map(lambda p, g: p - self.lr * g, params, grads)
        return new, state


class MomentumSGD:
    """SGD with classical momentum (addition beyond the reference)."""

    def __init__(self, lr: float, momentum: float = 0.9):
        self.lr = lr
        self.momentum = momentum

    def init(self, params: Any) -> Any:
        return tree_map(jnp.zeros_like, params)

    def step(self, params: Any, grads: Any, state: Any):
        vel = tree_map(lambda v, g: self.momentum * v + g, state, grads)
        new = tree_map(lambda p, v: p - self.lr * v, params, vel)
        return new, vel


class Adam:
    """Adam (addition; matches the reference's PyTorch-DDP baseline script,
    `scripts/DDP_PyTorch_MNIST.py`, which trains with torch Adam)."""

    def __init__(self, lr: float, b1: float = 0.9, b2: float = 0.999,
                 eps: float = 1e-8):
        self.lr, self.b1, self.b2, self.eps = lr, b1, b2, eps

    def init(self, params: Any) -> Any:
        return {"m": tree_map(jnp.zeros_like, params),
                "v": tree_map(jnp.zeros_like, params),
                "t": jnp.zeros((), jnp.int32)}

    def step(self, params: Any, grads: Any, state: Any):
        t = state["t"] + 1
        m = tree_map(lambda m_, g: self.b1 * m_ + (1 - self.b1) * g,
                     state["m"], grads)
        v = tree_map(lambda v_, g: self.b2 * v_ + (1 - self.b2) * g * g,
                     state["v"], grads)
        tf = t.astype(jnp.float32)
        bc1 = 1 - self.b1 ** tf
        bc2 = 1 - self.b2 ** tf
        new = tree_map(
            lambda p, m_, v_: p - self.lr * (m_ / bc1) /
            (jnp.sqrt(v_ / bc2) + self.eps),
            params, m, v)
        return new, {"m": m, "v": v, "t": t}


OPTIMIZERS = {"sgd": SGD, "momentum": MomentumSGD, "adam": Adam}
