"""FSDP / ZeRO-3: parameters, gradients, AND optimizer state sharded
over the data-parallel axis.

ZeRO stage 3 (Rajbhandari et al., 2020) / torch FSDP eliminate all
replicated training state: every rank owns 1/dp of each parameter, gathers
full parameters just-in-time for each layer's compute, re-gathers for the
backward, and reduce-scatters gradients so each rank keeps only its
gradient shard for the (sharded) optimizer update. The reference framework
replicates everything (SURVEY §2: its DP is gradient-all-reduce only,
`/root/reference/shallowspeed/pipe.py:302-327`).

TPU-native formulation: FSDP is a *placement decision*, not a runtime.
Each parameter leaf gets `PartitionSpec('dp' on its largest divisible
dim)`; the batch is sharded over 'dp' as usual; the training step is the
same jitted `(params, opt_state, batch) -> (params, opt_state, loss)`
program as every other GSPMD engine. XLA's SPMD partitioner then inserts
exactly the collective schedule ZeRO-3 hand-codes — all-gather each
weight where the forward/backward needs it full, reduce-scatter each
gradient where the update needs it sharded — and its latency-hiding
scheduler overlaps those collectives with compute. Optimizer moments
inherit the parameter sharding via `zeros_like` (see `GSPMDEngine`), so
the per-device footprint of params + grads + moments is 1/dp with no
extra machinery: ZeRO-1 and ZeRO-2 fall out as strict subsets.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.parallel.gspmd import GSPMDEngine

tree_map = jax.tree_util.tree_map


def add_dp(spec: P, shape: tuple, dp: int) -> P:
    """Add 'dp' to the LARGEST dimension not already sharded and divisible
    by dp (the biggest shard-able axis minimizes the number of leaves that
    stay replicated and spreads the big matrices); return the spec
    unchanged if none qualifies (e.g. tiny biases when dp > their length).
    The single placement rule behind both pure FSDP (empty base spec) and
    ZeRO-3-over-TP (`parallel/composite.py`)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    candidates = [(d, i) for i, d in enumerate(shape)
                  if entries[i] is None and d and d % dp == 0]
    if not candidates:
        return spec
    _, i = max(candidates)
    entries[i] = "dp"
    return P(*entries)


def fsdp_spec(shape: tuple, dp: int) -> P:
    """Pure-FSDP placement: `add_dp` from a fully replicated base."""
    return add_dp(P(), shape, dp)


class FSDPEngine(GSPMDEngine):
    """Fully-sharded data-parallel trainer for the transformer family.

    Mesh: 1-D `('dp',)` — FSDP is pure data parallelism with sharded
    state. Composes with `compute_dtype=bfloat16` (mixed precision) like
    every transformer engine; `zero1` is meaningless here (the optimizer
    state is already fully sharded) and rejected.
    """

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 seed: int = 0, zero1: bool = False, zero2: bool = False,
                 health: str = "off"):
        if zero1 or zero2:
            raise ValueError(
                "FSDP already shards the optimizer state (ZeRO-3 is a "
                "superset of ZeRO-1/2); drop zero1/zero2")
        super().__init__(cfg, optimizer, mesh, seed=seed, zero1=False,
                         health=health)

    def validate(self, cfg: T.TransformerConfig, mesh: Mesh) -> None:
        assert mesh.axis_names == ("dp",), (
            f"FSDPEngine expects a 1-D ('dp',) mesh, got {mesh.axis_names}")

    def param_specs(self, cfg: T.TransformerConfig) -> dict:
        dp = self.mesh.devices.shape[0]
        # shapes from the host init the base class already built
        return tree_map(lambda a: fsdp_spec(a.shape, dp), self._params_host)
