"""FSDP / ZeRO-3: parameters, gradients, AND optimizer state sharded
over the data-parallel axis.

ZeRO stage 3 (Rajbhandari et al., 2020) / torch FSDP eliminate all
replicated training state: every rank owns 1/dp of each parameter, gathers
full parameters just-in-time for each layer's compute, re-gathers for the
backward, and reduce-scatters gradients so each rank keeps only its
gradient shard for the (sharded) optimizer update. The reference framework
replicates everything (SURVEY §2: its DP is gradient-all-reduce only,
`/root/reference/shallowspeed/pipe.py:302-327`).

TPU-native formulation: FSDP is a *placement decision*, not a runtime.
Each parameter leaf gets `PartitionSpec('dp' on its largest divisible
dim)`; the batch is sharded over 'dp' as usual; the training step is the
same jitted `(params, opt_state, batch) -> (params, opt_state, loss)`
program as every other GSPMD engine. XLA's SPMD partitioner then inserts
exactly the collective schedule ZeRO-3 hand-codes — all-gather each
weight where the forward/backward needs it full, reduce-scatter each
gradient where the update needs it sharded — and its latency-hiding
scheduler overlaps those collectives with compute. Optimizer moments
inherit the parameter sharding via `zeros_like` (see `GSPMDEngine`), so
the per-device footprint of params + grads + moments is 1/dp with no
extra machinery: ZeRO-1 and ZeRO-2 fall out as strict subsets.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.parallel.gspmd import GSPMDEngine

tree_map = jax.tree_util.tree_map


def add_dp(spec: P, shape: tuple, dp: int) -> P:
    """Add 'dp' to the LARGEST dimension not already sharded and divisible
    by dp (the biggest shard-able axis minimizes the number of leaves that
    stay replicated and spreads the big matrices); return the spec
    unchanged if none qualifies (e.g. tiny biases when dp > their length).
    The single placement rule behind both pure FSDP (empty base spec) and
    ZeRO-3-over-TP (`parallel/composite.py`)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    candidates = [(d, i) for i, d in enumerate(shape)
                  if entries[i] is None and d and d % dp == 0]
    if not candidates:
        return spec
    _, i = max(candidates)
    entries[i] = "dp"
    return P(*entries)


def fsdp_spec(shape: tuple, dp: int) -> P:
    """Pure-FSDP placement: `add_dp` from a fully replicated base."""
    return add_dp(P(), shape, dp)


class FSDPEngine(GSPMDEngine):
    """Fully-sharded data-parallel trainer for the transformer family.

    Mesh: 1-D `('dp',)` — FSDP is pure data parallelism with sharded
    state. Composes with `compute_dtype=bfloat16` (mixed precision) like
    every transformer engine; `zero1` is meaningless here (the optimizer
    state is already fully sharded) and rejected.

    With `overlap=OverlapConfig(...)` the GSPMD step is replaced by an
    explicit shard_map program (`_build_overlapped`): every sharded
    leaf is `all_gather`'d where the forward needs it full — each
    gather's dataflow depends only on its own shard, so XLA's
    latency-hiding scheduler prefetches layer i+1's params under layer
    i's compute — and autodiff transposes each gather into a
    `reduce_scatter` placed exactly where that leaf's gradient
    finalizes in the backward (grads reduce INSIDE the backward, per
    leaf, instead of GSPMD's after-the-fact resharding). Replicated
    leaves (tiny biases dp cannot divide) reduce through bucketed
    psum-on-backward tags. Same math as the GSPMD step — pinned by
    `tests/test_overlap.py` against it.
    """

    supports_overlap = True

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 seed: int = 0, zero1: bool = False, zero2: bool = False,
                 health: str = "off", overlap=None):
        if zero1 or zero2:
            raise ValueError(
                "FSDP already shards the optimizer state (ZeRO-3 is a "
                "superset of ZeRO-1/2); drop zero1/zero2")
        super().__init__(cfg, optimizer, mesh, seed=seed, zero1=False,
                         health=health, overlap=overlap)
        if overlap is not None:
            self._build_overlapped(cfg, optimizer, mesh, health, overlap)

    def validate(self, cfg: T.TransformerConfig, mesh: Mesh) -> None:
        assert mesh.axis_names == ("dp",), (
            f"FSDPEngine expects a 1-D ('dp',) mesh, got {mesh.axis_names}")

    def param_specs(self, cfg: T.TransformerConfig) -> dict:
        dp = self.mesh.devices.shape[0]
        # shapes from the host init the base class already built
        return tree_map(lambda a: fsdp_spec(a.shape, dp), self._params_host)

    # ---------------------------------------------- overlapped step

    def _build_overlapped(self, cfg, optimizer, mesh, health, ov):
        """Replace the GSPMD `_step_fn` with the explicit gather/
        reduce-scatter shard_map program (class docstring). Same
        signature, same placements, same executable count — the swap
        is invisible to the driver/telemetry/checkpoint surfaces."""
        import copy
        from functools import partial

        from shallowspeed_tpu.optim import Adafactor
        from shallowspeed_tpu.parallel import overlap as OV
        from shallowspeed_tpu.utils import shard_map

        if isinstance(optimizer, Adafactor):
            raise ValueError(
                "--overlap fsdp runs the optimizer update on local "
                "shards; Adafactor's factored second moments reduce "
                "over whole matrix dims and need the GSPMD update — "
                "drop --overlap or pick an elementwise optimizer")

        specs = tree_map(lambda l: l.sharding.spec, self.params)
        opt_specs = tree_map(lambda l: l.sharding.spec, self.opt_state)
        leaves, tdef = jax.tree_util.tree_flatten(self.params)
        flat_specs = jax.tree_util.tree_leaves(
            specs, is_leaf=lambda x: isinstance(x, P))
        dims = [next((i for i, ax in enumerate(sp) if ax == "dp"), None)
                for sp in flat_specs]
        dp = self.dp

        # replicated leaves reduce through bucketed psum tags, in
        # backward-finalization order; sharded leaves reduce per-leaf
        # via the gather transpose (one reduce_scatter each)
        repl = [i for i, d in enumerate(dims) if d is None]
        raw = OV.plan_buckets([leaves[i] for i in repl[::-1]],
                              ov.bucket_bytes)
        plan_repl = [[repl[::-1][j] for j in bk] for bk in raw]
        self._bucket_sigs = (
            [OV.bucket_signature([leaves[i] for i in bk])
             for bk in plan_repl]
            + [OV.bucket_signature([leaves[i]])
               for i, d in enumerate(dims) if d is not None])

        opt = copy.copy(optimizer)
        opt.clip_axes = ("dp",)  # shard-local sq-sums need the psum
        health_mode = health
        has_dropout = cfg.dropout > 0.0 or cfg.attn_dropout > 0.0
        seed = getattr(self, "_seed", 0)

        def gather_full(shards):
            ls = jax.tree_util.tree_flatten(shards)[0]
            full = [l if dims[i] is None
                    else jax.lax.all_gather(l, "dp", axis=dims[i],
                                            tiled=True)
                    for i, l in enumerate(ls)]
            tree = jax.tree_util.tree_unflatten(tdef, full)
            return OV.reduce_grads_on_backward(tree, ("dp",), plan_repl)

        def train_key(step):
            if not has_dropout:
                return None
            key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
            # decorrelate masks across the batch shards
            return jax.random.fold_in(key, jax.lax.axis_index("dp"))

        def local_step(params, opt_state, tokens, targets, step):
            def L(p):
                return T.loss(gather_full(p), tokens, targets, cfg,
                              dropout_key=train_key(step))

            loss, grads = jax.value_and_grad(L)(params)
            # local losses are means over B/dp rows: mean-of-means is
            # the global mean, and the summed cotangents carry a dp
            # factor the global gradient does not
            grads = tree_map(lambda g: g / dp, grads)
            loss = jax.lax.pmean(loss, "dp")
            if health_mode == "off":
                new_p, new_s = opt.step(params, grads, opt_state)
                return new_p, new_s, loss
            from shallowspeed_tpu.telemetry.health import (grad_health,
                                                           spec_axes,
                                                           update_health)

            gax = spec_axes(specs)
            pack = grad_health(params, grads, grad_axes=gax,
                               param_axes=gax)
            if health_mode == "guard":
                ok = pack["nonfinite"] == 0
                new_p, new_s = opt.guarded_step(params, grads,
                                                opt_state, ok)
                pack = update_health(pack, params, new_p,
                                     param_axes=gax, skipped=1 - ok)
            else:
                new_p, new_s = opt.step(params, grads, opt_state)
                pack = update_health(pack, params, new_p,
                                     param_axes=gax)
            return new_p, new_s, loss, pack

        step_out = ((specs, opt_specs, P()) if health == "off"
                    else (specs, opt_specs, P(), P()))

        @partial(jax.jit, donate_argnums=(0, 1))
        @partial(shard_map, mesh=mesh,
                 in_specs=(specs, opt_specs, P("dp"), P("dp"), P()),
                 out_specs=step_out)
        def _step(params, opt_state, tokens, targets, step):
            return local_step(params, opt_state, tokens, targets, step)

        self._step_fn = _step
        OV.register_program(_step, "dp", self._bucket_sigs,
                            engine="FSDPEngine")
