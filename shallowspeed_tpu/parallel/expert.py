"""Expert parallelism — MoE transformer over a (dp, ep) or (dp, sp, ep)
mesh (the 'sp' axis shards the sequence for long-context MoE).

The reference has no MoE / expert parallelism (SURVEY §2 checklist: EP
absent); this engine adds the family in the GSPMD style the other engines
use (`parallel/gspmd.py`): pick a mesh, annotate shardings, let XLA insert
the collectives.

Placement:
- Stacked expert weights `wi/bi/wo/bo` (leading dim E): `P('ep', ...)` —
  each device group owns `E/ep` experts.
- Router gate, attention, embeddings, layernorms: replicated.
- Batch over 'dp' (and the sequence dim over 'sp' when present).

The MoE layer's dispatch einsum (`ops/moe.py`) maps token-sharded
activations `(G, S, d)` onto the expert-sharded buffer `(E, G, C, d)`;
GSPMD lowers that resharding to the all-to-all over 'ep' that NCCL-based
MoE frameworks (DeepSpeed-MoE, Tutel) issue by hand, and schedules it
against the expert matmuls.
"""

from __future__ import annotations

from jax.sharding import Mesh, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.parallel.gspmd import GSPMDEngine


def param_specs(cfg: T.TransformerConfig) -> dict:
    """PartitionSpec pytree matching `transformer.init` with n_experts>0."""
    assert cfg.n_experts > 0
    dense = {"W": P(), "b": P()}
    ln = {"g": P(), "b": P()}
    moe = {"gate": P(), "wi": P("ep", None, None), "bi": P("ep", None),
           "wo": P("ep", None, None), "bo": P("ep", None)}
    attn_proj = ({"q": dense, "kv": dense} if cfg.gqa
                 else {"qkv": dense})
    block = {"ln1": ln, **attn_proj, "proj": dense, "ln2": ln, "moe": moe}
    out = {
        "tok_emb": P(),
        "pos_emb": P(),
        "blocks": [block for _ in range(cfg.n_layers)],
        "ln_f": ln,
    }
    if not cfg.tie_embeddings:
        out["head"] = dense
    return out


class ExpertParallelEngine(GSPMDEngine):
    """Data x expert parallel trainer for the MoE transformer family.

    Mesh: ('dp', 'ep'), or ('dp', 'sp', 'ep') to also shard the SEQUENCE
    over 'sp' (long-context MoE). Sequence sharding is purely the batch
    annotation — the MoE program is logically global, so GSPMD reshards
    between the (batch, seq)-sharded token layout and the expert-sharded
    dispatch buffers however the mesh dictates; attention becomes the K/V
    all-gather formulation over 'sp' (as in `parallel/composite.py`).
    """

    def validate(self, cfg: T.TransformerConfig, mesh: Mesh) -> None:
        assert mesh.axis_names in (("dp", "ep"), ("dp", "sp", "ep")), (
            f"ExpertParallelEngine expects a ('dp'[,'sp'],'ep') mesh, got "
            f"{mesh.axis_names}")
        assert cfg.n_experts > 0, "ExpertParallelEngine needs n_experts > 0"
        self.sp = (mesh.devices.shape[1]
                   if len(mesh.axis_names) == 3 else 1)
        self.ep = mesh.devices.shape[-1]
        assert cfg.n_experts % self.ep == 0, (
            f"n_experts={cfg.n_experts} must be divisible by ep={self.ep}")
        assert cfg.moe_top_k <= cfg.n_experts, (
            f"moe_top_k={cfg.moe_top_k} cannot exceed "
            f"n_experts={cfg.n_experts}")

    def param_specs(self, cfg: T.TransformerConfig) -> dict:
        return param_specs(cfg)
