"""Comm/compute interleaving — bucketed overlapped gradient reduction.

The reference's headline capability is DDP-style interleaving of
communication with computation: it registers a per-parameter hook that
fires an `Iallreduce` the moment a parameter's gradient is final, so
reduction of layer i overlaps the backward of layer i-1
(`/root/reference/shallowspeed/pipe.py:302-327`). Our compiled engines
so far did the naive thing the reference improves on: accumulate the
whole gradient, then reduce — and because the accumulation `lax.scan`
is a single dataflow node, every byte of that reduction is *exposed*
(nothing independent is left to schedule under it).

This module is the compiled-XLA formulation of the same idea, shared by
every engine family:

- **Bucket plans** (`plan_buckets`): partition the grad pytree's leaves,
  in backward-finalization order, into size-targeted buckets
  (`--bucket-mb`). One bucket = ONE collective bind (a multi-operand
  `psum`), so the wire sees few right-sized collectives instead of one
  late bulk reduction or dozens of latency-bound per-leaf ones.
- **Reduce-on-backward tags** (`reduce_grads_on_backward`): a custom-VJP
  identity whose backward psums a bucket's cotangents over the data
  axes *at the point the bucket's last leaf gradient is produced* —
  inside the autodiff backward, the compiled equivalent of the
  reference's grad hooks. An optional `acc` (the unreduced sum of
  earlier microbatches from a peeled accumulation scan) is folded in
  before the reduction, so total wire bytes match the bulk path
  exactly. Engines with hand-written backwards (the MLP family) place
  the same per-bucket psums directly between layer VJPs
  (`bucketed_stage_backward`).
- **Scatter tags** (`scatter_grads_on_backward`): the ZeRO-2 flavor —
  the backward emits a per-leaf `psum_scatter` over 'dp' (half an
  all-reduce's bytes), embedded at the leaf's local shard slot, so the
  sharded-optimizer path reduces inside the backward too.
- **Exposure accounting** (`collective_exposure`): a dataflow measure
  of how much collective traffic a compiled program can hide — a
  collective is *overlapped* when the same scope contains MXU-heavy
  compute that neither feeds it nor depends on it (exactly what XLA's
  latency-hiding scheduler needs to run them concurrently), *exposed*
  otherwise. `exposed_comm_frac` = exposed bytes / total collective
  bytes; telemetry stamps it on every step line (schema v3) and the
  `overlap-bucket` analysis rule fails a registered program whose
  bucket collectives have no independent compute to hide under.
- **Registry** (`register_program`): engines that build an overlapped
  program record its bucket signatures on the jitted fn; the analysis
  rule then proves every grad-sized dp reduction in the program is a
  registered bucket and that the interleaving dataflow actually exists.

Double-buffered p2p hops (the pipeline-engine side of the same trade —
send the previous tick's activation while computing the current one,
`SPMDPipelineEngine(overlap=...)`) live in `spmd_pipeline.py`; the ring
attention path already carries its hop and its chunk compute as
independent dataflow (`ops/attention.py`), which this module's exposure
accounting now verifies instead of assuming.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from shallowspeed_tpu.analysis.walker import _as_jaxpr, aval_bytes, sub_jaxprs

tree_map = jax.tree_util.tree_map

MiB = float(1 << 20)


# ------------------------------------------------------------ config


@dataclass(frozen=True)
class OverlapConfig:
    """Per-engine comm/compute interleaving knobs.

    bucket_mb: target bucket payload (reference `pipe.py` bucketing
    semantics: a bucket closes when adding the next leaf would exceed
    the target; a single oversized leaf gets its own bucket).
    double_buffer_hops: pipeline engines only — defer each stage hop
    one tick so the `ppermute` of tick t's output overlaps tick t+1's
    compute (costs pp-1 extra warmup/drain ticks, removes the hop from
    the per-tick critical path)."""

    bucket_mb: float = 4.0
    double_buffer_hops: bool = True

    @property
    def bucket_bytes(self) -> int:
        return max(1, int(self.bucket_mb * MiB))


def from_flags(overlap: str, bucket_mb: float) -> OverlapConfig | None:
    """Driver-flag adapter: `--overlap off|on` + `--bucket-mb`."""
    if overlap == "off":
        return None
    return OverlapConfig(bucket_mb=bucket_mb)


# ------------------------------------------------------- bucket plans


def leaf_bytes(leaf) -> int:
    """Payload bytes of one array-ish leaf (arrays, SDS, avals)."""
    shape = getattr(leaf, "shape", ())
    dtype = np.dtype(getattr(leaf, "dtype", np.float32))
    return int(np.prod(shape, dtype=np.int64)) * dtype.itemsize if shape \
        else dtype.itemsize


def plan_buckets(leaves, bucket_bytes: int) -> list[list[int]]:
    """Partition leaf indices into contiguous buckets of at most
    `bucket_bytes` each, IN THE ORDER GIVEN — callers pass leaves in
    backward-finalization order (the last layer's grads are final
    first). Every index lands in exactly one bucket; a single leaf
    larger than the target gets a bucket of its own (the reference's
    bucketing does the same — you cannot split a tensor's allreduce)."""
    buckets: list[list[int]] = []
    cur: list[int] = []
    cur_b = 0
    for i, leaf in enumerate(leaves):
        b = leaf_bytes(leaf)
        if cur and cur_b + b > bucket_bytes:
            buckets.append(cur)
            cur, cur_b = [], 0
        cur.append(i)
        cur_b += b
    if cur:
        buckets.append(cur)
    return buckets


def plan_param_buckets(params, bucket_bytes: int):
    """Bucket plan for a params pytree, in backward-finalization order
    (reversed flatten order — autodiff finalizes the deepest layers'
    cotangents first). Returns (plan, leaves, treedef): `plan` indexes
    into the ORIGINAL flatten order."""
    leaves, tdef = jax.tree_util.tree_flatten(params)
    n = len(leaves)
    rev = plan_buckets(leaves[::-1], bucket_bytes)
    plan = [[n - 1 - j for j in bucket] for bucket in rev]
    return plan, leaves, tdef


# ------------------------------------------- reduce-on-backward tags

# Identity forward, per-bucket psum backward: applied to the params a
# loss is differentiated against, the transpose runs when ALL the
# bucket's cotangents are final — for a bucket of layer-i leaves,
# right after layer i's backward matmuls, dataflow-independent of the
# backward of layers < i. `acc` (unreduced grads of earlier
# microbatches, from a peeled accumulation scan) is folded in BEFORE
# the psum so wire bytes equal the bulk path's. On pre-VMA jax this is
# the tree/bucket generalization of `utils.tp_region_enter`; on VMA
# jax variance typing transposes the same way (the psum re-types the
# varying cotangents invariant, which is what the callers' out_specs
# declare).


@partial(jax.custom_vjp, nondiff_argnums=(0,))
def _reduce_tag(axes, leaves, acc):
    return leaves


def _reduce_tag_fwd(axes, leaves, acc):
    return leaves, acc


def _reduce_tag_bwd(axes, acc, g):
    if acc is not None:
        g = tuple(jnp.add(a, b) for a, b in zip(g, acc))
    g = jax.lax.psum(g, axes)  # ONE multi-operand bind = one collective
    zeros = None if acc is None else tuple(jnp.zeros_like(a) for a in acc)
    return (g, zeros)


_reduce_tag.defvjp(_reduce_tag_fwd, _reduce_tag_bwd)


def reduce_grads_on_backward(params, axes, plan, acc=None):
    """Tag `params` so differentiating through the tagged tree reduces
    each bucket's cotangents over `axes` inside the backward. `plan`
    indexes the tree's flatten order (`plan_param_buckets`); leaves not
    covered by any bucket pass through untagged (caller reduces them)."""
    leaves, tdef = jax.tree_util.tree_flatten(params)
    acc_leaves = (None if acc is None
                  else jax.tree_util.tree_flatten(acc)[0])
    out = list(leaves)
    for bucket in plan:
        sub = tuple(leaves[i] for i in bucket)
        sub_acc = (None if acc_leaves is None
                   else tuple(acc_leaves[i] for i in bucket))
        tagged = _reduce_tag(tuple(axes), sub, sub_acc)
        for slot, i in enumerate(bucket):
            out[i] = tagged[slot]
    return jax.tree_util.tree_unflatten(tdef, out)


# ------------------------------------------------------ scatter tags


@partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _scatter_tag(axis, extra_axes, dims, leaves, acc):
    return leaves


def _scatter_tag_fwd(axis, extra_axes, dims, leaves, acc):
    return leaves, acc


def _scatter_tag_bwd(axis, extra_axes, dims, acc, g):
    if acc is not None:
        g = tuple(jnp.add(a, b) for a, b in zip(g, acc))
    if extra_axes:
        # e.g. 'sp' in the (dp, sp) context mesh: full-sum the data
        # axes the scatter does not cover (one multi-operand bind)
        g = jax.lax.psum(g, tuple(extra_axes))
    size = jax.lax.psum(1, axis)
    idx = jax.lax.axis_index(axis)
    out = []
    for gl, dim in zip(g, dims):
        if dim is None:
            out.append(jax.lax.psum(gl, axis))
            continue
        shard = jax.lax.psum_scatter(gl, axis, scatter_dimension=dim,
                                     tiled=True)
        # cotangent shape must match the primal: embed the reduced
        # shard at this device's slot (zeros elsewhere); the caller
        # slices it back out after value_and_grad — free data motion,
        # and the reduce-scatter itself ran inside the backward.
        full = jnp.zeros_like(gl)
        start = [0] * gl.ndim
        start[dim] = idx * (gl.shape[dim] // size)
        out.append(jax.lax.dynamic_update_slice(full, shard, start))
    zeros = None if acc is None else tuple(jnp.zeros_like(a) for a in acc)
    return (tuple(out), zeros)


_scatter_tag.defvjp(_scatter_tag_fwd, _scatter_tag_bwd)


def scatter_grads_on_backward(params, axis, dims, plan, acc=None,
                              extra_axes=()):
    """ZeRO-2 flavor of `reduce_grads_on_backward`: each bucket's
    backward emits per-leaf `psum_scatter` over `axis` (dims[i] = the
    leaf's scatter dimension, None = plain psum), after an optional
    full psum over `extra_axes`. The cotangents come back full-shaped
    with the reduced shard embedded at this device's slot — slice with
    `take_local_shard` after `value_and_grad`."""
    leaves, tdef = jax.tree_util.tree_flatten(params)
    acc_leaves = (None if acc is None
                  else jax.tree_util.tree_flatten(acc)[0])
    out = list(leaves)
    for bucket in plan:
        sub = tuple(leaves[i] for i in bucket)
        sub_acc = (None if acc_leaves is None
                   else tuple(acc_leaves[i] for i in bucket))
        sub_dims = tuple(dims[i] for i in bucket)
        tagged = _scatter_tag(axis, tuple(extra_axes), sub_dims, sub,
                              sub_acc)
        for slot, i in enumerate(bucket):
            out[i] = tagged[slot]
    return jax.tree_util.tree_unflatten(tdef, out)


def take_local_shard(leaf, dim, axis):
    """Slice this device's shard back out of an embedded-scatter
    cotangent (see `_scatter_tag_bwd`); identity for dim=None."""
    if dim is None:
        return leaf
    size = jax.lax.psum(1, axis)
    idx = jax.lax.axis_index(axis)
    shard = leaf.shape[dim] // size
    start = [0] * leaf.ndim
    start[dim] = idx * shard
    return jax.lax.dynamic_slice(
        leaf, start, [s if d != dim else shard
                      for d, s in enumerate(leaf.shape)])


# ------------------------------------- hand-written-backward variant


class BucketEmitter:
    """Interleaved-reduction bookkeeping for hand-written backwards:
    `add` each leaf's finalized (accumulated) gradient as the layer
    loop produces it; the moment a bucket's leaves are all present,
    ONE multi-operand psum over `axes` is emitted right there in the
    traced program — between that layer's and the next (earlier)
    layer's backward matmuls, so the collective's dataflow is
    independent of the remaining backward."""

    def __init__(self, plan, axes):
        self._remaining = [set(b) for b in plan]
        self._axes = tuple(axes)
        self._pending: dict[int, Any] = {}
        self.reduced: dict[int, Any] = {}

    def add(self, leaf_id: int, val) -> None:
        self._pending[leaf_id] = val
        self._flush()

    def _flush(self) -> None:
        have = set(self._pending)
        for bi, need in enumerate(self._remaining):
            if need and need <= have:
                ids = sorted(need, reverse=True)  # finalization order
                red = jax.lax.psum(
                    tuple(self._pending[i] for i in ids), self._axes)
                for i, r in zip(ids, red):
                    self.reduced[i] = r
                    del self._pending[i]
                self._remaining[bi] = set()

    def done(self) -> dict:
        self._flush()
        assert not self._pending, sorted(self._pending)
        return self.reduced


def bucketed_stage_backward(stage, params, stash, dout, acc, plan,
                            axes):
    """`MLPStage.backward` with the DP reduction interleaved: after
    layer i's (dW, db) are computed and folded into the peeled-scan
    accumulator, every bucket completed so far is psum'd RIGHT THERE —
    between layer i's and layer i-1's backward matmuls in the traced
    program, so each bucket collective is dataflow-independent of the
    remaining backward (the compiled equivalent of the reference's
    per-parameter `Iallreduce` hooks, `pipe.py:302-327`).

    `plan` buckets leaf ids in finalization order, leaf id 2*i = layer
    i's W, 2*i+1 = its b (from `mlp_leaf_order`). Returns the fully
    reduced grads pytree (same structure as `params`)."""
    from shallowspeed_tpu.ops import functional as F

    if stage.is_last_stage:
        head = stash[-1]
        dout = F.mse_loss_grad(head["probs"], dout, stage.batch_size)
        dout = F.softmax_grad(dout, head["logits"])
    n = stage.n_linears
    em = BucketEmitter(plan, axes)
    for i in range(n - 1, -1, -1):
        entry = stash[i]
        if "mask" in entry:
            dout = F.relu_grad(dout, entry["mask"])
        dout, dw, db = F.linear_grad(dout, entry["x"], params[i]["W"])
        em.add(2 * i, acc[i]["W"] + dw)
        em.add(2 * i + 1, acc[i]["b"] + db)
    reduced = em.done()
    return [{"W": reduced[2 * i], "b": reduced[2 * i + 1]}
            for i in range(n)]


def mlp_leaf_order(params) -> list:
    """The MLP family's leaves in backward-finalization order (layer
    n-1 first, W before b within a layer), with leaf id 2*i / 2*i+1 —
    the order `plan_buckets` should see and the id convention
    `bucketed_stage_backward` consumes."""
    order = []
    for i in range(len(params) - 1, -1, -1):
        order.append((2 * i, params[i]["W"]))
        order.append((2 * i + 1, params[i]["b"]))
    return order


# ------------------------------------------------- program registry


def register_program(fn, axis: str, buckets: list, engine: str = "") \
        -> None:
    """Record an overlapped program's bucket layout on its jitted fn:
    `buckets` is a list of signature groups, one per reduction
    collective the program should emit, each a tuple of (shape, dtype
    str) per operand. The `overlap-bucket` analysis rule reads this to
    prove every grad-sized dp reduction in the program is a registered
    bucket and that the interleaving dataflow exists."""
    info = {"axis": axis, "engine": engine,
            "buckets": [tuple(b) for b in buckets]}
    try:
        fn._overlap_info = info
    except AttributeError:  # exotic callables: fall back to a registry
        _FALLBACK.append((fn, info))


_FALLBACK: list = []


def registered(fn):
    info = getattr(fn, "_overlap_info", None)
    if info is not None:
        return info
    for f, i in _FALLBACK:
        if f is fn:
            return i
    return None


def bucket_signature(leaves) -> tuple:
    """Signature group of one reduction collective: the sorted
    (shape, dtype) multiset of its operands."""
    return tuple(sorted(
        (tuple(getattr(l, "shape", ())),
         str(np.dtype(getattr(l, "dtype", np.float32))))
        for l in leaves))


# -------------------------------------------- exposure accounting

# The reduction/collective primitive sets (psum_scatter traces as
# either name depending on the path; ppermute is the pipeline/ring
# hop; all_gather is FSDP's param prefetch).
REDUCE_PRIMS = {"psum", "psum_scatter", "reduce_scatter"}
COMM_PRIMS = REDUCE_PRIMS | {"ppermute", "all_gather", "all_to_all",
                             "pbroadcast", "pgather"}

_AXIS_PARAM = {"psum": "axes", "pgather": "axes", "pbroadcast":
               "axis_name", "ppermute": "axis_name", "all_gather":
               "axis_name", "reduce_scatter": "axis_name",
               "psum_scatter": "axis_name", "all_to_all": "axis_name"}


def eqn_axes(eqn) -> tuple:
    axes = eqn.params.get(_AXIS_PARAM.get(eqn.primitive.name, "axes"))
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _operand_bytes(eqn) -> int:
    return sum(aval_bytes(v.aval) for v in eqn.invars
               if not isinstance(v, jax.core.Literal))


def _eqn_is_heavy(eqn, cache: dict) -> bool:
    """MXU-heavy: a dot_general/conv, or a sub-jaxpr (scan, pjit,
    remat, ...) containing one — the compute a collective can hide
    under."""
    name = eqn.primitive.name
    if name in ("dot_general", "conv_general_dilated"):
        return True
    subs = sub_jaxprs(eqn)
    if not subs:
        return False
    key = id(eqn)
    if key not in cache:
        cache[key] = any(
            _eqn_is_heavy(e, cache)
            for s in subs for e in _as_jaxpr(s).eqns)
    return cache[key]


def _scope_overlap(jaxpr, trips: int, acc: dict, cache: dict,
                   axes_filter=None):
    """One scope's collectives classified overlapped/exposed by
    dataflow: a collective is overlapped when some heavy eqn in the
    SAME scope neither feeds it nor depends on it (XLA's latency-hiding
    scheduler can then run them concurrently); exposed otherwise.
    Conservative across scopes: a collective only overlaps with compute
    it shares a scope with."""
    j = _as_jaxpr(jaxpr)
    eqns = j.eqns
    prod: dict[int, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            prod[id(v)] = i
    anc = [0] * len(eqns)  # ancestor bitsets over eqn indices
    for i, eqn in enumerate(eqns):
        m = 0
        for v in eqn.invars:
            if isinstance(v, jax.core.Literal):
                continue
            jdx = prod.get(id(v))
            if jdx is not None:
                m |= anc[jdx] | (1 << jdx)
        anc[i] = m
    heavy = [i for i, eqn in enumerate(eqns)
             if _eqn_is_heavy(eqn, cache)]
    for i, eqn in enumerate(eqns):
        name = eqn.primitive.name
        if name in COMM_PRIMS:
            if axes_filter is not None and not (
                    set(eqn_axes(eqn)) & set(axes_filter)):
                continue
            nbytes = _operand_bytes(eqn) * trips
            overlappable = any(
                h != i and not (anc[i] >> h) & 1
                and not (anc[h] >> i) & 1 for h in heavy)
            acc["total"] += nbytes
            acc["n"] += 1
            if overlappable:
                acc["overlapped"] += nbytes
                acc["n_overlapped"] += 1
            else:
                acc["exposed"] += nbytes
            continue
        subs = sub_jaxprs(eqn)
        if not subs:
            continue
        t = trips
        if name == "scan":
            n = eqn.params.get("length")
            if n is None:
                acc["approx"] = True
                n = 1
            t = trips * int(n)
        elif name in ("while", "cond"):
            acc["approx"] = True
        for s in subs:
            _scope_overlap(s, t, acc, cache, axes_filter)


def collective_exposure(closed, axes=None) -> dict:
    """Dataflow exposure of one traced program (a ClosedJaxpr):
    per-step collective bytes split into overlapped (independent heavy
    compute exists in the same scope) and exposed. `axes` restricts the
    accounting to collectives touching those mesh axes (None = all).

    Bytes follow `telemetry.collectives`' convention (local operand
    payload × loop trips). `exposed_comm_frac` is None when the program
    has no (matching) collectives — GSPMD-partitioned programs' compiler-
    inserted collectives are invisible at jaxpr level, and a fraction of
    nothing would read as perfect overlap."""
    acc = {"total": 0, "exposed": 0, "overlapped": 0, "n": 0,
           "n_overlapped": 0, "approx": False}
    _scope_overlap(closed.jaxpr, 1, acc, {}, axes)
    frac = (acc["exposed"] / acc["total"]) if acc["total"] else None
    return {
        "total_bytes": acc["total"],
        "exposed_bytes": acc["exposed"],
        "overlapped_bytes": acc["overlapped"],
        "n_collectives": acc["n"],
        "n_overlapped": acc["n_overlapped"],
        "exposed_comm_frac": None if frac is None else round(frac, 6),
        "overlap_ratio": None if frac is None else round(1.0 - frac, 6),
        "approximate": acc["approx"],
    }
