"""3-D composite parallelism: data x sequence x tensor over one mesh.

The reference composes exactly two axes (DP x PP,
`/root/reference/train.py:87-94`); production frameworks compose three or
more. This engine trains the transformer family over a single 3-axis
`Mesh(('dp', 'sp', 'tp'))`:

- **dp**: batch dimension sharded; gradient all-reduce inferred by GSPMD.
- **sp**: sequence dimension of the token batch sharded. Activations stay
  sequence-sharded through layernorms/FFNs; for attention GSPMD
  all-gathers K/V over 'sp' while queries stay sharded — the
  all-gather formulation of context parallelism (the ring formulation
  lives in `parallel/context.py`; same math, different collective).
- **tp**: Megatron placement reused verbatim from `parallel/tensor.py` —
  qkv/up column-sharded, proj/down row-sharded, one inferred all-reduce
  per block.

Everything is annotation: the model code is untouched, the training step
is the shared GSPMD jitted step, and XLA schedules/overlaps the three
axes' collectives jointly — which is the point of doing this under one
mesh instead of nesting engines. Optional `fsdp=True` additionally shards
every leaf's largest free dimension over 'dp' (ZeRO-3, `parallel/
fsdp.py`), stacking sharded-state data parallelism on top: a full
3-D + ZeRO configuration from pure placement decisions.
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.parallel import tensor as tp_mod
from shallowspeed_tpu.parallel.fsdp import add_dp
from shallowspeed_tpu.parallel.gspmd import GSPMDEngine

tree_map = jax.tree_util.tree_map


class Composite3DEngine(GSPMDEngine):
    """dp x sp x tp trainer (optionally + ZeRO-3 parameter sharding)."""

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 seed: int = 0, zero1: bool = False, fsdp: bool = False,
                 zero2: bool = False, health: str = "off"):
        if fsdp and (zero1 or zero2):
            raise ValueError("fsdp already shards the optimizer state; "
                             "drop zero1/zero2")
        self.fsdp = fsdp
        super().__init__(cfg, optimizer, mesh, seed=seed, zero1=zero1,
                         zero2=zero2, health=health)

    def validate(self, cfg: T.TransformerConfig, mesh: Mesh) -> None:
        assert mesh.axis_names == ("dp", "sp", "tp"), (
            f"Composite3DEngine expects a ('dp','sp','tp') mesh, got "
            f"{mesh.axis_names}")
        self.sp = mesh.devices.shape[1]
        self.tp = mesh.devices.shape[2]
        assert cfg.n_heads % self.tp == 0, (
            f"n_heads={cfg.n_heads} must be divisible by tp={self.tp}")
        assert (4 * cfg.d_model) % self.tp == 0
        assert cfg.n_experts == 0, (
            "Composite3DEngine shards the dense FFN; MoE composes with "
            "dp/ep (parallel/expert.py)")

    def param_specs(self, cfg: T.TransformerConfig) -> dict:
        specs = tp_mod.param_specs(cfg)
        if not self.fsdp:
            return specs
        dp = self.mesh.devices.shape[0]
        return tree_map(
            lambda a, s: add_dp(s, a.shape, dp),
            self._params_host, specs,
            is_leaf=lambda x: isinstance(x, P))

    # batch_spec/_place: the GSPMDEngine base keys sequence sharding off
    # `self.sp` (set in validate), so no overrides are needed here
