"""The pipeline VM instruction set (ISA) — pure data, no side effects.

Capability parity with the reference's 11 instruction dataclasses
(`/root/reference/shallowspeed/pipe.py:12-138`). Schedules emit these;
executors interpret them. Keeping the ISA as plain dataclasses is what makes
schedules unit-testable with zero devices (SURVEY §4.3) and gives later
executors (the fused SPMD pipeline) a stable seam.

TPU semantics differences from the reference (documented per instruction):
- Send/Recv pairs are realised as device-to-device array transfers
  (`jax.device_put` across stage shardings) from a single controller — the
  dispatch is asynchronous, so unlike the reference's blocking `MPI.Send`
  (`pipe.py:41-77` docstrings flag that limitation) the transfer overlaps
  with subsequent compute dispatch.
- BackwardGradAllReduce's interleaved per-parameter `Iallreduce`
  (`pipe.py:108-115`) becomes a single bucketed `lax.psum` over the `dp`
  mesh axis of the whole accumulated gradient pytree — the bucketing that the
  reference's own docstring (`pipe.py:309-310`) names as the known
  improvement; XLA's latency-hiding scheduler overlaps it with compute.
"""

from __future__ import annotations

from dataclasses import dataclass

__all__ = [
    "PipeInstr", "ZeroGrad", "OptimizerStep", "BufferPipeInstr",
    "RecvActivations", "SendActivations", "RecvOutputGrad", "SendInputGrad",
    "MuBatchPipeInstr", "Forward", "BackwardGradAcc", "BackwardGradAllReduce",
    "LoadInstruction", "LoadMuBatchInput", "LoadMuBatchTarget",
]


class PipeInstr:
    """Base of the ISA (`pipe.py:12-13`)."""


@dataclass
class ZeroGrad(PipeInstr):
    """Reset the gradient accumulator — starts a new accumulation phase
    (`pipe.py:16-23`)."""


@dataclass
class OptimizerStep(PipeInstr):
    """Apply the optimizer to (params, accumulated grads) (`pipe.py:26-32`)."""


@dataclass
class BufferPipeInstr(PipeInstr):
    buffer_id: int


@dataclass
class RecvActivations(BufferPipeInstr):
    """Receive activations from the previous stage into an input buffer
    (`pipe.py:40-47`)."""


@dataclass
class SendActivations(BufferPipeInstr):
    """Send this stage's forward output to the next stage (`pipe.py:50-57`)."""


@dataclass
class RecvOutputGrad(BufferPipeInstr):
    """Receive d(loss)/d(output) from the next stage into an output buffer
    (`pipe.py:60-67`)."""


@dataclass
class SendInputGrad(BufferPipeInstr):
    """Send d(loss)/d(input) to the previous stage (`pipe.py:70-77`)."""


@dataclass
class MuBatchPipeInstr(PipeInstr):
    buffer_id: int
    mubatch_id: int


@dataclass
class Forward(MuBatchPipeInstr):
    """Stage forward on one microbatch; stash activations under mubatch_id
    (`pipe.py:86-93`)."""


@dataclass
class BackwardGradAcc(MuBatchPipeInstr):
    """Stage backward on one microbatch; sum-accumulate grads locally
    (`pipe.py:96-104`)."""


@dataclass
class BackwardGradAllReduce(MuBatchPipeInstr):
    """Like BackwardGradAcc, then reduce the accumulated grads across the
    `dp` mesh axis (`pipe.py:107-115`; see module docstring for the psum
    bucketing semantics)."""


@dataclass
class LoadInstruction(MuBatchPipeInstr):
    """Base for host-data loads; executors pass the current batch_id
    (`pipe.py:118-120`, `pipe.py:456-462`)."""


@dataclass
class LoadMuBatchInput(LoadInstruction):
    """Load microbatch inputs X into an input buffer (`pipe.py:123-129`)."""


@dataclass
class LoadMuBatchTarget(LoadInstruction):
    """Load microbatch targets y into an output buffer (`pipe.py:132-138`)."""
