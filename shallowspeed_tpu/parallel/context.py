"""Context/sequence parallelism — long-context training over a (dp, sp) mesh.

The reference cannot scale sequence length at all (SURVEY §5: no sequence
dimension anywhere). This engine makes long context a first-class axis the
TPU way: shard the *sequence* over the `sp` mesh axis, run `ring_attention`
(K/V blocks rotating over ICI via `ppermute`,
`shallowspeed_tpu/ops/attention.py`) so no device ever materializes the full
(T, T) score matrix or the full sequence's activations, and compose with
batch sharding over `dp` in the same `shard_map`:

- tokens/targets: (B, T) sharded (dp, sp) — each device holds a
  (B/dp, T/sp) tile.
- params: replicated; every device computes the gradient contribution of its
  tile and one `pmean` over ('dp', 'sp') recovers the exact global-mean
  gradient (all tiles are equal-sized, so mean-of-means is exact — the same
  scaling invariant the MLP family inherits from the reference,
  `functional.py:43-44`).
- autograd: `jax.grad` straight through the ring collective (JAX
  differentiates `ppermute`), so the backward pass runs the ring in reverse
  automatically.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.ops.attention import ring_attention, ulysses_attention


class ContextParallelEngine:
    """Data x sequence parallel trainer for the transformer LM family.

    `attn` selects the attention substrate:
    - "ring" (default): `ring_attention` over the 'sp' axis — correct for
      any sp, O(T_local) memory, n ppermute hops.
    - "ulysses": `ulysses_attention` — all-to-all head<->sequence
      re-sharding around one fused full-attention program; needs
      n_heads % sp == 0.
    - "ulysses-flash": same all-to-all re-sharding, but the local
      attention is the fused Pallas flash kernel — sequence parallelism
      AND the flash kernel's O(T) memory / fused softmax in one path.
    - "flash": the fused Pallas flash kernel
      (`ops/flash_attention.py`) — sp must be 1 (sequence unsharded);
      fastest single-device path on TPU.
    """

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 seed: int = 0, attn: str = "ring", zero1: bool = False):
        assert mesh.axis_names == ("dp", "sp")
        self.cfg = cfg
        self.mesh = mesh
        self.dp, self.sp = mesh.devices.shape
        self.optimizer = optimizer
        self.rep = NamedSharding(mesh, P())
        self.tile = NamedSharding(mesh, P("dp", "sp"))

        self.params = jax.device_put(T.init(cfg, seed), self.rep)
        self.opt_state = jax.device_put(optimizer.init(self.params), self.rep)

        opt = optimizer
        if attn == "flash":
            from shallowspeed_tpu.ops.flash_attention import flash_attention

            assert self.sp == 1, "--attn flash requires sp=1 (use ring)"
            attn = partial(flash_attention, causal=True)
        elif attn in ("ulysses", "ulysses-flash"):
            assert cfg.n_heads % self.sp == 0, (
                f"--attn {attn} needs n_heads ({cfg.n_heads}) divisible by "
                f"sp ({self.sp}); use ring")
            attn = partial(ulysses_attention, axis_name="sp", causal=True,
                           use_flash=attn == "ulysses-flash")
        else:
            attn = partial(ring_attention, axis_name="sp", causal=True)

        def local_loss(params, tokens, targets):
            t_local = tokens.shape[1]
            off = jax.lax.axis_index("sp") * t_local
            return T.loss(params, tokens, targets, cfg,
                          attn_fn=attn, pos_offset=off)

        n_tiles = self.dp * self.sp

        def loss_and_grads(params, tokens, targets):
            # Params are mesh-invariant (replicated), the per-tile loss is
            # varying: jax.grad's transpose of that broadcast IS a psum over
            # ('dp','sp') — the gradient arrives already summed across tiles.
            # Scaling the local loss by 1/n_tiles therefore yields exactly
            # the global-mean gradient (equal tiles => mean of means), with
            # the DP all-reduce emitted by autodiff instead of hand-placed
            # (the XLA-native version of the reference's interleaved
            # Iallreduce, `pipe.py:302-327`).
            def scaled(p):
                return local_loss(p, tokens, targets) / n_tiles

            lloc, grads = jax.value_and_grad(scaled)(params)
            return jax.lax.pmean(lloc * n_tiles, ("dp", "sp")), grads

        if zero1:
            from shallowspeed_tpu.parallel.zero import (
                make_zero1_update, shard_state_zero1)

            @jax.jit
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P("dp", "sp"), P("dp", "sp")),
                     out_specs=(P(), P()))
            def _loss_grads(params, tokens, targets):
                # ZeRO-1 grad program: the grads leave the shard_map
                # already psum'd (invariant), ready for the dp-sharded
                # optimizer update.
                return loss_and_grads(params, tokens, targets)

            self.opt_state = shard_state_zero1(self.opt_state, mesh)
            self._loss_grads_fn = _loss_grads
            self._update_fn = make_zero1_update(
                opt, self.params, self.opt_state)
            self._step_fn = None
        else:

            @partial(jax.jit, donate_argnums=(0, 1))
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P(), P("dp", "sp"), P("dp", "sp")),
                     out_specs=(P(), P(), P()))
            def _step(params, opt_state, tokens, targets):
                loss, grads = loss_and_grads(params, tokens, targets)
                params, opt_state = opt.step(params, grads, opt_state)
                return params, opt_state, loss

            self._step_fn = _step

        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P("dp", "sp"), P("dp", "sp")),
                 out_specs=P())
        def _eval(params, tokens, targets):
            return jax.lax.pmean(
                local_loss(params, tokens, targets), ("dp", "sp"))

        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P("dp", "sp")), out_specs=P("dp", "sp"))
        def _logits(params, tokens):
            t_local = tokens.shape[1]
            off = jax.lax.axis_index("sp") * t_local
            return T.forward(params, tokens, cfg, attn_fn=attn,
                             pos_offset=off)

        self._eval_fn = _eval
        self._logits_fn = _logits

    # -------------------------------------------------------------- data

    def _place(self, arr: np.ndarray):
        # multi-host: arr is this process's local rows (place_global
        # stitches the global array); single-process: the global batch
        from shallowspeed_tpu.distributed import place_global

        b, t = arr.shape[:2]
        # local rows x processes = global batch; it must divide over dp
        assert (b * jax.process_count()) % self.dp == 0, (b, self.dp)
        assert t % self.sp == 0, (t, self.sp)
        assert t <= self.cfg.max_seq, (
            f"global sequence length {t} exceeds max_seq={self.cfg.max_seq}")
        return place_global(arr, self.tile)

    # -------------------------------------------------------------- steps

    def place(self, arr) -> jax.Array:
        """Public placement hook for prefetch pipelines."""
        return self._place(arr)

    def train_batch_async(self, tokens, targets) -> jax.Array:
        """One optimizer step; loss as a lazy device scalar (no host sync —
        `float()` it only at log points; see `data/prefetch.py`)."""
        if self._step_fn is None:  # ZeRO-1: grad program + sharded update
            loss, grads = self._loss_grads_fn(
                self.params, self._place(tokens), self._place(targets))
            self.params, self.opt_state = self._update_fn(
                self.params, grads, self.opt_state)
            return loss
        self.params, self.opt_state, loss = self._step_fn(
            self.params, self.opt_state,
            self._place(tokens), self._place(targets))
        return loss

    def train_batch(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        """One optimizer step on a (B, T) int token batch; returns the loss."""
        return float(self.train_batch_async(tokens, targets))

    def eval_loss(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        return float(self._eval_fn(
            self.params, self._place(tokens), self._place(targets)))

    def logits(self, tokens: np.ndarray) -> jax.Array:
        return self._logits_fn(self.params, self._place(tokens))

    # -------------------------------------------- checkpoint interface

    def get_canonical_params(self):
        return self.params

    def set_canonical_params(self, params):
        self.params = jax.device_put(params, self.rep)

    def set_opt_state(self, state):
        from shallowspeed_tpu.parallel.zero import replace_opt_state

        self.opt_state = replace_opt_state(self, state)
