"""Context/sequence parallelism — long-context training over a (dp, sp) mesh.

The reference cannot scale sequence length at all (SURVEY §5: no sequence
dimension anywhere). This engine makes long context a first-class axis the
TPU way: shard the *sequence* over the `sp` mesh axis, run `ring_attention`
(K/V blocks rotating over ICI via `ppermute`,
`shallowspeed_tpu/ops/attention.py`) so no device ever materializes the full
(T, T) score matrix or the full sequence's activations, and compose with
batch sharding over `dp` in the same `shard_map`:

- tokens/targets: (B, T) sharded (dp, sp) — each device holds a
  (B/dp, T/sp) tile.
- params: replicated; every device computes the gradient contribution of its
  tile and one `pmean` over ('dp', 'sp') recovers the exact global-mean
  gradient (all tiles are equal-sized, so mean-of-means is exact — the same
  scaling invariant the MLP family inherits from the reference,
  `functional.py:43-44`).
- autograd: `jax.grad` straight through the ring collective (JAX
  differentiates `ppermute`), so the backward pass runs the ring in reverse
  automatically.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# version-compat shard_map (utils.py): VMA jax as-is; pre-VMA jax
# with the legacy replication rewriter disabled
from shallowspeed_tpu.utils import shard_map

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.ops.attention import (attention, ring_attention,
                                            ulysses_attention)
from shallowspeed_tpu.utils import pvary_over

tree_map = jax.tree_util.tree_map


def _note_step(engine, pack):
    # health.note_step, imported lazily (telemetry stays off the module
    # import path): stores last_health + device-side cumulative counters
    from shallowspeed_tpu.telemetry.health import note_step

    note_step(engine, pack)



class ContextParallelEngine:
    """Data x sequence parallel trainer for the transformer LM family.

    `attn` selects the attention substrate:
    - "ring" (default): `ring_attention` over the 'sp' axis — correct for
      any sp, O(T_local) memory, n ppermute hops.
    - "ulysses": `ulysses_attention` — all-to-all head<->sequence
      re-sharding around one fused full-attention program; needs
      n_heads % sp == 0.
    - "ulysses-flash": same all-to-all re-sharding, but the local
      attention is the fused Pallas flash kernel — sequence parallelism
      AND the flash kernel's O(T) memory / fused softmax in one path.
    - "flash": the fused Pallas flash kernel
      (`ops/flash_attention.py`) — sp must be 1 (sequence unsharded);
      fastest single-device path on TPU.
    """

    # params (hence params-shaped moments) are already in the canonical
    # checkpoint layout; placement is not structure (checkpoint.py)
    canonical_opt_identity = True

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 seed: int = 0, attn: str = "ring", zero1: bool = False,
                 zero2: bool = False, accum: int = 1,
                 health: str = "off", overlap=None):
        from shallowspeed_tpu.telemetry.health import MODES

        assert mesh.axis_names == ("dp", "sp")
        assert not (zero1 and zero2), "zero2 subsumes zero1"
        assert accum >= 1, accum
        assert health in MODES, health
        self.health = health
        self.last_health = None
        self.overlap = overlap  # parallel.overlap.OverlapConfig | None
        self.accum = accum
        self.cfg = cfg
        self.mesh = mesh
        self.dp, self.sp = mesh.devices.shape
        self.optimizer = optimizer
        self._step_count = 0
        self.rep = NamedSharding(mesh, P())
        self.tile = NamedSharding(mesh, P("dp", "sp"))

        self.params = jax.device_put(T.init(cfg, seed), self.rep)
        self.opt_state = jax.device_put(optimizer.init(self.params), self.rep)

        opt = optimizer
        # Sliding windows compose with EVERY substrate: all of them take
        # `window=` with identical semantics (`ops/attention.py` masks,
        # the flash kernel skips out-of-window tiles outright).
        w = cfg.attn_window
        if cfg.attn_dropout > 0.0:
            # probability dropout lives on the plain substrate only; at
            # sp=1 the ring degenerates to it, so swap transparently
            assert self.sp == 1 and attn == "ring", (
                "cfg.attn_dropout needs the plain XLA attention "
                "substrate (sp=1, --attn ring); fused/resharded "
                "substrates cannot mask probabilities")
            attn = partial(attention, causal=True, window=w)
        elif attn == "flash":
            from shallowspeed_tpu.ops.flash_attention import flash_attention

            assert self.sp == 1, "--attn flash requires sp=1 (use ring)"
            attn = partial(flash_attention, causal=True, window=w)
        elif attn in ("ulysses", "ulysses-flash"):
            assert cfg.n_heads % self.sp == 0, (
                f"--attn {attn} needs n_heads ({cfg.n_heads}) divisible by "
                f"sp ({self.sp}); use ring")
            assert cfg.kv_heads % self.sp == 0, (
                f"--attn {attn} with GQA needs n_kv_heads "
                f"({cfg.kv_heads}) divisible by sp ({self.sp}); use ring")
            attn = partial(ulysses_attention, axis_name="sp", causal=True,
                           window=w, use_flash=attn == "ulysses-flash")
        elif attn == "ring-flash":
            from shallowspeed_tpu.ops.flash_attention import (
                ring_flash_attention)

            # the fused kernel as the ring's local compute: no
            # (T_local, T_local) score matrix, no head-divisibility
            # constraint — works for ANY sp (unlike ulysses)
            attn = partial(ring_flash_attention, axis_name="sp",
                           causal=True, window=w)
        else:
            attn = partial(ring_attention, axis_name="sp", causal=True,
                           window=w)

        sp = self.sp

        def local_loss(params, tokens, targets, key=None, train=True):
            t_local = tokens.shape[1]
            off = jax.lax.axis_index("sp") * t_local
            if key is not None:
                # decorrelate masks across tiles: each (dp, sp) position
                # folds its mesh coordinates into the per-step key
                key = jax.random.fold_in(
                    key, jax.lax.axis_index("dp") * sp
                    + jax.lax.axis_index("sp"))
            return T.loss(params, tokens, targets, cfg,
                          attn_fn=attn, pos_offset=off, dropout_key=key,
                          train=train)

        def train_key(step):
            if cfg.dropout == 0.0 and cfg.attn_dropout == 0.0:
                return None
            return jax.random.fold_in(jax.random.PRNGKey(seed), step)

        n_tiles = self.dp * self.sp
        accum = self.accum

        def mu_split(tokens, targets):
            """(b, t) local tile -> (accum, b/accum, t) microbatch
            stacks."""
            b, t = tokens.shape
            assert b % accum == 0, (
                f"--accum {accum} must divide the per-device batch rows "
                f"({b} here = batch / dp; sp shards the sequence dim, "
                f"not rows)")
            return (tokens.reshape(accum, b // accum, t),
                    targets.reshape(accum, b // accum, t))

        def partial_grad_sum(params_v, tok_r, tgt_r, key):
            """Gradient accumulation: scan the given microbatch stack
            of the local tile, each microbatch doing its own forward
            AND backward (the standard JAX pattern — no cross-iteration
            residuals, so activation memory is one microbatch's worth
            regardless of accum). `params_v` must be pvaried so
            per-microbatch cotangents stay UNREDUCED per-tile partials;
            the caller places the cross-tile reduction after the scan
            (or folds the returned sum into the peeled last
            microbatch's in-backward bucket reduction — the overlapped
            path). Returns (loss sum over microbatches, grad sum)."""

            def body(carry, xs):
                mu, tok_mu, tgt_mu = xs
                k_mu = (None if key is None
                        else jax.random.fold_in(key, mu))
                l, g = jax.value_and_grad(
                    lambda p: local_loss(p, tok_mu, tgt_mu, k_mu))(
                        params_v)
                return (carry[0] + l,
                        tree_map(jnp.add, carry[1], g)), None

            init = pvary_over(
                (jnp.float32(0.0),
                 tree_map(lambda l: jnp.zeros_like(l, jnp.float32),
                          params_v)),
                ("dp", "sp"))
            (loss_sum, gsum), _ = jax.lax.scan(
                body, init, (jnp.arange(tok_r.shape[0]), tok_r, tgt_r))
            return loss_sum, gsum

        def tile_loss_and_gsum(params_v, tokens, targets, key):
            """(pmean'd global loss, UNREDUCED per-tile gradient sum,
            scale to apply after the cross-tile reduction) — the single
            encoding of the loss/grad scaling, shared by the dense,
            ZeRO-1, and ZeRO-2 gradient programs; each places its own
            reduction (psum vs psum_scatter) on the returned sum. The
            global-mean gradient falls out because every tile and every
            microbatch is equal-sized (mean of means is exact — the
            reference's own scaling invariant, `functional.py:43-44`;
            its interleaved Iallreduce, `pipe.py:302-327`, is here a
            single compiled reduction)."""
            if accum == 1:
                lloc, gsum = jax.value_and_grad(
                    lambda p: local_loss(p, tokens, targets, key))(
                        params_v)
                return (jax.lax.pmean(lloc, ("dp", "sp")), gsum,
                        1.0 / n_tiles)
            tok_r, tgt_r = mu_split(tokens, targets)
            loss_sum, gsum = partial_grad_sum(params_v, tok_r, tgt_r,
                                              key)
            return (jax.lax.pmean(loss_sum / accum, ("dp", "sp")), gsum,
                    1.0 / (n_tiles * accum))

        def loss_and_grads(params, tokens, targets, step):
            key = train_key(step)
            loss, gsum, scale = tile_loss_and_gsum(
                pvary_over(params, ("dp", "sp")), tokens, targets, key)
            grads = tree_map(
                lambda g: jax.lax.psum(g, ("dp", "sp")) * scale, gsum)
            return loss, grads

        # ---- overlapped gradient programs (parallel/overlap.py): the
        # cross-tile reduction moves INSIDE the backward, one bucket at
        # a time. With accum > 1 the last microbatch is peeled out of
        # the accumulation scan (a scan is one dataflow node — every
        # reduction after it is exposed) and the earlier microbatches'
        # unreduced sum is folded into each bucket's psum, so wire
        # bytes match the bulk path exactly.
        if overlap is not None:
            from shallowspeed_tpu.parallel import overlap as OV

            ov_plan, _p_leaves, _ = OV.plan_param_buckets(
                self.params, overlap.bucket_bytes)
            self._bucket_sigs = [
                OV.bucket_signature([_p_leaves[i] for i in bk])
                for bk in ov_plan]

            def tagged_loss_and_gsum(params_v, tokens, targets, key,
                                     tag):
                """tile_loss_and_gsum with the reduction tags applied
                to the (peeled) last microbatch's params: returns
                (pmean'd loss, REDUCED grad sum, scale)."""
                if accum == 1:
                    lloc, gsum = jax.value_and_grad(
                        lambda p: local_loss(tag(p, None), tokens,
                                             targets, key))(params_v)
                    return (jax.lax.pmean(lloc, ("dp", "sp")), gsum,
                            1.0 / n_tiles)
                tok_r, tgt_r = mu_split(tokens, targets)
                loss_head, acc = partial_grad_sum(
                    params_v, tok_r[:-1], tgt_r[:-1], key)
                k_last = (None if key is None
                          else jax.random.fold_in(key, accum - 1))
                l_last, gsum = jax.value_and_grad(
                    lambda p: local_loss(tag(p, acc), tok_r[-1],
                                         tgt_r[-1], k_last))(params_v)
                return (jax.lax.pmean((loss_head + l_last) / accum,
                                      ("dp", "sp")),
                        gsum, 1.0 / (n_tiles * accum))

            def loss_and_grads_ov(params, tokens, targets, step):
                def tag(p, acc):
                    return OV.reduce_grads_on_backward(
                        p, ("dp", "sp"), ov_plan, acc=acc)

                loss, gsum, scale = tagged_loss_and_gsum(
                    pvary_over(params, ("dp", "sp")), tokens, targets,
                    train_key(step), tag)
                return loss, tree_map(lambda g: g * scale, gsum)

            lag = loss_and_grads_ov
        else:
            ov_plan = None
            self._bucket_sigs = []
            lag = loss_and_grads

        health_mode = health

        def maybe_pack(params, grads, grad_specs=None):
            """The health pack for this engine's fully reduced grads:
            replicated leaves need no psum; ZeRO-2's dp-scattered
            leaves psum their statistics over the axes their spec
            shards (health.spec_axes). None with health='off'."""
            if health_mode == "off":
                return None
            from shallowspeed_tpu.telemetry.health import (grad_health,
                                                           spec_axes)

            gax = spec_axes(grad_specs) if grad_specs is not None \
                else None
            return grad_health(params, grads, grad_axes=gax)

        if zero2:
            from shallowspeed_tpu.parallel.zero import (
                make_zero1_update, shard_state_zero1, zero2_grad_specs)

            # one reduce-scatter per leaf instead of an all-reduce: grads
            # leave the program dp-SHARDED (1/dp per device), aligned
            # leaf-for-leaf with the ZeRO-1-placed moments, so the
            # optimizer update below runs fully local. The scatter dim is
            # read off the spec itself — one encoding of the placement
            # rule, no chance of divergence.
            gspecs = zero2_grad_specs(self.params, mesh)
            gdims = [next((i for i, ax in enumerate(sp) if ax == "dp"),
                          None)
                     for sp in jax.tree_util.tree_leaves(
                         gspecs, is_leaf=lambda x: isinstance(x, P))]

            z2_out = ((P(), gspecs) if health == "off"
                      else (P(), gspecs, P()))

            @jax.jit
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P("dp", "sp"), P("dp", "sp"), P()),
                     out_specs=z2_out)
            def _loss_grads(params, tokens, targets, step):
                # pvary the params: cotangents then arrive as per-tile
                # PARTIALS (no auto-psum), and the reduction is ours to
                # place — psum_scatter over 'dp'
                key = train_key(step)
                if ov_plan is not None:
                    # overlapped: the scatter tags emit each leaf's
                    # psum_scatter INSIDE the backward (embedded at the
                    # local shard slot — sliced back out below), with
                    # the peeled-scan accumulator folded in; same wire
                    # bytes, reduction interleaved with the backward
                    from shallowspeed_tpu.parallel.overlap import (
                        scatter_grads_on_backward, take_local_shard)

                    def tag(p, acc):
                        return scatter_grads_on_backward(
                            p, "dp", gdims, ov_plan, acc=acc,
                            extra_axes=("sp",))

                    loss, grads, gscale = tagged_loss_and_gsum(
                        pvary_over(params, ("dp", "sp")), tokens,
                        targets, key, tag)
                    leaves, tdef = jax.tree_util.tree_flatten(grads)
                    grads = jax.tree_util.tree_unflatten(tdef, [
                        take_local_shard(g, dim, "dp") * gscale
                        for g, dim in zip(leaves, gdims)])
                else:
                    loss, grads, gscale = tile_loss_and_gsum(
                        pvary_over(params, ("dp", "sp")), tokens,
                        targets, key)
                    leaves, tdef = jax.tree_util.tree_flatten(grads)
                    out = []
                    for g, dim in zip(leaves, gdims):
                        # unconditionally: even at sp=1 the pvaried
                        # grads are TYPED sp-varying and need the
                        # (free) psum to retype
                        g = jax.lax.psum(g, "sp")
                        if dim is None:
                            g = jax.lax.psum(g, "dp")
                        else:
                            g = jax.lax.psum_scatter(
                                g, "dp", scatter_dimension=dim,
                                tiled=True)
                        out.append(g * gscale)
                    grads = jax.tree_util.tree_unflatten(tdef, out)
                if health_mode == "off":
                    return loss, grads
                return loss, grads, maybe_pack(params, grads, gspecs)

            self.opt_state = shard_state_zero1(self.opt_state, mesh)
            self._loss_grads_fn = _loss_grads
            self._update_fn = make_zero1_update(
                opt, self.params, self.opt_state, health=health)
            self._step_fn = None
            self._run_fn = None
        elif zero1:
            from shallowspeed_tpu.parallel.zero import (
                make_zero1_update, shard_state_zero1)

            z1_out = ((P(), P()) if health == "off" else (P(), P(), P()))

            @jax.jit
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P("dp", "sp"), P("dp", "sp"), P()),
                     out_specs=z1_out)
            def _loss_grads(params, tokens, targets, step):
                # ZeRO-1 grad program: the grads leave the shard_map
                # already psum'd (invariant), ready for the dp-sharded
                # optimizer update (`lag`: bulk psums after the
                # accumulation, or in-backward bucket psums with
                # `overlap` — same contract either way).
                loss, grads = lag(params, tokens, targets, step)
                if health_mode == "off":
                    return loss, grads
                return loss, grads, maybe_pack(params, grads)

            self.opt_state = shard_state_zero1(self.opt_state, mesh)
            self._loss_grads_fn = _loss_grads
            self._update_fn = make_zero1_update(
                opt, self.params, self.opt_state, health=health)
            self._step_fn = None
            self._run_fn = None
        else:
            step_out = ((P(), P(), P()) if health == "off"
                        else (P(), P(), P(), P()))

            @partial(jax.jit, donate_argnums=(0, 1))
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P(), P("dp", "sp"), P("dp", "sp"),
                               P()),
                     out_specs=step_out)
            def _step(params, opt_state, tokens, targets, step):
                loss, grads = lag(params, tokens, targets, step)
                if health_mode == "off":
                    params, opt_state = opt.step(params, grads,
                                                 opt_state)
                    return params, opt_state, loss
                from shallowspeed_tpu.telemetry.health import (
                    update_health)

                pack = maybe_pack(params, grads)
                if health_mode == "guard":
                    ok = pack["nonfinite"] == 0
                    new_p, new_s = opt.guarded_step(params, grads,
                                                    opt_state, ok)
                    pack = update_health(pack, params, new_p,
                                         skipped=1 - ok)
                else:
                    new_p, new_s = opt.step(params, grads, opt_state)
                    pack = update_health(pack, params, new_p)
                return new_p, new_s, loss, pack

            self._step_fn = _step

            # Run fusion: a whole multi-step run as ONE XLA dispatch
            # (`lax.scan` over optimizer steps, batches HBM-resident) —
            # the transformer-family counterpart of the MLP engine's
            # `train_run` (engine.py), and the honest way to measure
            # steady-state throughput when per-dispatch latency (e.g. a
            # tunneled backend) would otherwise pollute step timing.
            @partial(jax.jit, donate_argnums=(0, 1))
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P(), P(None, "dp", "sp"),
                               P(None, "dp", "sp"), P()),
                     out_specs=(P(), P(), P()))
            def _run(params, opt_state, toks, tgts, step0):
                def body(carry, xs):
                    params, opt_state, step = carry
                    tok, tgt = xs
                    loss, grads = lag(params, tok, tgt, step)
                    params, opt_state = opt.step(params, grads, opt_state)
                    return (params, opt_state, step + 1), loss

                (params, opt_state, _), losses = jax.lax.scan(
                    body, (params, opt_state, step0), (toks, tgts))
                return params, opt_state, losses

            self._run_fn = _run

        if overlap is not None:
            from shallowspeed_tpu.parallel import overlap as OV

            if zero2:
                # the dp-axis binds here are per-leaf scatters/psums
                # (the bucket-grouped psums run over 'sp' only)
                self._bucket_sigs = [
                    OV.bucket_signature([l])
                    for l in jax.tree_util.tree_leaves(self.params)]
            fns = ([self._loss_grads_fn] if self._step_fn is None
                   else [self._step_fn, self._run_fn])
            for fn in fns:
                OV.register_program(fn, "dp", self._bucket_sigs,
                                    engine="ContextParallelEngine")

        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P("dp", "sp"), P("dp", "sp")),
                 out_specs=P())
        def _eval(params, tokens, targets):
            return jax.lax.pmean(
                local_loss(params, tokens, targets, train=False),
                ("dp", "sp"))

        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P("dp", "sp")), out_specs=P("dp", "sp"))
        def _logits(params, tokens):
            t_local = tokens.shape[1]
            off = jax.lax.axis_index("sp") * t_local
            return T.forward(params, tokens, cfg, attn_fn=attn,
                             pos_offset=off)

        if cfg.n_experts > 0:
            @jax.jit
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P("dp", "sp")), out_specs=P())
            def _router_stats(params, tokens):
                t_local = tokens.shape[1]
                off = jax.lax.axis_index("sp") * t_local
                _, _aux, st = T.forward_with_aux(
                    params, tokens, cfg, attn_fn=attn, pos_offset=off,
                    with_stats=True)
                # equal-sized tiles: pmean is the exact global average
                return tree_map(lambda v: jax.lax.pmean(v, ("dp", "sp")),
                                st)

            self._router_stats_fn = _router_stats
        else:
            self._router_stats_fn = None

        self._eval_fn = _eval
        self._logits_fn = _logits

    # -------------------------------------------------------------- data

    def _place(self, arr: np.ndarray):
        # multi-host: arr is this process's local rows (place_global
        # stitches the global array); single-process: the global batch
        from shallowspeed_tpu.distributed import place_global

        b, t = arr.shape[:2]
        # local rows x processes = global batch; it must divide over dp
        assert (b * jax.process_count()) % self.dp == 0, (b, self.dp)
        assert t % self.sp == 0, (t, self.sp)
        assert t <= self.cfg.max_seq, (
            f"global sequence length {t} exceeds max_seq={self.cfg.max_seq}")
        return place_global(arr, self.tile)

    # -------------------------------------------------------------- steps

    def place(self, arr) -> jax.Array:
        """Public placement hook for prefetch pipelines."""
        return self._place(arr)

    def train_batch_async(self, tokens, targets) -> jax.Array:
        """One optimizer step; loss as a lazy device scalar (no host sync —
        `float()` it only at log points; see `data/prefetch.py`)."""
        from shallowspeed_tpu.telemetry import tracer

        step = np.uint32(self._step_count)
        self._step_count += 1
        monitored = self.health != "off"
        with tracer().span("step", step=int(step)) as sp:
            if self._step_fn is None:  # ZeRO-1/2: grads + sharded update
                with tracer().span("grads", step=int(step)) as g:
                    out = self._loss_grads_fn(
                        self.params, self._place(tokens),
                        self._place(targets), step)
                    loss, grads = out[0], out[1]
                    g.fence(loss)
                with tracer().span("update", step=int(step)) as u:
                    if self._telemetry_eps is None \
                            and tracer().level != "off":
                        self._record_entrypoints(tokens, targets,
                                                 grads=grads)
                    if self.health == "guard":
                        self.params, self.opt_state, upd = \
                            self._update_fn(self.params, grads,
                                            self.opt_state,
                                            out[2]["nonfinite"] == 0)
                        _note_step(self, {**out[2], **upd})
                    elif monitored:
                        self.params, self.opt_state, upd = \
                            self._update_fn(self.params, grads,
                                            self.opt_state)
                        _note_step(self, {**out[2], **upd})
                    else:
                        self.params, self.opt_state = self._update_fn(
                            self.params, grads, self.opt_state)
                    u.fence(self.opt_state)
            else:
                out = self._step_fn(
                    self.params, self.opt_state,
                    self._place(tokens), self._place(targets), step)
                self.params, self.opt_state, loss = out[:3]
                if monitored:
                    _note_step(self, out[3])
                if self._telemetry_eps is None \
                        and tracer().level != "off":
                    self._record_entrypoints(tokens, targets)
            sp.fence(loss)
        return loss

    # ----------------------------------------------- telemetry surface

    _telemetry_eps = None

    def _record_entrypoints(self, tokens, targets, grads=None):
        """One-time (first traced step) skeleton capture for
        telemetry's static accounting (report.py resolves the
        conventional entrypoint attributes)."""
        from shallowspeed_tpu.telemetry.report import (
            record_engine_entrypoints)

        self._telemetry_eps = record_engine_entrypoints(
            self, tokens, targets, grads=grads)

    def telemetry_entrypoints(self) -> list:
        """(name, fn, SDS args) per compiled entrypoint, step first
        (report.py convention); empty before the first traced step."""
        return list(self._telemetry_eps or ())

    def health_snapshot(self) -> dict | None:
        """The last step's health pack as a plain host dict (one
        device_get — call at log points); None before the first step
        or with health='off'."""
        from shallowspeed_tpu.telemetry.health import engine_snapshot

        return engine_snapshot(self)

    def train_batch(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        """One optimizer step on a (B, T) int token batch; returns the loss."""
        return float(self.train_batch_async(tokens, targets))

    def train_run(self, tokens: np.ndarray, targets: np.ndarray):
        """S optimizer steps as ONE compiled dispatch. tokens/targets:
        (S, B, T) int arrays, staged HBM-resident up front; returns the
        (S,) per-step losses as a lazy device array. Dense engine only
        (ZeRO-1/2 interleave a host-side sharded update per step)."""
        assert self._run_fn is not None, (
            "train_run needs the dense engine (zero1/zero2 step on the "
            "host between grad programs)")
        assert self.health == "off", (
            "train_run fuses many steps into one dispatch; the per-step "
            "health pack (and the guard) lives in the train_batch path "
            "— build the engine with health='off' for fused runs")
        s, b, t = tokens.shape
        assert t % self.sp == 0 and t <= self.cfg.max_seq, (t, self.sp)
        assert (b * jax.process_count()) % self.dp == 0, (b, self.dp)
        sharding = NamedSharding(self.mesh, P(None, "dp", "sp"))
        toks = jax.device_put(np.asarray(tokens), sharding)
        tgts = jax.device_put(np.asarray(targets), sharding)
        step0 = np.uint32(self._step_count)
        self._step_count += s
        self.params, self.opt_state, losses = self._run_fn(
            self.params, self.opt_state, toks, tgts, step0)
        return losses

    def eval_loss(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        return float(self._eval_fn(
            self.params, self._place(tokens), self._place(targets)))

    def logits(self, tokens: np.ndarray) -> jax.Array:
        return self._logits_fn(self.params, self._place(tokens))

    def router_stats(self, tokens) -> dict | None:
        """MoE routing observability on one batch (see
        `GSPMDEngine.router_stats`): per-expert assignment load (pre-drop)
        and the dropped-assignment fraction, tile-averaged over the
        (dp, sp) mesh. None for dense configs."""
        if self._router_stats_fn is None:
            return None
        st = jax.device_get(
            self._router_stats_fn(self.params, self._place(tokens)))
        return {"expert_load": [round(float(x), 4) for x in st["load"]],
                "drop_fraction": round(float(st["drop_fraction"]), 4)}

    # -------------------------------------------- checkpoint interface

    def get_canonical_params(self):
        return self.params

    def set_canonical_params(self, params):
        self.params = jax.device_put(params, self.rep)

    def set_opt_state(self, state):
        from shallowspeed_tpu.parallel.zero import replace_opt_state

        self.opt_state = replace_opt_state(self, state)
