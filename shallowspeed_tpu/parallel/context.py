"""Context/sequence parallelism — long-context training over a (dp, sp) mesh.

The reference cannot scale sequence length at all (SURVEY §5: no sequence
dimension anywhere). This engine makes long context a first-class axis the
TPU way: shard the *sequence* over the `sp` mesh axis, run `ring_attention`
(K/V blocks rotating over ICI via `ppermute`,
`shallowspeed_tpu/ops/attention.py`) so no device ever materializes the full
(T, T) score matrix or the full sequence's activations, and compose with
batch sharding over `dp` in the same `shard_map`:

- tokens/targets: (B, T) sharded (dp, sp) — each device holds a
  (B/dp, T/sp) tile.
- params: replicated; every device computes the gradient contribution of its
  tile and one `pmean` over ('dp', 'sp') recovers the exact global-mean
  gradient (all tiles are equal-sized, so mean-of-means is exact — the same
  scaling invariant the MLP family inherits from the reference,
  `functional.py:43-44`).
- autograd: `jax.grad` straight through the ring collective (JAX
  differentiates `ppermute`), so the backward pass runs the ring in reverse
  automatically.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:
    from jax import shard_map
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.ops.attention import ring_attention, ulysses_attention


class ContextParallelEngine:
    """Data x sequence parallel trainer for the transformer LM family.

    `attn` selects the attention substrate:
    - "ring" (default): `ring_attention` over the 'sp' axis — correct for
      any sp, O(T_local) memory, n ppermute hops.
    - "ulysses": `ulysses_attention` — all-to-all head<->sequence
      re-sharding around one fused full-attention program; needs
      n_heads % sp == 0.
    - "ulysses-flash": same all-to-all re-sharding, but the local
      attention is the fused Pallas flash kernel — sequence parallelism
      AND the flash kernel's O(T) memory / fused softmax in one path.
    - "flash": the fused Pallas flash kernel
      (`ops/flash_attention.py`) — sp must be 1 (sequence unsharded);
      fastest single-device path on TPU.
    """

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 seed: int = 0, attn: str = "ring", zero1: bool = False,
                 zero2: bool = False):
        assert mesh.axis_names == ("dp", "sp")
        assert not (zero1 and zero2), "zero2 subsumes zero1"
        self.cfg = cfg
        self.mesh = mesh
        self.dp, self.sp = mesh.devices.shape
        self.optimizer = optimizer
        self._step_count = 0
        self.rep = NamedSharding(mesh, P())
        self.tile = NamedSharding(mesh, P("dp", "sp"))

        self.params = jax.device_put(T.init(cfg, seed), self.rep)
        self.opt_state = jax.device_put(optimizer.init(self.params), self.rep)

        opt = optimizer
        if attn == "flash":
            from shallowspeed_tpu.ops.flash_attention import flash_attention

            assert self.sp == 1, "--attn flash requires sp=1 (use ring)"
            attn = partial(flash_attention, causal=True)
        elif attn in ("ulysses", "ulysses-flash"):
            assert cfg.n_heads % self.sp == 0, (
                f"--attn {attn} needs n_heads ({cfg.n_heads}) divisible by "
                f"sp ({self.sp}); use ring")
            attn = partial(ulysses_attention, axis_name="sp", causal=True,
                           use_flash=attn == "ulysses-flash")
        else:
            attn = partial(ring_attention, axis_name="sp", causal=True)

        sp = self.sp

        def local_loss(params, tokens, targets, key=None):
            t_local = tokens.shape[1]
            off = jax.lax.axis_index("sp") * t_local
            if key is not None:
                # decorrelate masks across tiles: each (dp, sp) position
                # folds its mesh coordinates into the per-step key
                key = jax.random.fold_in(
                    key, jax.lax.axis_index("dp") * sp
                    + jax.lax.axis_index("sp"))
            return T.loss(params, tokens, targets, cfg,
                          attn_fn=attn, pos_offset=off, dropout_key=key)

        def train_key(step):
            if cfg.dropout == 0.0:
                return None
            return jax.random.fold_in(jax.random.PRNGKey(seed), step)

        n_tiles = self.dp * self.sp

        def loss_and_grads(params, tokens, targets, step):
            # Params are mesh-invariant (replicated), the per-tile loss is
            # varying: jax.grad's transpose of that broadcast IS a psum over
            # ('dp','sp') — the gradient arrives already summed across tiles.
            # Scaling the local loss by 1/n_tiles therefore yields exactly
            # the global-mean gradient (equal tiles => mean of means), with
            # the DP all-reduce emitted by autodiff instead of hand-placed
            # (the XLA-native version of the reference's interleaved
            # Iallreduce, `pipe.py:302-327`).
            key = train_key(step)

            def scaled(p):
                return local_loss(p, tokens, targets, key) / n_tiles

            lloc, grads = jax.value_and_grad(scaled)(params)
            return jax.lax.pmean(lloc * n_tiles, ("dp", "sp")), grads

        if zero2:
            from shallowspeed_tpu.parallel.zero import (
                make_zero1_update, shard_state_zero1, zero2_grad_specs)
            from shallowspeed_tpu.utils import pvary_over

            # one reduce-scatter per leaf instead of an all-reduce: grads
            # leave the program dp-SHARDED (1/dp per device), aligned
            # leaf-for-leaf with the ZeRO-1-placed moments, so the
            # optimizer update below runs fully local. The scatter dim is
            # read off the spec itself — one encoding of the placement
            # rule, no chance of divergence.
            gspecs = zero2_grad_specs(self.params, mesh)
            gdims = [next((i for i, ax in enumerate(sp) if ax == "dp"),
                          None)
                     for sp in jax.tree_util.tree_leaves(
                         gspecs, is_leaf=lambda x: isinstance(x, P))]

            @jax.jit
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P("dp", "sp"), P("dp", "sp"), P()),
                     out_specs=(P(), gspecs))
            def _loss_grads(params, tokens, targets, step):
                # pvary the params: cotangents then arrive as per-tile
                # PARTIALS (no auto-psum), and the reduction is ours to
                # place — psum_scatter over 'dp'
                params_v = pvary_over(params, ("dp", "sp"))
                key = train_key(step)

                def scaled(p):
                    return local_loss(p, tokens, targets, key) / n_tiles

                lloc, grads = jax.value_and_grad(scaled)(params_v)
                leaves, tdef = jax.tree_util.tree_flatten(grads)
                out = []
                for g, dim in zip(leaves, gdims):
                    # unconditionally: even at sp=1 the pvaried grads are
                    # TYPED sp-varying and need the (free) psum to retype
                    g = jax.lax.psum(g, "sp")
                    if dim is None:
                        g = jax.lax.psum(g, "dp")
                    else:
                        g = jax.lax.psum_scatter(
                            g, "dp", scatter_dimension=dim, tiled=True)
                    out.append(g)
                grads = jax.tree_util.tree_unflatten(tdef, out)
                return (jax.lax.pmean(lloc * n_tiles, ("dp", "sp")),
                        grads)

            self.opt_state = shard_state_zero1(self.opt_state, mesh)
            self._loss_grads_fn = _loss_grads
            self._update_fn = make_zero1_update(
                opt, self.params, self.opt_state)
            self._step_fn = None
        elif zero1:
            from shallowspeed_tpu.parallel.zero import (
                make_zero1_update, shard_state_zero1)

            @jax.jit
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P("dp", "sp"), P("dp", "sp"), P()),
                     out_specs=(P(), P()))
            def _loss_grads(params, tokens, targets, step):
                # ZeRO-1 grad program: the grads leave the shard_map
                # already psum'd (invariant), ready for the dp-sharded
                # optimizer update.
                return loss_and_grads(params, tokens, targets, step)

            self.opt_state = shard_state_zero1(self.opt_state, mesh)
            self._loss_grads_fn = _loss_grads
            self._update_fn = make_zero1_update(
                opt, self.params, self.opt_state)
            self._step_fn = None
        else:

            @partial(jax.jit, donate_argnums=(0, 1))
            @partial(shard_map, mesh=mesh,
                     in_specs=(P(), P(), P("dp", "sp"), P("dp", "sp"),
                               P()),
                     out_specs=(P(), P(), P()))
            def _step(params, opt_state, tokens, targets, step):
                loss, grads = loss_and_grads(params, tokens, targets, step)
                params, opt_state = opt.step(params, grads, opt_state)
                return params, opt_state, loss

            self._step_fn = _step

        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P("dp", "sp"), P("dp", "sp")),
                 out_specs=P())
        def _eval(params, tokens, targets):
            return jax.lax.pmean(
                local_loss(params, tokens, targets), ("dp", "sp"))

        @jax.jit
        @partial(shard_map, mesh=mesh,
                 in_specs=(P(), P("dp", "sp")), out_specs=P("dp", "sp"))
        def _logits(params, tokens):
            t_local = tokens.shape[1]
            off = jax.lax.axis_index("sp") * t_local
            return T.forward(params, tokens, cfg, attn_fn=attn,
                             pos_offset=off)

        self._eval_fn = _eval
        self._logits_fn = _logits

    # -------------------------------------------------------------- data

    def _place(self, arr: np.ndarray):
        # multi-host: arr is this process's local rows (place_global
        # stitches the global array); single-process: the global batch
        from shallowspeed_tpu.distributed import place_global

        b, t = arr.shape[:2]
        # local rows x processes = global batch; it must divide over dp
        assert (b * jax.process_count()) % self.dp == 0, (b, self.dp)
        assert t % self.sp == 0, (t, self.sp)
        assert t <= self.cfg.max_seq, (
            f"global sequence length {t} exceeds max_seq={self.cfg.max_seq}")
        return place_global(arr, self.tile)

    # -------------------------------------------------------------- steps

    def place(self, arr) -> jax.Array:
        """Public placement hook for prefetch pipelines."""
        return self._place(arr)

    def train_batch_async(self, tokens, targets) -> jax.Array:
        """One optimizer step; loss as a lazy device scalar (no host sync —
        `float()` it only at log points; see `data/prefetch.py`)."""
        step = np.uint32(self._step_count)
        self._step_count += 1
        if self._step_fn is None:  # ZeRO-1/2: grad program + sharded update
            loss, grads = self._loss_grads_fn(
                self.params, self._place(tokens), self._place(targets),
                step)
            self.params, self.opt_state = self._update_fn(
                self.params, grads, self.opt_state)
            return loss
        self.params, self.opt_state, loss = self._step_fn(
            self.params, self.opt_state,
            self._place(tokens), self._place(targets), step)
        return loss

    def train_batch(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        """One optimizer step on a (B, T) int token batch; returns the loss."""
        return float(self.train_batch_async(tokens, targets))

    def eval_loss(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        return float(self._eval_fn(
            self.params, self._place(tokens), self._place(targets)))

    def logits(self, tokens: np.ndarray) -> jax.Array:
        return self._logits_fn(self.params, self._place(tokens))

    # -------------------------------------------- checkpoint interface

    def get_canonical_params(self):
        return self.params

    def set_canonical_params(self, params):
        self.params = jax.device_put(params, self.rep)

    def set_opt_state(self, state):
        from shallowspeed_tpu.parallel.zero import replace_opt_state

        self.opt_state = replace_opt_state(self, state)
