"""Hand-split transformer-block backward for zero-bubble pipelining.

The ZB-H1 schedule (Qi et al.; `verify.simulate_zb`) needs the backward
split into two SEPARATELY SCHEDULABLE passes at F-like unit cost each:

- **B** — the input-cotangent pass: walk the chain dy -> dx using
  residuals stashed by F (NO forward recompute — this is what JAX's
  expressible dw-only vjp cannot do, the blocking mechanism the round-4
  pinned decision named: `tests/test_schedule_verify.py`
  test_zb_h1_compile_decision history). While walking, B peels off the
  per-matmul OUTPUT cotangents ("taps") and the cheap norm-parameter
  grads.
- **W** — the weight-gradient pass: pure batched outer products
  dW = x^T g from the stashed matmul INPUTS (F's residuals) and B's
  taps. No chain work, no attention work — exactly the deferrable
  bubble-filler the schedule wants.

Everything here mirrors `pipeline_lm.mega_block`'s dense tp=1 math 1:1
(same ops, same f32-stat norms, same dtype casts), so schedule="zb"
reproduces gpipe/1f1b trajectories; parity is asserted per piece in
`tests/test_zb_block.py` and end-to-end in `tests/test_pipeline_zb.py`.
The attention core is pluggable: "flash" replays the Pallas backward
kernels from stashed (q, k, v, o, lse) — no forward re-run; "xla"
recomputes the (weightless) attention interior inside its vjp, the
CPU-testable fallback (pinned cost note: its B includes one attention
forward; the measured-perf path is flash).

Reference lineage: the reference abandoned schedule research at
PipeDream (`/root/reference/shallowspeed/pipe.py:297-299`); ZB-H1 is
that lineage finished past 1F1B.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from shallowspeed_tpu.models import transformer as T

_EPS = 1e-5  # matches T._layernorm/_rmsnorm


# ------------------------------------------------------------ norm split


def norm_fwd(p, x, kind: str):
    """Forward + the f32 stats the hand backward needs. Math identical
    to `T._layernorm`/`T._rmsnorm` (f32 statistics, result in x.dtype)."""
    xf = x.astype(jnp.float32)
    g = p["g"].astype(jnp.float32)
    if kind == "rmsnorm":
        ms = (xf * xf).mean(axis=-1, keepdims=True)
        rstd = jax.lax.rsqrt(ms + _EPS)
        y = xf * rstd * g
        return y.astype(x.dtype), {"rstd": rstd}
    mu = xf.mean(axis=-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + _EPS)
    y = (xf - mu) * rstd * g + p["b"].astype(jnp.float32)
    return y.astype(x.dtype), {"mu": mu, "rstd": rstd}


def norm_bwd(p, x, stats, dy, kind: str):
    """dx plus the (cheap) norm-parameter grads — computed in B, not
    deferred: they are elementwise+reduce, and deferring them would
    force dh1/dh2 (full activations) into the tap stash."""
    xf = x.astype(jnp.float32)
    dyf = dy.astype(jnp.float32)
    g = p["g"].astype(jnp.float32)
    rstd = stats["rstd"]
    if kind == "rmsnorm":
        xhat = xf * rstd
        dxh = dyf * g
        dg = (dyf * xhat).sum(axis=(0, 1))
        db = jnp.zeros_like(p["b"])  # rmsnorm keeps b structurally only
        dxf = rstd * (dxh - xhat * (dxh * xhat).mean(axis=-1,
                                                     keepdims=True))
    else:
        xhat = (xf - stats["mu"]) * rstd
        dxh = dyf * g
        dg = (dyf * xhat).sum(axis=(0, 1))
        db = dyf.sum(axis=(0, 1)).astype(p["b"].dtype)
        dxf = rstd * (dxh - dxh.mean(axis=-1, keepdims=True)
                      - xhat * (dxh * xhat).mean(axis=-1, keepdims=True))
    return (dxf.astype(dy.dtype),
            {"g": dg.astype(p["g"].dtype), "b": db.astype(p["b"].dtype)})


# ------------------------------------------------------- attention cores


def make_attn_core(attn: str, window: int):
    """(fwd_save, bwd) for the ZB block. fwd_save(q, k, v) -> (o, res);
    bwd(q, k, v, o, res, do) -> (dq, dk, dv). q: (B,T,H,hd); k/v may
    carry fewer GQA kv heads; o: (B,T,H,hd)."""
    if attn == "flash":
        from shallowspeed_tpu.ops import flash_attention as fa

        def fwd_save(q, k, v):
            b, tq, h, d, kvh, g, bq, bk, nqb = fa._geometry(
                q, k, fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K)
            interpret = fa._interpret_default()
            q3 = fa._fold_q(q, kvh)
            k3, v3 = fa._to_bhsd(k), fa._to_bhsd(v)
            o3, lse = fa._chunk_fwd(q3, k3, v3, 0, causal=True,
                                    window=int(window), bq=bq, bk=bk,
                                    nqb_chunk=nqb, interpret=interpret)
            # one stats lane suffices (all 128 identical); re-broadcast
            # at B — stashing the full lane dim would 128x its bytes
            return fa._unfold_q(o3, b, h), {"lse": lse[..., :1]}

        def bwd(q, k, v, o, res, do):
            b, tq, h, d, kvh, g, bq, bk, nqb = fa._geometry(
                q, k, fa.DEFAULT_BLOCK_Q, fa.DEFAULT_BLOCK_K)
            interpret = fa._interpret_default()
            q3 = fa._fold_q(q, kvh)
            k3, v3 = fa._to_bhsd(k), fa._to_bhsd(v)
            o3, do3 = fa._fold_q(o, kvh), fa._fold_q(do, kvh)
            lse = jnp.broadcast_to(res["lse"],
                                   res["lse"].shape[:-1] + (fa._LANES,))
            delta = fa._delta_of(do3, o3, lse)
            kw = dict(causal=True, window=int(window), bq=bq, bk=bk,
                      nqb_chunk=nqb, interpret=interpret)
            dq3 = fa._chunk_dq(q3, k3, v3, do3, lse, delta, 0, **kw)
            dk3, dv3 = fa._chunk_dkv(q3, k3, v3, do3, lse, delta, 0,
                                     groups=g, **kw)
            return (fa._unfold_q(dq3, b, h).astype(q.dtype),
                    fa._from_bhsd(dk3, b, kvh).astype(k.dtype),
                    fa._from_bhsd(dv3, b, kvh).astype(v.dtype))

        return fwd_save, bwd

    assert attn == "xla", attn
    from shallowspeed_tpu.ops.attention import attention

    def fwd_save(q, k, v):
        return attention(q, k, v, causal=True, window=window), {}

    def bwd(q, k, v, o, res, do):
        # the interior is weightless, so its full vjp IS the B pass;
        # the recompute here is one attention forward (pinned cost of
        # the xla fallback — flash replays kernels from the stash)
        _, pb = jax.vjp(
            lambda q_, k_, v_: attention(q_, k_, v_, causal=True,
                                         window=window), q, k, v)
        return pb(do)

    return fwd_save, bwd


# ------------------------------------------------------ block fwd / B / W


def block_fwd(blk, x, pos, cfg, attn_fwd):
    """One pre-LN block, saving the split-backward residuals. Returns
    (y, resb, resw): resb is freed at B (q/k/v, stats, block inputs),
    resw lives to W (the per-matmul input activations + attention out +
    ffn pre-activations, which B's elementwise derivatives also read)."""
    b, t, d = x.shape
    hd = cfg.head_dim
    h1, n1 = norm_fwd(blk["ln1"], x, cfg.norm)
    if cfg.gqa:
        q = (h1 @ blk["q"]["W"] + blk["q"]["b"]).reshape(
            b, t, cfg.n_heads, hd)
        kv = (h1 @ blk["kv"]["W"] + blk["kv"]["b"]).reshape(
            b, t, cfg.kv_heads, 2, hd)
        k, v = kv[..., 0, :], kv[..., 1, :]
    else:
        qkv = (h1 @ blk["qkv"]["W"] + blk["qkv"]["b"]).reshape(
            b, t, cfg.n_heads, 3, hd)
        q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
    if cfg.rope:
        q = T.rope_rotate(q, pos, cfg.rope_theta)
        k = T.rope_rotate(k, pos, cfg.rope_theta)
    o, attn_res = attn_fwd(q, k, v)
    a = o.reshape(b, t, d)
    x2 = x + (a @ blk["proj"]["W"] + blk["proj"]["b"])
    h2, n2 = norm_fwd(blk["ln2"], x2, cfg.norm)
    if cfg.ffn == "swiglu":
        sg = h2 @ blk["gate"]["W"] + blk["gate"]["b"]
        up = h2 @ blk["up"]["W"] + blk["up"]["b"]
        u = jax.nn.silu(sg) * up
        ffn_res = {"sg": sg, "up": up}
    else:
        pre = h2 @ blk["up"]["W"] + blk["up"]["b"]
        u = jax.nn.gelu(pre)
        ffn_res = {"pre": pre}
    y = x2 + (u @ blk["down"]["W"] + blk["down"]["b"])
    resb = {"x": x, "n1": n1, "q": q, "k": k, "v": v, "x2": x2,
            "n2": n2, **attn_res}
    resw = {"h1": h1, "o": o, "h2": h2, **ffn_res}
    return y, resb, resw


def _act_recompute(resw, cfg):
    """ffn activation output u from the stashed pre-activations —
    elementwise, shared by B (derivative) and W (dWdown input)."""
    if cfg.ffn == "swiglu":
        return jax.vjp(lambda s, u_: jax.nn.silu(s) * u_,
                       resw["sg"], resw["up"])
    return jax.vjp(jax.nn.gelu, resw["pre"])


def block_bwd_x(blk, resb, resw, dy, pos, cfg, attn_bwd):
    """The B pass: dy -> dx with NO forward recompute (flash core).
    Returns (dx, taps, dnorm): taps are the matmul output-cotangents W
    turns into weight grads; dnorm the ln1/ln2 param grads."""
    b, t, d = dy.shape
    hd = cfg.head_dim
    # ---- FFN side
    _, act_pb = _act_recompute(resw, cfg)
    du = dy @ blk["down"]["W"].T
    if cfg.ffn == "swiglu":
        dsg, dup = act_pb(du)
        dh2 = dsg @ blk["gate"]["W"].T + dup @ blk["up"]["W"].T
        taps_ffn = {"dsg": dsg, "dup": dup}
    else:
        (dpre,) = act_pb(du)
        dh2 = dpre @ blk["up"]["W"].T
        taps_ffn = {"dpre": dpre}
    dx2_n, dn2 = norm_bwd(blk["ln2"], resb["x2"], resb["n2"], dh2,
                          cfg.norm)
    dx2 = dy + dx2_n
    # ---- attention side
    do_proj = dx2
    da = do_proj @ blk["proj"]["W"].T
    do = da.reshape(b, t, cfg.n_heads, hd)
    dq, dk, dv = attn_bwd(resb["q"], resb["k"], resb["v"], resw["o"],
                          {k_: v_ for k_, v_ in resb.items()
                           if k_ == "lse"}, do)
    if cfg.rope:
        # rope is orthogonal: the transpose is rotation by -pos
        dq = T.rope_rotate(dq, -pos, cfg.rope_theta)
        dk = T.rope_rotate(dk, -pos, cfg.rope_theta)
    if cfg.gqa:
        dqf = dq.reshape(b, t, d)
        dkvf = jnp.stack([dk, dv], axis=3).reshape(
            b, t, cfg.kv_heads * 2 * hd)
        dh1 = dqf @ blk["q"]["W"].T + dkvf @ blk["kv"]["W"].T
        taps_attn = {"dq": dqf, "dkv": dkvf}
    else:
        dqkvf = jnp.stack([dq, dk, dv], axis=3).reshape(b, t, 3 * d)
        dh1 = dqkvf @ blk["qkv"]["W"].T
        taps_attn = {"dqkv": dqkvf}
    dx1, dn1 = norm_bwd(blk["ln1"], resb["x"], resb["n1"], dh1,
                        cfg.norm)
    dx = dx2 + dx1
    taps = {**taps_attn, "dproj": do_proj, **taps_ffn, "ddown": dy}
    return dx, taps, {"ln1": dn1, "ln2": dn2}


# ------------------------------------------------------------ stack level


def stack_fwd(blocks, x, pos, cfg, attn_fwd):
    """This stage's stacked blocks: scan forward collecting per-layer
    residuals (leaves gain a leading L axis)."""
    def body(h, blk):
        y, resb, resw = block_fwd(blk, h, pos, cfg, attn_fwd)
        return y, (resb, resw)

    y, (resb_s, resw_s) = jax.lax.scan(body, x, blocks)
    return y, resb_s, resw_s


def stack_bwd_x(blocks, resb_s, resw_s, dy, pos, cfg, attn_bwd):
    """Reverse scan of the B pass; stacked taps/norm-grads come out
    aligned with the layer axis."""
    def body(g, xs):
        blk, resb, resw = xs
        dx, taps, dnorm = block_bwd_x(blk, resb, resw, g, pos, cfg,
                                      attn_bwd)
        return dx, (taps, dnorm)

    dx, (taps_s, dnorm_s) = jax.lax.scan(
        body, dy, (blocks, resb_s, resw_s), reverse=True)
    return dx, taps_s, dnorm_s


def stack_bwd_w(resw_s, taps_s, cfg):
    """The W pass: batched outer products over the layer axis — one
    fused einsum per projection, the whole stage's weight grads in a
    handful of MXU dispatches. No chain, no attention, no recompute
    (the ffn activation re-evaluates elementwise from stashed
    pre-activations). Returns the blocks' dense-leaf grad subtree."""
    def outer(xs, gs):
        return jnp.einsum("lbtd,lbtk->ldk", xs, gs)

    def bias(gs):
        return gs.sum(axis=(1, 2))

    if cfg.ffn == "swiglu":
        u = jax.nn.silu(resw_s["sg"]) * resw_s["up"]
    else:
        u = jax.nn.gelu(resw_s["pre"])
    a = resw_s["o"].reshape(resw_s["o"].shape[:3] + (-1,))  # (L,B,T,D)
    out = {
        "proj": {"W": outer(a, taps_s["dproj"]),
                 "b": bias(taps_s["dproj"])},
        "down": {"W": outer(u, taps_s["ddown"]),
                 "b": bias(taps_s["ddown"])},
    }
    if "dqkv" in taps_s:
        out["qkv"] = {"W": outer(resw_s["h1"], taps_s["dqkv"]),
                      "b": bias(taps_s["dqkv"])}
    else:
        out["q"] = {"W": outer(resw_s["h1"], taps_s["dq"]),
                    "b": bias(taps_s["dq"])}
        out["kv"] = {"W": outer(resw_s["h1"], taps_s["dkv"]),
                     "b": bias(taps_s["dkv"])}
    if cfg.ffn == "swiglu":
        out["gate"] = {"W": outer(resw_s["h2"], taps_s["dsg"]),
                       "b": bias(taps_s["dsg"])}
        out["up"] = {"W": outer(resw_s["h2"], taps_s["dup"]),
                     "b": bias(taps_s["dup"])}
    else:
        out["up"] = {"W": outer(resw_s["h2"], taps_s["dpre"]),
                     "b": bias(taps_s["dpre"])}
    return out
