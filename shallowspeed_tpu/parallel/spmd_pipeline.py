"""Fused SPMD pipeline engine — the compiled GPipe performance path.

The pipeline VM (`worker.py`) interprets instruction streams with Python
dispatch per instruction (faithful to the reference's executor,
`/root/reference/shallowspeed/pipe.py:434-466`). This module compiles the
ENTIRE GPipe batch step — warmup, steady state, drain, gradient all-reduce,
optimizer update — into ONE jitted XLA program over a 2-D (dp, pp)
`jax.sharding.Mesh` (SURVEY §7 step 7, option (a)):

- Every device runs the same program (SPMD) under `shard_map`; the stage id
  is `lax.axis_index('pp')`.
- Stage-to-stage activation/grad hops are `lax.ppermute` over the 'pp' axis
  (the ICI neighbor exchange replacing blocking `MPI.Send/Recv`,
  `pipe.py:367-381`).
- The clock runs `n_mu + pp - 1` forward ticks then `n_mu + pp - 1`
  backward ticks via `lax.scan`; bubble ticks compute on zeros and their
  results are masked out — the standard SPMD pipelining formulation (cf. the
  scaling-book pipelining recipe); XLA's latency-hiding scheduler overlaps
  tick t's compute with the neighbor permute.
- Heterogeneous stage widths (the reference's [784,128,...,10] stages,
  SURVEY §7 hard part 1) are handled by zero-padding every stage to an equal
  layer count L and a common max width Wmax. Zero padding is exact for
  linear+ReLU algebra (padded rows/cols contribute 0); the softmax head
  masks padded logits to -1e30. Gradients of padding are forced to zero, so
  the optimizer never moves padded entries.
- DP composes orthogonally: batches are sharded over 'dp', the accumulated
  grads get one bucketed `lax.psum` over 'dp' (replacing per-param
  Iallreduce + Waitall, `pipe.py:302-327`), and the optimizer update runs
  replicated over 'dp' / sharded over 'pp'.

Semantics match GPipe-with-sum-accumulation (microbatch grads summed, loss
scaled by global batch size, `functional.py:43-44`), verified against the
fused sequential engine in tests/test_spmd_pipeline.py.
"""

from __future__ import annotations

import copy
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# version-compat shard_map (utils.py): VMA jax as-is; pre-VMA jax
# with the legacy replication rewriter disabled
from shallowspeed_tpu.utils import shard_map

from shallowspeed_tpu.models.mlp import init_linear_np, stage_layer_sizes
from shallowspeed_tpu.utils import pvary_over as _pvary

tree_map = jax.tree_util.tree_map


def _note_step(engine, pack):
    # health.note_step, imported lazily (telemetry stays off the module
    # import path): stores last_health + device-side cumulative counters
    from shallowspeed_tpu.telemetry.health import note_step

    note_step(engine, pack)



def _pad_to(arr: np.ndarray, shape) -> np.ndarray:
    out = np.zeros(shape, arr.dtype)
    out[tuple(slice(0, s) for s in arr.shape)] = arr
    return out


class StageStack:
    """Stage-stacked, width-padded parameters + static per-stage metadata.

    Layout: W (pp, L, Wmax, Wmax), b (pp, L, 1, Wmax); flags (pp, L):
    `valid` (layer exists on this stage) and `relu` (layer has a ReLU —
    everything except the last stage's final linear, `layers.py:251-260`).
    """

    def __init__(self, sizes: list[int], pp: int):
        self.sizes = list(sizes)
        self.pp = pp
        self.wmax = max(sizes)
        per_stage = [stage_layer_sizes(sizes, s, pp) for s in range(pp)]
        self.n_linears = [len(ls) - 1 for ls in per_stage]
        self.L = max(self.n_linears)
        self.in_dim = per_stage[0][0]
        self.out_dim = per_stage[-1][-1]

    def init(self):
        pp, L, wmax = self.pp, self.L, self.wmax
        W = np.zeros((pp, L, wmax, wmax), np.float32)
        b = np.zeros((pp, L, 1, wmax), np.float32)
        valid = np.zeros((pp, L), np.float32)
        relu = np.zeros((pp, L), np.float32)
        for s in range(pp):
            local = stage_layer_sizes(self.sizes, s, pp)
            for i in range(len(local) - 1):
                layer = init_linear_np(local[i], local[i + 1])
                W[s, i] = _pad_to(layer["W"], (wmax, wmax))
                b[s, i] = _pad_to(layer["b"], (1, wmax))
                valid[s, i] = 1.0
                is_last_linear = (s == pp - 1) and (i == len(local) - 2)
                relu[s, i] = 0.0 if is_last_linear else 1.0
        head_mask = np.zeros((wmax,), np.float32)
        head_mask[: self.out_dim] = 1.0
        return {"W": W, "b": b}, {"valid": valid, "relu": relu,
                                  "head_mask": head_mask}

    def unstack_params(self, stacked) -> list[list[dict]]:
        """Back to the per-stage list-of-{'W','b'} pytree (unpadded), for
        parity checks and checkpoint interchange with the other engines."""
        W = np.asarray(stacked["W"])
        b = np.asarray(stacked["b"])
        out = []
        for s in range(self.pp):
            local = stage_layer_sizes(self.sizes, s, self.pp)
            layers = []
            for i in range(len(local) - 1):
                layers.append({
                    "W": W[s, i, : local[i + 1], : local[i]].copy(),
                    "b": b[s, i, :, : local[i + 1]].copy(),
                })
            out.append(layers)
        return out


class SPMDPipelineEngine:
    """GPipe training with the whole batch step compiled as one XLA program.

    API-compatible with `FusedDPEngine` (train_batch / stage_epoch /
    train_epoch / infer) so `train.py` and the bench can swap engines.
    """

    def __init__(self, sizes, optimizer, mesh: Mesh, n_mubatches: int,
                 mubatch_size: int, global_batch_size: int,
                 health: str = "off", overlap=None):
        from shallowspeed_tpu.telemetry.health import MODES

        assert health in MODES, health
        self.health = health
        self.last_health = None
        self.overlap = overlap  # parallel.overlap.OverlapConfig | None
        assert mesh.axis_names == ("dp", "pp")
        self.mesh = mesh
        self.dp, self.pp = mesh.devices.shape
        self.n_mu = n_mubatches
        self.mubs = mubatch_size  # per-replica microbatch rows
        self.stack = StageStack(sizes, self.pp)
        self.optimizer = optimizer
        self.wmax = self.stack.wmax
        self.out_dim = self.stack.out_dim
        self.gbs = global_batch_size

        params_h, meta_h = self.stack.init()
        self.p_shard = NamedSharding(mesh, P("pp"))
        self.rep = NamedSharding(mesh, P())
        self.params = jax.device_put(params_h, self.p_shard)
        # static per-stage metadata: small, baked in replicated
        self._valid_full = jnp.asarray(meta_h["valid"])
        self._relu_full = jnp.asarray(meta_h["relu"])
        self._head_mask = jnp.asarray(meta_h["head_mask"])

        template = optimizer.init(self.params)
        opt_specs = tree_map(
            lambda l: P("pp") if getattr(l, "ndim", 0) >= 1 else P(), template)
        self._opt_specs = opt_specs
        self.opt_state = jax.device_put(
            template,
            tree_map(lambda s: NamedSharding(mesh, s), opt_specs))

        self._build()

    # ---------------------------------------------------------------- build

    def _build(self):
        mesh = self.mesh
        n_mu, mubs, wmax = self.n_mu, self.mubs, self.wmax
        L = self.stack.L
        pp = self.pp
        gbs = self.gbs
        # opt.step is traced inside shard_map with grads SHARDED over 'pp'
        # (each device holds only its stage's slice), so the clipping norm
        # must psum over 'pp' to be global. Private copy: the caller's
        # optimizer may also drive engines with full-gradient contexts.
        opt = copy.copy(self.optimizer)
        opt.clip_axes = ("pp",)
        valid_full, relu_full = self._valid_full, self._relu_full
        head_mask = self._head_mask

        right = [(i, (i + 1) % pp) for i in range(pp)]
        left = [((i + 1) % pp, i) for i in range(pp)]

        def stage_fwd(W, b, valid, relu_f, x, is_last):
            """One stage's padded forward on one (mubs, wmax) block.
            Returns (out, stash)."""
            h = x
            xs, masks = [], []
            for l in range(L):
                xs.append(h)
                z = h @ W[l].T + b[l]
                a = jnp.where(relu_f[l] > 0, jnp.maximum(z, 0.0), z)
                masks.append((z > 0) & (relu_f[l] > 0))
                h = jnp.where(valid[l] > 0, a, h)
            # softmax head (meaningful on the last stage only): reference
            # numerics — global max shift + 1e-7 (`functional.py:24-27`) —
            # restricted to the valid class columns.
            logits = h
            ml = jnp.where(head_mask > 0, logits, jnp.float32(-1e30))
            e = jnp.exp(ml - jnp.max(ml))
            probs = e / (e.sum(axis=1, keepdims=True) + 1e-7)
            out = jnp.where(is_last, probs, h)
            stash = {"xs": jnp.stack(xs), "masks": jnp.stack(masks),
                     "probs": probs}
            return out, stash

        def head_grad(probs, target, dout, is_last):
            """MSELoss head: target -> upstream grad
            (`layers.py:157-163`), then softmax VJP expressed via
            probs; non-last stages pass `dout` through. The ONE
            encoding shared by the scanned backward tick and the
            peeled bucketed replay."""
            g0 = -2.0 * (target - probs) / gbs
            gg = probs * g0
            d_head = gg - probs * gg.sum(axis=-1, keepdims=True)
            return jnp.where(is_last, d_head, dout)

        def bwd_layer(W, valid, relu_f, stash, l, d):
            """One layer's padded backward step: (d_next, dW_l, db_l).
            Padding layers are identity (gradient passes through, zero
            weight grads). Shared by stage_bwd and the peeled replay so
            the overlapped path can never diverge from the oracle's
            math."""
            d_in = d
            d_act = jnp.where(relu_f[l] > 0,
                              jnp.where(stash["masks"][l], d, 0.0), d)
            dW = d_act.T @ stash["xs"][l]
            db = d_act.sum(axis=0, keepdims=True)
            d_prev = d_act @ W[l]
            d = jnp.where(valid[l] > 0, d_prev, d_in)
            return (d, jnp.where(valid[l] > 0, dW, 0.0),
                    jnp.where(valid[l] > 0, db, 0.0))

        def stage_bwd(W, valid, relu_f, dout, stash, is_last, target):
            """One stage's padded backward; returns (dx, dW, db)."""
            d = head_grad(stash["probs"], target, dout, is_last)
            dWs, dbs = [], []
            for l in range(L - 1, -1, -1):
                d, dW, db = bwd_layer(W, valid, relu_f, stash, l, d)
                dWs.append(dW)
                dbs.append(db)
            dWs.reverse()
            dbs.reverse()
            return d, jnp.stack(dWs), jnp.stack(dbs)

        # Comm/compute interleaving (parallel/overlap.py). Two opt-in
        # pieces share the `overlap` config:
        # - double-buffered p2p hops (stride 2): each tick permutes the
        #   PREVIOUS tick's output while computing the current one, so
        #   the hop leaves the per-tick critical path (single-buffer
        #   ticks serialize compute -> ppermute -> next compute). Costs
        #   pp-1 extra warmup/drain ticks: microbatch m sits at stage s
        #   at tick stride*s + m.
        # - bucketed dp reduction: the final backward tick is peeled
        #   out of the scan and its layer loop emits each grad bucket's
        #   psum the moment the bucket's leaves are final — interleaved
        #   with the remaining backward instead of one exposed bulk
        #   reduction after the scan.
        ov = self.overlap
        stride = 2 if (ov is not None and ov.double_buffer_hops) else 1
        if ov is not None:
            from shallowspeed_tpu.parallel import overlap as OVM

            order = []
            for l in range(L - 1, -1, -1):  # backward-finalization order
                order.append((2 * l, jax.ShapeDtypeStruct(
                    (self.wmax, self.wmax), jnp.float32)))
                order.append((2 * l + 1, jax.ShapeDtypeStruct(
                    (1, self.wmax), jnp.float32)))
            raw = OVM.plan_buckets([x for _, x in order],
                                   ov.bucket_bytes)
            ov_plan = [[order[j][0] for j in bk] for bk in raw]
            by_id = dict(order)
            self._bucket_sigs = [
                OVM.bucket_signature([by_id[i] for i in bk])
                for bk in ov_plan]
        else:
            ov_plan = None
            self._bucket_sigs = []

        fwd_ticks = n_mu + stride * (pp - 1)
        bwd_ticks = n_mu + stride * (pp - 1)

        def local_step(params, opt_state, xs, ys):
            """Per-device GPipe batch step.
            Blocks: params W (1, L, wmax, wmax); xs (1, n_mu, mubs, wmax)
            width-padded (stage 0 consumes); ys (1, n_mu, mubs, out_dim)
            compact (the last stage pads on the fly)."""
            W = params["W"][0]
            b = params["b"][0]
            s = jax.lax.axis_index("pp")
            is_first = s == 0
            is_last = s == pp - 1
            valid = valid_full[s]
            relu_f = relu_full[s]
            xs, ys = xs[0], ys[0]

            # ---------------- forward phase
            def fwd_compute(cur, stashes, t):
                m = t - stride * s  # microbatch this stage handles at t
                active = (m >= 0) & (m < n_mu)
                mc = jnp.clip(m, 0, n_mu - 1)
                x_own = jax.lax.dynamic_index_in_dim(xs, mc, keepdims=False)
                x_in = jnp.where(is_first, x_own, cur)
                out, stash = stage_fwd(W, b, valid, relu_f, x_in, is_last)

                def upd(buf, new):
                    newb = jax.lax.dynamic_update_index_in_dim(buf, new, mc, 0)
                    return jnp.where(active, newb, buf)

                return out, tree_map(upd, stashes, stash)

            def fwd_tick(carry, t):
                # single-buffer: compute, then hop this tick's output
                # (the next tick's compute waits on the permute)
                cur, stashes = carry
                out, stashes = fwd_compute(cur, stashes, t)
                nxt = jax.lax.ppermute(out, "pp", right)
                return (nxt, stashes), None

            def fwd_tick_db(carry, t):
                # double-buffered: hop the PREVIOUS tick's output while
                # computing this tick's — the ppermute and the matmuls
                # share no dataflow, so the latency-hiding scheduler
                # runs them concurrently (delivery takes two ticks,
                # hence the stride-2 microbatch placement)
                cur, inflight, stashes = carry
                recv = jax.lax.ppermute(inflight, "pp", right)
                out, stashes = fwd_compute(cur, stashes, t)
                return (recv, out, stashes), None

            stash0 = {
                "xs": jnp.zeros((n_mu, L, mubs, wmax)),
                "masks": jnp.zeros((n_mu, L, mubs, wmax), bool),
                "probs": jnp.zeros((n_mu, mubs, wmax)),
            }
            zblk = jnp.zeros((mubs, wmax))
            if stride == 1:
                init = _pvary((zblk, stash0), ("pp", "dp"))
                (cur, stashes), _ = jax.lax.scan(
                    fwd_tick, init, jnp.arange(fwd_ticks))
            else:
                init = _pvary((zblk, zblk, stash0), ("pp", "dp"))
                (cur, _, stashes), _ = jax.lax.scan(
                    fwd_tick_db, init, jnp.arange(fwd_ticks))

            # ---------------- backward phase (reversed microbatch order,
            # GPipe `pipe.py:234-235`; the last stage leads)
            def bwd_mu_stash(t):
                r = t - stride * (pp - 1 - s)  # reversed index at tick t
                m = n_mu - 1 - r
                active = (r >= 0) & (r < n_mu)
                mc = jnp.clip(m, 0, n_mu - 1)
                stash_m = tree_map(
                    lambda buf: jax.lax.dynamic_index_in_dim(
                        buf, mc, keepdims=False), stashes)
                # targets stay compact (out_dim cols) in HBM; pad here on
                # device — padded target entries are zero, matching padded
                # probs, so the head grad on padding is exactly zero.
                y_own = jax.lax.dynamic_index_in_dim(ys, mc, keepdims=False)
                y_own = jnp.pad(y_own, ((0, 0), (0, wmax - y_own.shape[-1])))
                return active, stash_m, y_own

            def bwd_compute(cur, gW, gb, t):
                active, stash_m, y_own = bwd_mu_stash(t)
                dx, dW, db = stage_bwd(W, valid, relu_f, cur, stash_m,
                                       is_last, y_own)
                gW = gW + jnp.where(active, dW, 0.0)
                gb = gb + jnp.where(active, db, 0.0)
                return jnp.where(active, dx, 0.0), gW, gb

            def bwd_tick(carry, t):
                cur, gW, gb = carry
                dx, gW, gb = bwd_compute(cur, gW, gb, t)
                nxt = jax.lax.ppermute(dx, "pp", left)
                return (nxt, gW, gb), None

            def bwd_tick_db(carry, t):
                cur, inflight, gW, gb = carry
                recv = jax.lax.ppermute(inflight, "pp", left)
                dx, gW, gb = bwd_compute(cur, gW, gb, t)
                return (recv, dx, gW, gb), None

            # with a bucket plan the final tick is peeled out of the
            # scan so its layer loop can interleave the dp reduction
            n_scan = bwd_ticks - (1 if ov_plan is not None else 0)
            if stride == 1:
                binit = _pvary((zblk, jnp.zeros_like(W),
                                jnp.zeros_like(b)), ("pp", "dp"))
                (cur, gW, gb), _ = jax.lax.scan(
                    bwd_tick, binit, jnp.arange(n_scan))
            else:
                binit = _pvary((zblk, zblk, jnp.zeros_like(W),
                                jnp.zeros_like(b)), ("pp", "dp"))
                (cur, _, gW, gb), _ = jax.lax.scan(
                    bwd_tick_db, binit, jnp.arange(n_scan))

            if ov_plan is None:
                # bulk oracle: one psum per stacked leaf AFTER the scan
                # — fully exposed (the scan is its only producer), kept
                # as the reduction-order reference (`pipe.py:302-327`)
                grads = {"W": jax.lax.psum(gW, "dp")[None],
                         "b": jax.lax.psum(gb, "dp")[None]}
            else:
                from shallowspeed_tpu.parallel.overlap import (
                    BucketEmitter)

                # peeled final backward tick (only stage 0 is still
                # active — every other stage's grads are already
                # final): replay stage_bwd's layer loop and emit each
                # bucket's psum the moment its layers' totals are
                # final, dataflow-independent of the earlier layers'
                # backward matmuls still being computed.
                t_last = bwd_ticks - 1
                active, stash_m, y_own = bwd_mu_stash(t_last)
                d = head_grad(stash_m["probs"], y_own, cur, is_last)
                em = BucketEmitter(ov_plan, ("dp",))
                for l in range(L - 1, -1, -1):
                    d, dW_l, db_l = bwd_layer(W, valid, relu_f,
                                              stash_m, l, d)
                    em.add(2 * l, gW[l] + jnp.where(active, dW_l, 0.0))
                    em.add(2 * l + 1,
                           gb[l] + jnp.where(active, db_l, 0.0))
                red = em.done()
                grads = {
                    "W": jnp.stack([red[2 * l] for l in range(L)])[None],
                    "b": jnp.stack([red[2 * l + 1]
                                    for l in range(L)])[None]}
            if health_mode == "off":
                return opt.step(params, grads, opt_state)
            # health pack fused into the step (telemetry/health.py):
            # params/grads are pp-sharded stage stacks, so each leaf's
            # statistic psums over 'pp' to span every stage in-program;
            # under "guard" the update gates on the (pp-global)
            # nonfinite sentinel — all stages skip in lockstep,
            # bit-identically (optim.guarded_step).
            from shallowspeed_tpu.telemetry.health import (grad_health,
                                                           update_health)

            pax = [("pp",), ("pp",)]  # {'W','b'} stacks, P('pp') each
            pack = grad_health(params, grads, grad_axes=pax,
                               param_axes=pax)
            if health_mode == "guard":
                ok = pack["nonfinite"] == 0
                new_p, new_s = opt.guarded_step(params, grads,
                                                opt_state, ok)
                pack = update_health(pack, params, new_p,
                                     param_axes=pax, skipped=1 - ok)
            else:
                new_p, new_s = opt.step(params, grads, opt_state)
                pack = update_health(pack, params, new_p,
                                     param_axes=pax)
            return new_p, new_s, pack

        health_mode = self.health
        p_specs = {"W": P("pp"), "b": P("pp")}
        step_out = ((p_specs, self._opt_specs) if health_mode == "off"
                    else (p_specs, self._opt_specs, P()))

        @partial(jax.jit, donate_argnums=(0, 1))
        @partial(shard_map, mesh=mesh,
                 in_specs=(p_specs, self._opt_specs, P("dp"), P("dp")),
                 out_specs=step_out)
        def _step(params, opt_state, xs, ys):
            return local_step(params, opt_state, xs, ys)

        @partial(jax.jit, donate_argnums=(0, 1))
        @partial(shard_map, mesh=mesh,
                 in_specs=(p_specs, self._opt_specs, P(None, "dp"),
                           P(None, "dp")),
                 out_specs=(p_specs, self._opt_specs))
        def _epoch(params, opt_state, xs, ys):
            def body(carry, xy):
                p, o = carry
                x, y = xy
                out = local_step(p, o, x, y)
                # the fused-epoch path never carries the health pack
                # (drivers step per-batch when health is on)
                return out[:2], None

            (params, opt_state), _ = jax.lax.scan(
                body, (params, opt_state), (xs, ys))
            return params, opt_state

        @partial(jax.jit)
        @partial(shard_map, mesh=mesh, in_specs=(p_specs, P("dp")),
                 out_specs=P("dp"))
        def _infer(params, x):
            # Each stage applies its slice every tick; after pp compute+shift
            # rounds the block that started at stage 0 has traversed
            # f_{pp-1} ∘ ... ∘ f_0 and wrapped around to stage 0. A psum-mask
            # then makes the result pp-invariant.
            W = params["W"][0]
            b = params["b"][0]
            s = jax.lax.axis_index("pp")
            is_last = s == pp - 1
            valid = valid_full[s]
            relu_f = relu_full[s]

            def tick(h, _):
                out, _stash = stage_fwd(W, b, valid, relu_f, h, is_last)
                return jax.lax.ppermute(out, "pp", right), None

            h0 = _pvary(x, ("pp",))
            h, _ = jax.lax.scan(tick, h0, None, length=pp)
            return jax.lax.psum(jnp.where(s == 0, h, 0.0), "pp")

        self._step_fn = _step
        self._epoch_fn = _epoch
        self._infer_fn = _infer
        if ov is not None:
            from shallowspeed_tpu.parallel import overlap as OVM

            for fn in (_step, _epoch):
                OVM.register_program(fn, "dp", self._bucket_sigs,
                                     engine="SPMDPipelineEngine")

    # ------------------------------------------------------------- data

    def _pad_batch(self, arr):
        out = np.zeros(arr.shape[:-1] + (self.wmax,), np.float32)
        out[..., : arr.shape[-1]] = arr
        return out

    def stage_batch(self, datasets, batch_id):
        """(dp, n_mu, mubs, *) stacks sharded over 'dp' (axis 0), replicated
        over 'pp'. Inputs are width-padded; targets stay compact."""
        stacks = [ds.load_mubatch_stack(batch_id) for ds in datasets]
        xs = np.stack([s[0] for s in stacks])
        ys = np.stack([s[1] for s in stacks])
        shard = NamedSharding(self.mesh, P("dp"))
        return (jax.device_put(self._pad_batch(xs), shard),
                jax.device_put(ys, shard))

    def train_batch(self, batch_id, datasets):
        from shallowspeed_tpu.telemetry import tracer

        xs, ys = self.stage_batch(datasets, batch_id)
        with tracer().span("step", batch=batch_id,
                           schedule="gpipe") as sp:
            if self._telemetry_eps is None and tracer().level != "off":
                self._record_entrypoints(xs, ys)
            out = self._step_fn(self.params, self.opt_state, xs, ys)
            self.params, self.opt_state = out[0], out[1]
            if self.health != "off":
                _note_step(self, out[2])
            sp.fence(self.params["b"])

    def stage_epoch(self, datasets, n_batches=None):
        from shallowspeed_tpu.data.dataset import stack_epoch

        xs, ys = stack_epoch(datasets, n_batches)
        shard = NamedSharding(self.mesh, P(None, "dp"))
        return (jax.device_put(self._pad_batch(xs), shard),
                jax.device_put(ys, shard))

    def train_epoch(self, staged):
        from shallowspeed_tpu.telemetry import tracer

        xs, ys = staged
        with tracer().span("epoch") as sp:
            self.params, self.opt_state = self._epoch_fn(
                self.params, self.opt_state, xs, ys)
            sp.fence(self.params["b"])

    # ----------------------------------------------- telemetry surface

    _telemetry_eps = None

    def _record_entrypoints(self, xs, ys):
        from shallowspeed_tpu.telemetry.report import (
            record_engine_entrypoints)

        self._telemetry_eps = record_engine_entrypoints(
            self, xs, ys, step_arg=False)

    def telemetry_entrypoints(self) -> list:
        """(name, fn, SDS args) for telemetry's static accounting
        (report.py); empty before the first traced `train_batch`."""
        return list(self._telemetry_eps or ())

    def schedule_info(self) -> dict:
        """Executed-schedule identity for bubble accounting: this
        engine IS the compiled GPipe tick program. With double-buffered
        hops the stage spacing is 2 ticks (microbatch m sits at stage s
        at tick 2s+m), trading pp-1 extra warmup/drain ticks for hops
        off the per-tick critical path."""
        db = bool(self.overlap is not None
                  and self.overlap.double_buffer_hops)
        return {"schedule": "gpipe", "n_mu": self.n_mu, "pp": self.pp,
                "vpp": 1, "hop_double_buffer": db}

    def health_snapshot(self) -> dict | None:
        """The last train_batch's health pack as a host dict (one
        device_get); None before the first step or with health='off'.
        The fused train_epoch path does not carry the pack."""
        from shallowspeed_tpu.telemetry.health import engine_snapshot

        return engine_snapshot(self)

    def infer(self, x: np.ndarray) -> jax.Array:
        """Forward a (rows, in_dim) batch; returns (rows, out_dim) probs."""
        xp = self._pad_batch(x.reshape(x.shape[0], -1))
        xd = jax.device_put(xp, NamedSharding(self.mesh, P("dp")))
        out = self._infer_fn(self.params, xd)
        return out[:, : self.out_dim]

    # ------------------------------------------------------------- misc

    @property
    def unstacked_params(self):
        return self.stack.unstack_params(jax.device_get(self.params))

    # -------------------------------------------------- checkpoint interface

    def get_canonical_params(self):
        return [layer for stage_p in self.unstacked_params
                for layer in stage_p]

    def _stack_layers(self, layers) -> dict:
        """Re-pad a canonical flat layer list into the stage-stacked
        {'W','b'} layout (host-side) — shared by params restore and the
        canonical optimizer-moment import."""
        st = self.stack
        W = np.zeros((st.pp, st.L, st.wmax, st.wmax), np.float32)
        b = np.zeros((st.pp, st.L, 1, st.wmax), np.float32)
        i = 0
        for s in range(st.pp):
            for l in range(st.n_linears[s]):
                layer = layers[i]
                W[s, l] = _pad_to(np.asarray(layer["W"]), (st.wmax, st.wmax))
                b[s, l] = _pad_to(np.asarray(layer["b"]), (1, st.wmax))
                i += 1
        assert i == len(layers), (i, len(layers))
        return {"W": W, "b": b}

    def set_canonical_params(self, layers):
        self.params = jax.device_put(self._stack_layers(layers),
                                     self.p_shard)

    def canon_export_tree(self, tree):
        """Params-shaped tree (e.g. Adam moments, stacked+padded) ->
        canonical flat layer list; the padding is zeros-in, zeros-out, so
        unpadded moments round-trip exactly."""
        return [layer
                for stage in self.stack.unstack_params(jax.device_get(tree))
                for layer in stage]

    def canon_import_tree(self, tree):
        """Inverse of `canon_export_tree` (host-side; `set_opt_state`
        applies the sharding specs)."""
        return self._stack_layers(tree)

    def set_opt_state(self, state):
        self.opt_state = jax.device_put(
            state,
            tree_map(lambda s: NamedSharding(self.mesh, s), self._opt_specs))
