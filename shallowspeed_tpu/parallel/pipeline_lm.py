"""Pipeline parallelism for the transformer family — SPMD GPipe derived
by autodiff.

The reference pipelines an MLP with a hand-written instruction stream:
explicit FWD/BWD instructions, Send/Recv hops, per-microbatch stashes
(`/root/reference/shallowspeed/pipe.py:184-299,330-466`). The MLP family
here keeps that shape (`parallel/worker.py`, `parallel/spmd_pipeline.py`
with hand-written VJPs). This engine pipelines the *transformer* the most
TPU-native way available:

- **One SPMD program.** Inside a single `shard_map` over ('dp', 'pp')
  — or ('dp', 'pp', 'tp') — every device runs the same tick loop
  (`lax.scan`); stage identity is `lax.axis_index('pp')`, activations
  hop right via `lax.ppermute` each tick. Transformer blocks are
  homogeneous, so per-stage params are just the stacked block pytree
  sharded `P('pp')` on the layer axis — no padding/masking gymnastics
  (contrast the heterogeneous-width MLP, `spmd_pipeline.py`). With a tp
  axis, each stage's blocks additionally take the Megatron placement
  (qkv/up column-sharded into whole head groups, proj/down row-sharded
  with an explicit `lax.psum` over 'tp' — hand-placed, since GSPMD does
  not see inside shard_map), composing data x pipeline x tensor
  parallelism in one compiled program.
- **The backward pipeline is DERIVED, not scheduled.** `jax.value_and_grad`
  differentiates through the tick scan: the transpose of `ppermute` is the
  reverse ppermute, the transpose of the scan is the reversed-tick scan —
  i.e. exactly GPipe's all-FWD-then-all-BWD schedule with reversed
  microbatch order (`pipe.py:234-235`), including the per-microbatch
  activation stash (the scan's saved residuals). The reference hand-codes
  ~300 lines of schedule + stash bookkeeping; here it is the transpose of
  30.
- **Timing invariant.** At tick t, stage s handles microbatch m = t - s;
  stage s+1 consumes at t+1 what stage s produced at t, so valid data
  always arrives on time. Inactive ticks compute on don't-care values
  whose loss contribution is masked to zero — autodiff therefore sends
  them zero cotangents, and they contribute nothing to gradients.
- **Gradient reduction by variance typing.** Block params enter sharded
  over 'pp' (dp-invariant): their gradient transpose inserts the psum
  over 'dp' only. Embeddings/head enter replicated: their transpose
  psums over ('dp', 'pp'). The DP all-reduce the reference interleaves
  by hand (`pipe.py:302-327`) is, again, the transpose of a broadcast.

A second compiled schedule, **1F1B / PipeDream-Flush** (`schedule=
"1f1b"`), hand-schedules what GPipe leaves to autodiff. The reference
declares PipeDream but crashes on it (`pipe.py:297-299`); the pipeline
VM here runs it interpreted (`parallel/worker.py`); this is the
fully-compiled SPMD form:

- **Closed-form conflict-free slots.** Stage s runs FWD of microbatch m
  at tick `2m + s` and BWD at tick `2m + 2pp - 1 - s`. The two families
  never collide (their difference is odd), every send is consumed
  exactly one tick later (no rx queues), and the total tick count,
  `2(n_mu + pp - 1)`, equals GPipe's fwd+bwd ticks — same bubble, same
  compute.
- **Bounded activation memory.** The backward recomputes each stage from
  a stashed *stage input* (`jax.vjp` per tick), so the stash holds at
  most `min(pp, n_mu)` microbatch inputs — the 1F1B in-flight bound —
  instead of GPipe's `n_mu + pp - 1` saved tick residuals. Microbatch
  count no longer costs memory: crank n_mu to shrink the bubble.
- **Ticks skip, not mask.** Each tick gates its F and B halves behind
  `lax.cond`, so inactive slots cost nothing; only the two `ppermute`
  hops (activations right, cotangents left) run unconditionally, as
  collectives must.

Composes with mixed precision (`compute_dtype`) and remat (recompute each
stage's blocks in the backward).

Round-3 composability (VERDICT r2 item 3 — the reference composed
everything it had, `/root/reference/train.py:75-94`):

- **MoE x pp**: expert weights are per-block pytree leaves, so stacking
  blocks stacks them too and `P('pp')` shards whole stages of experts;
  routing runs within the stage. Every stage contributes its blocks'
  balance/z aux losses — accumulated per tick (masked by activity) and
  psum'd over 'pp' with the NLL, in both schedules (in 1F1B the aux
  rides the same per-tick vjp as the NLL: the cotangent seed is fanned
  to every stage, not just the last).
- **sp x pp** (long context in the pipeline): a ('dp', 'pp', 'sp') mesh
  shards each microbatch's SEQUENCE over 'sp' inside the stage; the
  stage's attention substrate is ring / ring-flash / ulysses-flash over
  'sp' (`attn=` ctor arg), positions are global (each sp peer offsets by
  its tile), and the inter-stage ppermute hops carry only the local
  (mubs, T/sp, d) tile. Pipeline-parallel 65k-token training no longer
  requires re-gathering sequences.

tp x sp in one mesh remains out of scope here (the GSPMD composite
engine covers that pairing); MoE composes with dp/pp/sp in this engine
and with dp/ep in `parallel/expert.py`.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# version-compat shard_map (utils.py): VMA jax as-is; pre-VMA jax
# with the legacy replication rewriter disabled
from shallowspeed_tpu.utils import shard_map

from shallowspeed_tpu.models import transformer as T
from shallowspeed_tpu.ops.attention import attention
from shallowspeed_tpu.utils import pvary_over as _pvary

tree_map = jax.tree_util.tree_map


def _note_step(engine, pack):
    # health.note_step, imported lazily (telemetry stays off the module
    # import path): stores last_health + device-side cumulative counters
    from shallowspeed_tpu.telemetry.health import note_step

    note_step(engine, pack)



def stack_blocks(params: dict) -> dict:
    """blocks: list of per-layer dicts -> one dict with a leading layer
    axis on every leaf (the axis that shards over 'pp')."""
    blocks = params["blocks"]
    stacked = tree_map(lambda *ls: jnp.stack(ls), *blocks)
    return {**{k: v for k, v in params.items() if k != "blocks"},
            "blocks": stacked}


def unstack_blocks(params: dict, n_layers: int) -> dict:
    """Inverse of `stack_blocks` (canonical checkpoint layout)."""
    stacked = params["blocks"]
    blocks = [tree_map(lambda l: l[i], stacked) for i in range(n_layers)]
    return {**{k: v for k, v in params.items() if k != "blocks"},
            "blocks": blocks}


class PipelineLMEngine:
    """GPipe-parallel transformer trainer over a ('dp', 'pp') or
    ('dp', 'pp', 'tp') mesh — with the tp axis, each pipeline stage's
    blocks are additionally Megatron-sharded (explicit psum over 'tp'
    inside the shard_map, since GSPMD is not in play here), composing
    data, pipeline, and tensor parallelism in one compiled program.

    tokens/targets: (B, T) with B sharded over dp; each dp shard is split
    into `n_mubatches` microbatches that stream through the pp stages.

    `schedule` picks the compiled pipeline schedule: "gpipe" (all-FWD
    then all-BWD, backward derived by autodiff) or "1f1b"
    (PipeDream-Flush: hand-scheduled slots, `min(pp, n_mu)`-deep
    stage-input stash, backward rebuilt per tick with `jax.vjp`).
    """

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 n_mubatches: int = 4, seed: int = 0,
                 schedule: str = "gpipe", attn: str = "xla",
                 virtual_pp: int = 1, zero1: bool = False,
                 zero2: bool = False, fsdp: bool = False,
                 health: str = "off"):
        from shallowspeed_tpu.telemetry.health import MODES

        assert health in MODES, health
        self.health = health
        self.last_health = None
        assert mesh.axis_names in (("dp", "pp"), ("dp", "pp", "tp"),
                                   ("dp", "pp", "sp"),
                                   ("dp", "pp", "ep")), (
            f"PipelineLMEngine expects a ('dp','pp'[,'tp'|'sp'|'ep']) "
            f"mesh, got {mesh.axis_names}")
        assert schedule in ("gpipe", "1f1b", "zb"), schedule
        if schedule == "zb":
            # ZB-H1 (round 5): the compiled zero-bubble schedule. The
            # hand-split B/W backward (parallel/zb.py) covers the dense
            # collective-free block family; each exclusion below states
            # its mechanism (pinned in tests/test_pipeline_zb.py):
            assert mesh.axis_names == ("dp", "pp"), (
                "schedule='zb' runs on a ('dp','pp') mesh — tp/sp/ep "
                "put collectives inside the per-round lax.switch "
                "branches (the same de-sync hazard 1F1B documents for "
                "cond-gated halves)")
            assert virtual_pp == 1, (
                "schedule='zb' composes with vpp=1 (interleaved chunks "
                "would need per-chunk B/W tables; not built)")
            assert cfg.n_experts == 0, (
                "schedule='zb' needs the dense block family (the MoE "
                "dispatch/combine backward is not hand-split)")
            assert cfg.dropout == 0.0 and cfg.attn_dropout == 0.0, (
                "schedule='zb' trains without dropout (the hand-split "
                "backward does not thread mask keys F->B)")
            assert attn in ("xla", "flash"), (
                "schedule='zb' supports the xla/flash substrates "
                "(sequence stays whole inside a stage)")
            assert not cfg.remat, (
                "schedule='zb' IS the no-recompute schedule: it stashes "
                "block residuals F->B by design (remat would undo the "
                "B=1 cost the schedule needs)")
            # zero2/fsdp compose (round 5, same day it shipped): the zb
            # scan accumulates raw per-device partials and takes the
            # identical grad_reduce substitution the 1F1B scan does, so
            # the dp reduce-scatter drops in unchanged (parity tests in
            # tests/test_pipeline_zb.py)
        assert virtual_pp >= 1, virtual_pp
        assert attn in ("xla", "flash", "ring", "ring-flash",
                        "ulysses-flash"), attn
        self.schedule = schedule
        self.attn = attn
        self.cfg = cfg
        self.mesh = mesh
        self.dp, self.pp = mesh.devices.shape[:2]
        self.has_tp = mesh.axis_names[2:] == ("tp",)
        self.has_sp = mesh.axis_names[2:] == ("sp",)
        self.has_ep = mesh.axis_names[2:] == ("ep",)
        self.tp = mesh.devices.shape[2] if self.has_tp else 1
        self.sp = mesh.devices.shape[2] if self.has_sp else 1
        self.ep = mesh.devices.shape[2] if self.has_ep else 1
        if self.has_ep and self.ep > 1:
            # ep x pp (round 4): expert weights shard over 'ep' inside
            # each pipeline stage; tokens shard over ('dp','ep') and the
            # stage-local dispatch is the explicit all-to-all pair
            # (ops.moe.moe_ffn_ep — shard_map has no GSPMD to lower the
            # resharding). The ep axis is a DATA axis for every
            # non-expert parameter (grads reduce over dp AND ep).
            assert cfg.n_experts > 0, (
                "an 'ep' mesh axis needs n_experts > 0")
            assert cfg.n_experts % self.ep == 0, (
                f"n_experts={cfg.n_experts} must divide over "
                f"ep={self.ep}")
            assert attn in ("xla", "flash"), (
                f"ep composes with the xla/flash attention substrates "
                f"(sequence stays whole inside the stage), got {attn!r}")
        if self.has_sp and self.sp > 1:
            assert attn in ("ring", "ring-flash", "ulysses-flash"), (
                f"sp>1 needs a sequence-parallel attention substrate "
                f"(ring / ring-flash / ulysses-flash), got {attn!r}")
        if attn in ("ring", "ring-flash", "ulysses-flash"):
            assert self.has_sp, (
                f"attn={attn!r} collects over an 'sp' mesh axis; this "
                f"mesh is {mesh.axis_names} (use attn='xla' or 'flash')")
        if attn == "ulysses-flash":
            assert cfg.n_heads % self.sp == 0 and \
                cfg.kv_heads % self.sp == 0, (
                    "ulysses-flash needs head counts divisible by sp")
        assert cfg.attn_dropout == 0.0, (
            "attention-probability dropout is not available in the "
            "pipeline engine (plain-substrate only; see "
            "TransformerConfig.attn_dropout)")
        assert cfg.n_experts == 0 or not self.has_tp, (
            "MoE x tp is not supported in the pipeline engine: the "
            "Megatron placement has no expert-dimension rule, so tp "
            "peers would each run the FULL routed FFN on identical "
            "inputs — a correct program that silently wastes the tp "
            "axis's FLOPs. Expert scaling is the ep axis's job (MoE "
            "composes with dp/pp/sp here, dp/ep in parallel/expert.py)")
        self.vpp = virtual_pp
        if virtual_pp > 1:
            # interleaved virtual stages: device d hosts logical stages
            # {d, d+pp, ...}. GPipe: the chunk hops are a plain ring
            # (cond-gated chunk compute). 1F1B: the engine follows the
            # verified greedy contention schedule as static per-round
            # tables (verify.interleaved_tables — round 4). Either way
            # chunk bodies must be collective-free:
            # tp composes (round 5): the chunk-gating predicate depends
            # only on (tick, pp coordinate), so every tp peer takes the
            # SAME cond branch and the Megatron psums inside stay
            # schedule-identical — unlike sp/ep, whose ring/all-to-all
            # members span the gated axis (the measured 1F1B x sp
            # corruption hazard documented in local_1f1b).
            assert self.sp == 1 and self.ep == 1, (
                "virtual_pp needs sp/ep-collective-free chunk bodies "
                "(an sp ring / ep all-to-all inside a cond-gated chunk "
                "de-syncs the collective schedule across branches; tp "
                "composes — its psum peers share the gate predicate)")
            assert cfg.n_layers % (self.pp * virtual_pp) == 0, (
                f"n_layers={cfg.n_layers} must divide over "
                f"pp*virtual_pp={self.pp * virtual_pp}")
        assert cfg.n_layers % self.pp == 0, (
            f"n_layers={cfg.n_layers} must be divisible by pp={self.pp}")
        assert cfg.n_heads % self.tp == 0, (
            f"n_heads={cfg.n_heads} must be divisible by tp={self.tp}")
        assert cfg.kv_heads % self.tp == 0, (
            f"n_kv_heads={cfg.kv_heads} must be divisible by tp={self.tp}")
        assert cfg.ffn_dim % self.tp == 0
        assert sum((zero1, zero2, fsdp)) <= 1, (
            "pick ONE of zero1 / zero2 / fsdp (each subsumes the last)")
        self.zero1, self.zero2, self.fsdp = zero1, zero2, fsdp
        if zero1 or zero2 or fsdp:
            assert self.dp > 1, (
                "--zero1/--zero2/--fsdp shard over dp; need dp > 1")
        if zero2 or fsdp:
            # tp composes (round 4): the dp reduce-scatter/all-gather
            # acts on each leaf's ZeRO dim while tp reductions stay
            # with variance-typed autodiff, and zero2_grad_specs picks
            # a free (non-'pp'/'tp') dim per leaf. sp composes (round
            # 5): the uniform-execution 1F1B path's post-scan partials
            # reduce per leaf over grad_psum_axes minus 'dp' (the 'sp'
            # sum) before the dp reduce-scatter — the same per-leaf
            # shape as the tp case. Virtual stages compose too (the
            # interleaved scan takes the same grad_reduce
            # substitution). ep stays out: expert leaves' grads are
            # ep-SHARDED (not ep-partial), so the ZeRO dim choice and
            # the scatter would have to be expert-aware
            # (tests/test_zero2.py pins this decision).
            assert not self.has_ep, (
                "zero2/fsdp x pp support ('dp','pp'[,'tp'|'sp']) "
                "meshes and virtual stages (no ep axis: expert-leaf "
                "grads are ep-sharded, which the per-leaf ZeRO "
                "dim/scatter rule does not describe)")
        self.n_mu = n_mubatches
        self.l_local = cfg.n_layers // self.pp
        self.optimizer = optimizer
        self._seed = seed
        self._step_count = 0

        self.rep = NamedSharding(mesh, P())
        self.row = NamedSharding(mesh, P("dp"))
        # interleaved placement permutation: stacked position
        # d*(vpp*Lc) + v*Lc + j holds layer (v*pp + d)*Lc + j, so the
        # P('pp') shard of device d is exactly its vpp chunks in order.
        # Identity when vpp == 1.
        lc = cfg.n_layers // (self.pp * self.vpp)
        self._perm = np.array([
            (v * self.pp + d) * lc + j
            for d in range(self.pp)
            for v in range(self.vpp)
            for j in range(lc)])
        self._inv_perm = np.argsort(self._perm)
        host = stack_blocks(T.init(cfg, seed))
        if self.vpp > 1:
            host = {**host, "blocks": tree_map(
                lambda l: l[self._perm], host["blocks"])}
        # stacked blocks shard their layer axis over pp; with a tp axis the
        # feature dims additionally take the Megatron placement (qkv/up
        # column-sharded — whole head groups, thanks to the head-major
        # fused qkv layout — proj/down row-sharded, their biases applied
        # once after the tp psum). Embeddings/head replicate.
        if self.has_tp:
            col = {"W": P("pp", None, "tp"), "b": P("pp", "tp")}
            rowp = {"W": P("pp", "tp", None), "b": P("pp")}
            ln = {"g": P("pp"), "b": P("pp")}
            attn_proj = ({"q": col, "kv": col} if cfg.gqa
                         else {"qkv": col})
            blocks_spec = {"ln1": ln, **attn_proj, "proj": rowp,
                           "ln2": ln, "up": col, "down": rowp}
            if cfg.ffn == "swiglu":
                blocks_spec = {**blocks_spec, "gate": col}
        elif self.has_ep and "moe" in host["blocks"]:
            # expert leaves (stacked (L, E, ...)) additionally shard the
            # expert axis over 'ep'; the router gate replicates over ep
            # (every token routes over all E global experts). A dense
            # model on an ep-size-1 mesh keeps the plain P('pp') specs
            # (the ep axis is then purely a data axis).
            blocks_spec = tree_map(lambda _: P("pp"), host["blocks"])
            blocks_spec["moe"] = {
                "gate": P("pp"), "wi": P("pp", "ep"), "bi": P("pp", "ep"),
                "wo": P("pp", "ep"), "bo": P("pp", "ep")}
        else:
            blocks_spec = tree_map(lambda _: P("pp"), host["blocks"])
        self._pspecs = {
            "tok_emb": P(), "pos_emb": P(), "ln_f": {"g": P(), "b": P()},
            "blocks": blocks_spec,
        }
        if not cfg.tie_embeddings:
            self._pspecs["head"] = {"W": P(), "b": P()}
        if fsdp:
            # ZeRO-3-style: the RESTING placement adds 'dp' to every
            # leaf's first free divisible dim (zero.py's rule) — master
            # params, and through init-inheritance the moments, live
            # 1/dp per device; the step gathers each stage's params
            # transiently and reduce-scatters the grads back.
            from shallowspeed_tpu.parallel.zero import zero2_grad_specs

            tmp = jax.device_put(
                host, tree_map(lambda s: NamedSharding(mesh, s),
                               self._pspecs,
                               is_leaf=lambda x: isinstance(x, P)))
            self._store_specs = zero2_grad_specs(tmp, mesh)
            self.params = jax.device_put(
                host, tree_map(lambda s: NamedSharding(mesh, s),
                               self._store_specs,
                               is_leaf=lambda x: isinstance(x, P)))
        else:
            self._store_specs = self._pspecs
            self.params = jax.device_put(
                host, tree_map(lambda s: NamedSharding(mesh, s),
                               self._pspecs,
                               is_leaf=lambda x: isinstance(x, P)))
        template = optimizer.init(self.params)
        self.opt_state = tree_map(
            lambda l: l if isinstance(getattr(l, "sharding", None),
                                      NamedSharding)
            else jax.device_put(l, self.rep), template)
        self._opt_specs = tree_map(
            lambda l: (l.sharding.spec
                       if isinstance(getattr(l, "sharding", None),
                                     NamedSharding) else P()),
            self.opt_state)
        self._build()

    # ---------------------------------------------------------------- build

    def _build(self):
        import copy

        cfg = self.cfg
        pp, n_mu = self.pp, self.n_mu
        # block grads are sharded over 'pp' (and feature-sharded over 'tp')
        # inside the shard_map step: the clipping norm psums each leaf over
        # exactly the axes it varies on (VMA-aware global_norm); private
        # copy, caller's optimizer untouched
        opt = copy.copy(self.optimizer)
        opt.clip_axes = (("pp", "tp") if self.has_tp else
                         ("pp", "ep") if self.has_ep else ("pp",))
        right = [(i, (i + 1) % pp) for i in range(pp)]
        heads_local = cfg.n_heads // self.tp
        kv_local = cfg.kv_heads // self.tp
        hd = cfg.head_dim

        if self.has_tp:
            # Megatron conjugate pair (utils.py): psum_tp after the
            # row-parallel matmuls, enter_tp where the replicated
            # residual stream feeds column-parallel compute. On VMA jax
            # enter_tp is identity and psum_tp a plain lax.psum; on
            # pre-VMA jax both carry explicit custom VJPs — autodiff
            # straight through a bare psum there double-counted the
            # sharded-weight grads tp x and left the replicated-param
            # cotangents shard-partial (caught by the health pack's
            # oracle parity, round 7).
            from shallowspeed_tpu.utils import tp_allreduce, tp_region_enter

            def psum_tp(x):
                return tp_allreduce(x, "tp")

            def enter_tp(x):
                return tp_region_enter(x, "tp")
        else:
            def psum_tp(x):
                return x

            def enter_tp(x):
                return x

        w = cfg.attn_window  # windows compose with every substrate
        if self.attn == "flash":
            # the fused Pallas kernel drops into the stage block
            # unchanged: per-device heads, full (unsharded) microbatch
            # sequence — and its custom VJP composes with both backward
            # derivations (autodiff through the GPipe scan, per-tick
            # jax.vjp in 1F1B)
            from shallowspeed_tpu.ops.flash_attention import (
                flash_attention)

            def attn_fn(q, k, v):
                return flash_attention(q, k, v, causal=True, window=w)
        elif self.attn == "ring":
            from shallowspeed_tpu.ops.attention import ring_attention

            def attn_fn(q, k, v):
                return ring_attention(q, k, v, axis_name="sp",
                                      causal=True, window=w)
        elif self.attn == "ring-flash":
            from shallowspeed_tpu.ops.flash_attention import (
                ring_flash_attention)

            def attn_fn(q, k, v):
                return ring_flash_attention(q, k, v, axis_name="sp",
                                            causal=True, window=w)
        elif self.attn == "ulysses-flash":
            from shallowspeed_tpu.ops.attention import ulysses_attention

            def attn_fn(q, k, v):
                return ulysses_attention(q, k, v, axis_name="sp",
                                         causal=True, window=w,
                                         use_flash=True)
        else:

            def attn_fn(q, k, v):
                return attention(q, k, v, causal=True, window=w)

        def mega_block(blk, x, pos, key=None):
            """One pre-LN block on this device's tp shard: qkv/up columns
            hold `heads_local` whole heads / `4d/tp` neurons, proj/down
            rows are partial-summed over 'tp' (one all-reduce per matmul
            pair, Megatron placement). With tp absent this is exactly
            `T._block`'s dense path (plus the MoE branch). `pos` is this
            tile's GLOBAL positions (offset under sp sharding). `key`
            (training only) seeds the attention/FFN dropout; it is
            tp-invariant by construction, so every tp peer draws the SAME
            mask on the (full-size) residual stream — required for the
            psum'd partial sums to stay exact. Returns (x, weighted aux):
            the block's balance/z losses, pre-weighted so the caller just
            accumulates a scalar (0.0 for dense blocks)."""
            b, t, d = x.shape
            k_attn = k_ffn = None
            if key is not None and cfg.dropout > 0.0:
                k_attn, k_ffn = jax.random.split(key)
            h = enter_tp(T._norm(blk["ln1"], x, cfg))
            if cfg.gqa:  # split projections; each shard owns whole groups
                q = (h @ blk["q"]["W"] + blk["q"]["b"]).reshape(
                    b, t, heads_local, hd)
                kv = (h @ blk["kv"]["W"] + blk["kv"]["b"]).reshape(
                    b, t, kv_local, 2, hd)
                k, v = kv[..., 0, :], kv[..., 1, :]
            else:
                qkv = (h @ blk["qkv"]["W"] + blk["qkv"]["b"]).reshape(
                    b, t, heads_local, 3, hd)
                q, k, v = qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]
            if cfg.rope:
                q = T.rope_rotate(q, pos, cfg.rope_theta)
                k = T.rope_rotate(k, pos, cfg.rope_theta)
            # group factor is tp-invariant (both head counts divide by
            # tp); all substrates consume unrepeated GQA heads natively
            a = attn_fn(q, k, v).reshape(b, t, heads_local * hd)
            # selective-remat tag: policies "attn"/"dots" save this value
            # so the backward replay skips the attention substrate
            a = T._checkpoint_name(a, "attn_out")
            x = x + T._dropout(
                psum_tp(a @ blk["proj"]["W"]) + blk["proj"]["b"],
                cfg.dropout, k_attn)
            h = enter_tp(T._norm(blk["ln2"], x, cfg))
            aux = jnp.float32(0.0)
            if cfg.n_experts > 0:
                from shallowspeed_tpu.ops.moe import moe_ffn, moe_ffn_ep

                if self.has_ep and self.ep > 1:
                    y, bal, z, _ = moe_ffn_ep(
                        blk["moe"], h, cfg.moe_top_k,
                        cfg.moe_capacity_factor, axis_name="ep",
                        priority=cfg.moe_routing == "priority")
                else:
                    y, bal, z, _ = moe_ffn(
                        blk["moe"], h, cfg.moe_top_k,
                        cfg.moe_capacity_factor,
                        priority=cfg.moe_routing == "priority")
                aux = (cfg.moe_aux_weight * bal
                       + cfg.moe_z_weight * z).astype(jnp.float32)
                return x + T._dropout(y, cfg.dropout, k_ffn), aux
            if cfg.ffn == "swiglu":
                # gate/up share the same column partition, so the
                # elementwise product is local to each tp shard
                u = (jax.nn.silu(h @ blk["gate"]["W"] + blk["gate"]["b"])
                     * (h @ blk["up"]["W"] + blk["up"]["b"]))
            else:
                u = jax.nn.gelu(h @ blk["up"]["W"] + blk["up"]["b"])
            return x + T._dropout(
                psum_tp(u @ blk["down"]["W"]) + blk["down"]["b"],
                cfg.dropout, k_ffn), aux

        def apply_blocks(blocks, x, pos, key=None):
            """This stage's l_local blocks; optionally rematerialized.
            `key` is this (microbatch, stage)'s dropout key — split into
            one key per block; explicit keys mean remat (and the 1F1B
            vjp recompute) regenerate bit-identical masks. Returns
            (x, summed weighted aux of this stage's blocks)."""
            # MoE aux derives from the (mesh-varying) activations, so its
            # scan carry must start with the matching variance type;
            # dense aux stays the invariant constant 0.0
            aux0 = (_pvary(jnp.float32(0.0), act_axes)
                    if cfg.n_experts > 0 else jnp.float32(0.0))
            if key is None:
                def body(carry, blk):
                    h, aux = carry
                    h, a = mega_block(blk, h, pos)
                    return (h, aux + a), None

                if cfg.remat:
                    body = jax.checkpoint(
                        body, policy=T._remat_policy(cfg))
                (x, aux), _ = jax.lax.scan(body, (x, aux0), blocks)
                return x, aux

            def body(carry, xs):
                h, aux = carry
                blk, k = xs
                h, a = mega_block(blk, h, pos, k)
                return (h, aux + a), None

            if cfg.remat:
                body = jax.checkpoint(body, policy=T._remat_policy(cfg))
            n_blk = jax.tree_util.tree_leaves(blocks)[0].shape[0]
            keys = jax.random.split(key, n_blk)
            (x, aux), _ = jax.lax.scan(
                body, (x, aux0), (blocks, keys))
            return x, aux

        has_ep = self.has_ep and self.ep > 1

        def mu_key(base, m):
            """Per-(step, microbatch, dp-tile, stage) dropout key — the
            SAME derivation in the GPipe and 1F1B builds, so the two
            schedules produce bit-identical masks (asserted in tests).
            With an ep axis the rows are ep-sharded too, so the ep
            coordinate folds in (ep=1 keeps the exact legacy stream)."""
            if base is None:
                return None, None
            k = jax.random.fold_in(
                jax.random.fold_in(base, m), jax.lax.axis_index("dp"))
            if has_ep:
                k = jax.random.fold_in(k, jax.lax.axis_index("ep"))
            k_stage = jax.random.fold_in(k, jax.lax.axis_index("pp"))
            k_emb = jax.random.fold_in(k, pp)  # stage ids are < pp
            return k_stage, k_emb

        sp = self.sp
        act_axes = (("pp", "dp", "sp") if self.has_sp else
                    ("pp", "dp", "ep") if self.has_ep else ("pp", "dp"))
        # the mesh axes that shard DATA rows: loss partials pmean over
        # these; non-expert grads reduce over them (plus 'pp' by spec)
        data_axes = ("dp", "ep") if self.has_ep else ("dp",)

        def tile_pos(t_local):
            """GLOBAL positions of this device's sequence tile (sp shards
            the sequence; without an sp axis this is 0..t)."""
            if self.has_sp:
                return jax.lax.axis_index("sp") * t_local \
                    + jnp.arange(t_local)
            return jnp.arange(t_local)

        def head_nll(params_c, hf, tgt_m, train=True):
            """Final-norm output -> mean token NLL over the LOCAL tile;
            chunked cross-entropy when cfg.xent_chunk (never materializes
            the (mubs*T, vocab) logits on the last stage)."""
            if cfg.xent_chunk > 0:
                return T.chunked_token_loss(params_c, hf, tgt_m, cfg,
                                            train)
            return T.token_loss(T.head_logits(params_c, hf, cfg), tgt_m,
                                cfg, train)

        def local_loss(params, tokens, targets, key=None, train=True):
            """Inside shard_map: tokens/targets (n_mu, mubs, T_local)
            local tiles. Returns this device's PARTIAL of the global
            objective: psum over ('pp'[, 'sp']) of the return value is
            the global mean NLL plus every stage's weighted MoE aux."""
            s = jax.lax.axis_index("pp")
            is_first, is_last = s == 0, s == pp - 1
            mubs, t = tokens.shape[1], tokens.shape[2]
            pos = tile_pos(t)

            def tick(carry, tk):
                cur, loss_acc = carry
                # cast INSIDE the tick: the scan's closed-over consts
                # stay f32, so autodiff's derived backward accumulates
                # each param's per-tick cotangent in an f32 carry (the
                # cast's VJP upcasts per tick). Cast once outside and
                # the grad sum re-rounds to bf16 every tick — the same
                # bug the hand schedules avoid with `a + g.astype(f32)`.
                # XLA hoists the loop-invariant forward cast.
                params_c = T.cast_params(params, cfg.compute_dtype)
                m = jnp.clip(tk - s, 0, n_mu - 1)
                active = (tk - s >= 0) & (tk - s < n_mu)
                tok_m = jax.lax.dynamic_index_in_dim(tokens, m, 0, False)
                k_stage, k_emb = mu_key(key, m)
                x_own = params_c["tok_emb"][tok_m]
                if not cfg.rope:  # rope replaces the learned pos embedding
                    x_own = x_own + params_c["pos_emb"][pos]
                if cfg.compute_dtype is not None:
                    x_own = x_own.astype(cfg.compute_dtype)
                x_own = T._dropout(x_own, cfg.dropout, k_emb)
                x_in = jnp.where(is_first, x_own, cur)
                h, aux = apply_blocks(params_c["blocks"], x_in, pos,
                                      k_stage)
                # last stage: this microbatch's mean token NLL
                hf = T._norm(params_c["ln_f"], h, cfg)
                tgt_m = jax.lax.dynamic_index_in_dim(targets, m, 0, False)
                nll = head_nll(params_c, hf, tgt_m, train)
                # every stage contributes its blocks' aux; only the last
                # contributes the NLL — both masked to active ticks
                contrib = jnp.where(active & is_last, nll, 0.0) \
                    + jnp.where(active, aux, 0.0)
                loss_acc = loss_acc + contrib
                nxt = jax.lax.ppermute(h, "pp", right)
                return (nxt, loss_acc), None

            dt = cfg.compute_dtype or cfg.dtype
            init = _pvary(
                (jnp.zeros((mubs, t, cfg.d_model), dt), jnp.float32(0.0)),
                act_axes)
            (_, loss_sum), _ = jax.lax.scan(
                tick, init, jnp.arange(n_mu + pp - 1))
            # each device's partial: /n_mu averages microbatches, /sp
            # makes the sp tiles' local means (and per-tile aux) average
            # under the caller's psum — mean of equal-sized tiles is exact
            return loss_sum / (n_mu * sp), None

        vpp = self.vpp
        lcv = cfg.n_layers // (pp * vpp)

        def local_loss_virtual(params, tokens, targets, key=None,
                               train=True):
            """Interleaved virtual-stage GPipe (inside shard_map):
            device d runs chunk v as LOGICAL stage v*pp + d; the tick
            hop ppermutes the whole (vpp, ...) chunk buffer around the
            pp ring, and on device 0 the arriving messages shift up one
            chunk (the wrap from the last device feeds the NEXT chunk).
            Chunk compute is cond-gated — bubble ticks cost only the
            hop — which is safe because chunk bodies carry no
            collectives (tp/sp are asserted off for virtual_pp > 1).
            Ticks: n_mu + pp*vpp - 1, each 1/vpp the work of a plain
            GPipe tick — the interleaving bubble shrink
            (`verify.simulate_interleaved` proves the schedule-level
            version). Backward = autodiff of this scan, like GPipe."""
            s = jax.lax.axis_index("pp")
            depth = pp * vpp
            mubs, t = tokens.shape[1], tokens.shape[2]
            pos = jnp.arange(t)
            dt = cfg.compute_dtype or cfg.dtype

            def tick(carry, tk):
                cur, loss_acc = carry      # cur: (vpp, mubs, t, d)
                # cast inside the tick so backward accumulates param
                # cotangents in f32 (see local_loss's tick)
                params_c = T.cast_params(params, cfg.compute_dtype)

                def chunk_blocks(v):
                    return tree_map(lambda l: l[v * lcv:(v + 1) * lcv],
                                    params_c["blocks"])

                outs = []
                for v in range(vpp):       # static unroll over chunks
                    logical = v * pp + s
                    m = jnp.clip(tk - logical, 0, n_mu - 1)
                    active = (tk - logical >= 0) & (tk - logical < n_mu)
                    tok_m = jax.lax.dynamic_index_in_dim(
                        tokens, m, 0, False)
                    tgt_m = jax.lax.dynamic_index_in_dim(
                        targets, m, 0, False)
                    k_stage, k_emb = mu_key(key, m)
                    if k_stage is not None:  # decorrelate chunks
                        k_stage = jax.random.fold_in(k_stage, v)
                    x_own = params_c["tok_emb"][tok_m]
                    if not cfg.rope:
                        x_own = x_own + params_c["pos_emb"][pos]
                    if cfg.compute_dtype is not None:
                        x_own = x_own.astype(cfg.compute_dtype)
                    x_own = T._dropout(x_own, cfg.dropout, k_emb)
                    x_in = jnp.where(logical == 0, x_own, cur[v])

                    def work(x_in, v=v):
                        h, aux = apply_blocks(chunk_blocks(v), x_in,
                                              pos, k_stage)
                        # zero derived from x_in so contrib carries the
                        # (pp, dp)-varying type in EVERY chunk (dense
                        # chunks' aux is an invariant 0.0, which would
                        # type-clash with skip's pvaried zero)
                        contrib = (x_in[0, 0, 0] * 0).astype(
                            jnp.float32) + aux
                        if v == vpp - 1:  # the depth-1 logical stage
                            hf = T._norm(params_c["ln_f"], h, cfg)
                            nll = head_nll(params_c, hf, tgt_m, train)
                            contrib = contrib + jnp.where(
                                s == pp - 1, nll, 0.0)
                        return h, contrib

                    def skip(x_in):
                        return _pvary(
                            (jnp.zeros((mubs, t, cfg.d_model), dt),
                             jnp.float32(0.0)), ("pp", "dp"))

                    h_v, contrib = jax.lax.cond(active, work, skip,
                                                x_in)
                    loss_acc = loss_acc + jnp.where(active, contrib,
                                                    0.0)
                    outs.append(h_v)
                hopped = jax.lax.ppermute(jnp.stack(outs), "pp", right)
                # device 0's arrivals come from the ring wrap: chunk
                # v's output becomes chunk v+1's input (slot 0 is
                # re-embedded anyway)
                cur_next = jnp.where(s == 0,
                                     jnp.roll(hopped, 1, axis=0), hopped)
                return (cur_next, loss_acc), None

            init = _pvary(
                (jnp.zeros((vpp, mubs, t, cfg.d_model), dt),
                 jnp.float32(0.0)), ("pp", "dp"))
            (_, loss_sum), _ = jax.lax.scan(
                tick, init, jnp.arange(n_mu + depth - 1))
            return loss_sum / n_mu, None

        loss_fn = local_loss_virtual if vpp > 1 else local_loss

        def grads_and_loss(params, tokens, targets, key):
            if vpp > 1:
                # pvary params BEFORE differentiating: the virtual path
                # cond-gates chunk compute on a pp-varying predicate,
                # and variance-typed autodiff would otherwise insert
                # the invariant-param cotangent psum INSIDE the branch
                # — devices in different branches then execute different
                # collective sequences and the rendezvous deadlocks
                # (same hazard the 1F1B path documents). Varying params
                # keep cotangents local; the reduction happens once,
                # here (grad_psum_axes is the 1F1B section's per-leaf
                # axis list — identical contract).
                (loss, _), grads = jax.value_and_grad(
                    lambda p: local_loss_virtual(p, tokens, targets,
                                                 key),
                    has_aux=True)(_pvary(params, ("dp", "pp")))
                g_leaves, tdef = jax.tree_util.tree_flatten(grads)
                g_leaves = [jax.lax.psum(g, ax) if ax else g
                            for g, ax in zip(g_leaves, grad_psum_axes)]
                grads = jax.tree_util.tree_unflatten(tdef, g_leaves)
                loss = jax.lax.psum(loss, "pp")
                return jax.lax.pmean(loss, "dp"), grads
            # pvary the params and reduce each leaf EXPLICITLY over the
            # axes it is invariant on (reduce_plain — the same per-leaf
            # contract the 1F1B/zb/vpp paths use). Round 7: this branch
            # used to lean on variance-typed autodiff for the grad
            # reductions, which pre-VMA jax (check_rep=False shim)
            # simply does not have — head/ln_f grads came back as one
            # device's zero partial (never trained) and dp>1 grads
            # stayed per-tile partials; caught by the health pack's
            # oracle parity, invisible to the loss-only parity tests.
            (loss, _), grads = jax.value_and_grad(
                local_loss, has_aux=True)(
                    _pvary(params, vary_axes), tokens, targets, key)
            grads = reduce_plain(grads)
            loss = jax.lax.psum(loss,
                                ("pp", "sp") if self.has_sp else "pp")
            return jax.lax.pmean(loss, data_axes), grads

        # ------------------------------------------- 1F1B (PipeDream-Flush)

        left = [(i, (i - 1) % pp) for i in range(pp)]
        stash_depth = min(pp, n_mu)
        # pvary over (dp, pp[, sp]) ONLY: the per-tick vjp must not
        # auto-psum over those axes (their reduction happens once, after
        # the scan), but 'tp' reductions stay with variance-typed
        # autodiff — it knows exactly which cotangents are tp-partial
        # (ln/bias/embed/inter-stage dx get the Megatron per-microbatch
        # psum) and which are already tp-complete (head, behind the
        # activation psum)
        vary_axes = (("dp", "pp", "sp") if self.has_sp else
                     ("dp", "pp", "ep") if self.has_ep else ("dp", "pp"))

        def _spec_axes(spec: P) -> set:
            used = set()
            for e in spec:
                if e is None:
                    continue
                for a in (e if isinstance(e, tuple) else (e,)):
                    used.add(a)
            return used

        # per-leaf mesh axes a gradient must be summed over = the axes its
        # parameter is invariant on (autodiff's variance typing derives
        # this in the GPipe path; the hand-built backward does it by spec)
        grad_psum_axes = [
            tuple(a for a in vary_axes if a not in _spec_axes(sp))
            for sp in jax.tree_util.tree_leaves(
                self._pspecs, is_leaf=lambda x: isinstance(x, P))]

        def reduce_plain(grads):
            g_leaves, tdef = jax.tree_util.tree_flatten(grads)
            g_leaves = [jax.lax.psum(g, ax) if ax else g
                        for g, ax in zip(g_leaves, grad_psum_axes)]
            return jax.tree_util.tree_unflatten(tdef, g_leaves)

        if self.zero2 or self.fsdp:
            from shallowspeed_tpu.parallel.zero import (zero2_grad_dim,
                                                        zero2_grad_specs)

            # ZeRO-2 gradient layout: each leaf's param spec plus 'dp'
            # on its first free divisible dim — identical rule to the
            # ZeRO-1 moment placement, so the sharded update is local.
            # Under fsdp the params ALREADY rest at that placement, so
            # the grad specs coincide with the storage specs.
            self._gspecs2 = (self._store_specs if self.fsdp else
                             zero2_grad_specs(self.params, self.mesh))
            scatter_dims = [
                zero2_grad_dim(sp_, l.shape, self.dp)
                for sp_, l in zip(
                    jax.tree_util.tree_leaves(
                        self._pspecs,
                        is_leaf=lambda x: isinstance(x, P)),
                    jax.tree_util.tree_leaves(self.params))]

            def reduce_scatter_dp(grads):
                """Raw per-device partials -> dp-SHARDED grads: psum the
                non-dp axes, reduce-scatter 'dp' on the leaf's ZeRO dim
                (plain psum when no dim qualifies — that leaf's update
                stays replicated, like zero.py's placement rule)."""
                g_leaves, tdef = jax.tree_util.tree_flatten(grads)
                out = []
                for g, axes, dim in zip(g_leaves, grad_psum_axes,
                                        scatter_dims):
                    rest = tuple(a for a in axes if a != "dp")
                    if rest:
                        g = jax.lax.psum(g, rest)
                    if "dp" in axes:
                        if dim is not None:
                            g = jax.lax.psum_scatter(
                                g, "dp", scatter_dimension=dim,
                                tiled=True)
                        else:
                            g = jax.lax.psum(g, "dp")
                    out.append(g)
                return jax.tree_util.tree_unflatten(tdef, out)

            self._reduce_scatter_dp = reduce_scatter_dp

        def stage_fwd(params_c, x_in, tok_m, tgt_m, keys=(None, None)):
            """One stage's whole tick on already-cast params: embed (if
            first), this stage's blocks, head + token NLL. Returns
            (h, contrib): contrib = NLL (last stage only — the jnp.where
            routes zero cotangent into the head elsewhere) + this
            stage's weighted MoE aux (EVERY stage — the backward seed is
            fanned to all stages accordingly). Differentiable in
            (params_c, x_in); the same function serves F ticks (primal)
            and B ticks (vjp recompute from the stashed x_in — `keys`
            are derived from the microbatch id, so the recompute draws
            identical dropout masks)."""
            k_stage, k_emb = keys
            s = jax.lax.axis_index("pp")
            t = tok_m.shape[-1]
            pos = tile_pos(t)
            x_own = params_c["tok_emb"][tok_m]
            if not cfg.rope:
                x_own = x_own + params_c["pos_emb"][pos]
            if cfg.compute_dtype is not None:
                x_own = x_own.astype(cfg.compute_dtype)
            x_own = T._dropout(x_own, cfg.dropout, k_emb)
            x = jnp.where(s == 0, x_own, x_in)
            h, aux = apply_blocks(params_c["blocks"], x, pos, k_stage)
            hf = T._norm(params_c["ln_f"], h, cfg)
            nll = head_nll(params_c, hf, tgt_m)
            contrib = jnp.where(s == pp - 1, nll, 0.0) + aux
            return h, contrib

        def local_1f1b(params, tokens, targets, key=None,
                       grad_reduce=None):
            """The full 1F1B batch step body (inside shard_map): returns
            (local-mean loss, accumulated f32 grads). Slot algebra:
            F(s, m) at tick 2m+s, B(s, m) at tick 2m+2pp-1-s — disjoint
            (odd difference), immediate-consumption both directions.
            `grad_reduce` maps the raw per-device partial grads to their
            reduced form (default: psum per grad_psum_axes; the ZeRO-2
            path substitutes a dp reduce-scatter)."""
            s = jax.lax.axis_index("pp")
            is_last = s == pp - 1
            # sp ring hops AND ep all-to-alls live inside stage_fwd;
            # either way the collective schedule must be identical on
            # every device, so the F/B halves run unmasked (see below)
            uniform = self.has_sp or has_ep
            # pvary the cast params to fully-varying BEFORE the vjp:
            # variance-typed autodiff would otherwise auto-psum each
            # invariant param's cotangent inside every B tick (a full
            # grad all-reduce per tick); varying params keep cotangents
            # local, and the one psum after the scan does the reduction
            params_c = _pvary(T.cast_params(params, cfg.compute_dtype),
                              vary_axes)
            mubs, t = tokens.shape[1], tokens.shape[2]
            dt = cfg.compute_dtype or cfg.dtype
            act_shape = (mubs, t, cfg.d_model)

            def zeros_act():
                return jnp.zeros(act_shape, dt)

            def tick(carry, tk):
                x_rx, g_rx, stash, grads, loss_acc = carry

                # ---- F half: fwd microbatch mF, stash its stage input
                f_rel = tk - s
                f_act = (f_rel >= 0) & (f_rel < 2 * n_mu) & (f_rel % 2 == 0)
                mF = jnp.clip(f_rel // 2, 0, n_mu - 1)
                tokF = jax.lax.dynamic_index_in_dim(tokens, mF, 0, False)
                tgtF = jax.lax.dynamic_index_in_dim(targets, mF, 0, False)

                def do_f(x_rx, stash):
                    h, contrib = stage_fwd(params_c, x_rx, tokF, tgtF,
                                           mu_key(key, mF))
                    stash = jax.lax.dynamic_update_index_in_dim(
                        stash, x_rx, mF % stash_depth, 0)
                    return h, contrib, stash

                def skip_f(x_rx, stash):
                    # zeros are axis-invariant; pvary so both cond
                    # branches carry the same variance type
                    return (_pvary((zeros_act(), jnp.float32(0.0)),
                                   vary_axes) + (stash,))

                if uniform:
                    # sp collectives (ring/all-to-all hops) live inside
                    # stage_fwd, and the F/B predicates vary over 'pp':
                    # gating them behind lax.cond de-synchronizes the
                    # collective schedule across branches and SILENTLY
                    # corrupts results (measured: sp=2 pp=2 loss off by
                    # 3%). With an sp axis, every tick therefore executes
                    # both halves unconditionally — the collective
                    # pattern is identical on every device — and masks
                    # results after, GPipe-style.
                    h_out, contrib = stage_fwd(params_c, x_rx, tokF,
                                               tgtF, mu_key(key, mF))
                    stash_new = jax.lax.dynamic_update_index_in_dim(
                        stash, x_rx, mF % stash_depth, 0)
                    stash = jnp.where(f_act, stash_new, stash)
                    h_out = jnp.where(f_act, h_out, 0.0)
                    contrib = jnp.where(f_act, contrib, 0.0)
                else:
                    h_out, contrib, stash = jax.lax.cond(
                        f_act, do_f, skip_f, x_rx, stash)
                loss_acc = loss_acc + jnp.where(f_act, contrib, 0.0)

                # ---- B half: vjp-recompute microbatch mB from the stash
                b_rel = tk - (2 * pp - 1 - s)
                b_act = (b_rel >= 0) & (b_rel < 2 * n_mu) & (b_rel % 2 == 0)
                mB = jnp.clip(b_rel // 2, 0, n_mu - 1)
                tokB = jax.lax.dynamic_index_in_dim(tokens, mB, 0, False)
                tgtB = jax.lax.dynamic_index_in_dim(targets, mB, 0, False)

                def do_b(g_rx, stash):
                    x_saved = jax.lax.dynamic_index_in_dim(
                        stash, mB % stash_depth, 0, False)
                    keysB = mu_key(key, mB)
                    _, vjp = jax.vjp(
                        lambda p, xi: stage_fwd(p, xi, tokB, tgtB, keysB),
                        params_c, x_saved)
                    # every stage seeds its contrib (NLL on the last,
                    # MoE aux everywhere) with 1/(n_mu*sp) — the
                    # transpose of the loss mean over microbatches and
                    # sp tiles; earlier stages additionally receive the
                    # activation cotangent ppermuted in
                    dh = jnp.where(is_last, jnp.zeros_like(g_rx), g_rx)
                    dcontrib = _pvary(jnp.float32(1.0 / (n_mu * sp)),
                                      vary_axes)
                    dp_, dx = vjp((dh, dcontrib))
                    return dp_, dx

                def skip_b(g_rx, stash):
                    return _pvary((tree_map(jnp.zeros_like, params_c),
                                   zeros_act()), vary_axes)

                if uniform:
                    # serialize the B collectives after the F ones (and
                    # below, the hops after both): XLA CPU's in-process
                    # rendezvous cannot tolerate two iterations of the
                    # SAME channel in flight under thread skew — without
                    # these barriers an oversubscribed host aborts in
                    # rendezvous.h (id >= num_threads)
                    g_rx, _ = jax.lax.optimization_barrier(
                        (g_rx, h_out))
                    dparams, dx_out = do_b(g_rx, stash)
                    dx_out = jnp.where(b_act, dx_out, 0.0)
                    grads = tree_map(
                        lambda a, g: a + jnp.where(
                            b_act, g, 0.0).astype(jnp.float32),
                        grads, dparams)
                else:
                    dparams, dx_out = jax.lax.cond(b_act, do_b, skip_b,
                                                   g_rx, stash)
                    grads = tree_map(
                        lambda a, g: a + g.astype(jnp.float32), grads,
                        dparams)

                # ---- comms: activations right, cotangents left — both
                # consumed exactly one tick later by schedule construction
                if uniform:
                    h_hop, _ = jax.lax.optimization_barrier(
                        (h_out, dx_out))
                    x_nxt = jax.lax.ppermute(h_hop, "pp", right)
                    dx_hop, _ = jax.lax.optimization_barrier(
                        (dx_out, x_nxt))
                    g_nxt = jax.lax.ppermute(dx_hop, "pp", left)
                    x_nxt, _ = jax.lax.optimization_barrier(
                        (x_nxt, g_nxt))
                else:
                    x_nxt = jax.lax.ppermute(h_out, "pp", right)
                    g_nxt = jax.lax.ppermute(dx_out, "pp", left)
                return (x_nxt, g_nxt, stash, grads, loss_acc), None

            init = _pvary(
                (zeros_act(), zeros_act(),
                 jnp.zeros((stash_depth,) + act_shape, dt),
                 tree_map(lambda l: jnp.zeros_like(l, jnp.float32),
                          params),
                 jnp.float32(0.0)),
                vary_axes)
            (_, _, _, grads, loss_sum), _ = jax.lax.scan(
                tick, init, jnp.arange(2 * (n_mu + pp - 1)))

            grads = (grad_reduce or reduce_plain)(grads)
            loss = jax.lax.psum(
                loss_sum, ("pp", "sp") if self.has_sp else "pp") \
                / (n_mu * sp)
            if self.has_tp:
                # all tp peers computed the same value, but the pvaried
                # params typed it tp-varying; pmean is exact and re-types
                loss = jax.lax.pmean(loss, "tp")
            return loss, grads

        # ---------------------------- interleaved 1F1B (vpp x 1f1b, round 4)
        #
        # The schedule is NOT a closed form here: stretching the plain
        # slot algebra to depth pp*vpp keeps conflict-freedom but loses
        # the interleaving win (the deep form has 2(n_mu + pp*vpp - 1)
        # ticks with chunk work parity-clustered into half of them — its
        # contention makespan is WORSE than plain 1F1B). Instead the
        # engine follows the greedy device-contention schedule that
        # `verify.simulate_interleaved` proves, lowered by
        # `verify.interleaved_tables` to static per-round arrays: one
        # chunk op (F or B or idle) per device per round, activations
        # hopping right and cotangents left each round (unconditional
        # ppermutes), arrivals/stash routed through interval-colored
        # slot indices (trash slot absorbs idle rounds). What executes
        # IS what the simulator verified — schedule-as-data, compiled.
        # Cost shape: ~vpp x more rounds than plain 1F1B, each 1/vpp the
        # compute; the bubble shrinks by ~vpp (the Megatron interleaving
        # economics), while the full-tree grad accumulate runs per round
        # (vs per tick), which is the overhead to watch at toy widths.
        if self.vpp > 1 and self.schedule == "1f1b":
            from shallowspeed_tpu.parallel.verify import interleaved_tables

            tb = interleaved_tables(n_mu, pp, self.vpp)
            depth_v = pp * self.vpp
            tb_rows = {
                "op": jnp.asarray(tb.op), "chunk": jnp.asarray(tb.chunk),
                "mu": jnp.asarray(tb.mu),
                "act_read": jnp.asarray(tb.act_read),
                "act_write": jnp.asarray(tb.act_write),
                "grad_read": jnp.asarray(tb.grad_read),
                "grad_write": jnp.asarray(tb.grad_write),
                "stash_write": jnp.asarray(tb.stash_write),
                "stash_read": jnp.asarray(tb.stash_read),
            }

            def chunk_fwd_v(params_c, x_in, tok_m, tgt_m, v, l, keys):
                """One CHUNK's tick on cast params: embed where l==0,
                this chunk's lcv blocks (dynamic slice at v*lcv — the
                interleave permutation makes device d's chunks
                contiguous), head NLL where l==depth-1. Differentiable
                in (params_c, x_in); serves F (primal) and B (vjp
                recompute from the stashed x_in)."""
                k_stage, k_emb = keys
                t_loc = tok_m.shape[-1]
                pos = jnp.arange(t_loc)
                x_own = params_c["tok_emb"][tok_m]
                if not cfg.rope:
                    x_own = x_own + params_c["pos_emb"][pos]
                if cfg.compute_dtype is not None:
                    x_own = x_own.astype(cfg.compute_dtype)
                x_own = T._dropout(x_own, cfg.dropout, k_emb)
                x = jnp.where(l == 0, x_own, x_in)
                blocks_v = tree_map(
                    lambda a: jax.lax.dynamic_slice_in_dim(
                        a, v * lcv, lcv), params_c["blocks"])
                h, aux = apply_blocks(blocks_v, x, pos, k_stage)
                hf = T._norm(params_c["ln_f"], h, cfg)
                nll = head_nll(params_c, hf, tgt_m)
                contrib = jnp.where(l == depth_v - 1, nll, 0.0) + aux
                return h, contrib

            def local_1f1b_virtual(params, tokens, targets, key=None,
                                   grad_reduce=None):
                """Interleaved PipeDream-Flush batch step (inside
                shard_map): a scan over the schedule's rounds, each
                executing this device's table entry. Returns
                (local-mean loss, accumulated f32 grads) like
                local_1f1b (including the `grad_reduce` substitution
                the ZeRO-2/FSDP path uses — round 5)."""
                s = jax.lax.axis_index("pp")
                params_c = _pvary(
                    T.cast_params(params, cfg.compute_dtype),
                    ("dp", "pp"))
                mubs = tokens.shape[1]
                t_loc = tokens.shape[2]
                dt = cfg.compute_dtype or cfg.dtype
                act_shape = (mubs, t_loc, cfg.d_model)

                def zeros_act():
                    return jnp.zeros(act_shape, dt)

                def vkey(m, v):
                    ks, ke = mu_key(key, m)
                    if ks is not None:  # decorrelate chunks (as vpp-gpipe)
                        ks = jax.random.fold_in(ks, v)
                    return ks, ke

                def round_fn(carry, row):
                    act_buf, grad_buf, stash, grads, loss_acc = carry
                    op = jnp.take(row["op"], s)
                    v = jnp.take(row["chunk"], s)
                    m = jnp.take(row["mu"], s)
                    l = v * pp + s
                    tok_m = jax.lax.dynamic_index_in_dim(
                        tokens, m, 0, False)
                    tgt_m = jax.lax.dynamic_index_in_dim(
                        targets, m, 0, False)
                    keys = vkey(m, v)
                    x_in = jax.lax.dynamic_index_in_dim(
                        act_buf, jnp.take(row["act_read"], s), 0, False)
                    g_rx = jax.lax.dynamic_index_in_dim(
                        grad_buf, jnp.take(row["grad_read"], s), 0,
                        False)

                    zero_out = _pvary(
                        (zeros_act(), zeros_act(),
                         tree_map(jnp.zeros_like, params_c),
                         jnp.float32(0.0)), ("dp", "pp"))

                    def do_idle(stash):
                        return zero_out + (stash,)

                    def do_f(stash):
                        h, contrib = chunk_fwd_v(params_c, x_in, tok_m,
                                                 tgt_m, v, l, keys)
                        stash2 = jax.lax.dynamic_update_index_in_dim(
                            stash, x_in,
                            jnp.take(row["stash_write"], s), 0)
                        return (h, zero_out[1], zero_out[2], contrib,
                                stash2)

                    def do_b(stash):
                        x_saved = jax.lax.dynamic_index_in_dim(
                            stash, jnp.take(row["stash_read"], s), 0,
                            False)
                        _, vjp = jax.vjp(
                            lambda p, xi: chunk_fwd_v(p, xi, tok_m,
                                                      tgt_m, v, l,
                                                      keys),
                            params_c, x_saved)
                        dh = jnp.where(l == depth_v - 1,
                                       jnp.zeros_like(g_rx), g_rx)
                        dcontrib = _pvary(jnp.float32(1.0 / n_mu),
                                          ("dp", "pp"))
                        dp_, dx = vjp((dh, dcontrib))
                        return (zero_out[0], dx, dp_, zero_out[3],
                                stash)

                    out_act, out_grad, dparams, contrib, stash = \
                        jax.lax.switch(op, [do_idle, do_f, do_b], stash)
                    grads = tree_map(
                        lambda a, g: a + g.astype(jnp.float32), grads,
                        dparams)
                    loss_acc = loss_acc + contrib
                    x_next = jax.lax.ppermute(out_act, "pp", right)
                    g_next = jax.lax.ppermute(out_grad, "pp", left)
                    act_buf = jax.lax.dynamic_update_index_in_dim(
                        act_buf, x_next, jnp.take(row["act_write"], s),
                        0)
                    grad_buf = jax.lax.dynamic_update_index_in_dim(
                        grad_buf, g_next,
                        jnp.take(row["grad_write"], s), 0)
                    return (act_buf, grad_buf, stash, grads,
                            loss_acc), None

                init = _pvary(
                    (jnp.zeros((tb.n_act_slots + 1,) + act_shape, dt),
                     jnp.zeros((tb.n_grad_slots + 1,) + act_shape, dt),
                     jnp.zeros((tb.n_stash_slots + 1,) + act_shape, dt),
                     tree_map(lambda le: jnp.zeros_like(le, jnp.float32),
                              params),
                     jnp.float32(0.0)),
                    ("dp", "pp"))
                (_, _, _, grads, loss_sum), _ = jax.lax.scan(
                    round_fn, init, tb_rows)
                grads = (grad_reduce or reduce_plain)(grads)
                loss = jax.lax.psum(loss_sum, "pp") / n_mu
                return loss, grads

            local_1f1b = local_1f1b_virtual

        # ------------------------------------ ZB-H1 zero-bubble (round 5)
        #
        # The backward splits into B (input cotangents — critical path)
        # and W (weight gradients — deferrable bubble filler), each at
        # F-like cost because NOTHING is recomputed: F stashes the block
        # residuals (parallel/zb.py), B walks the chain from the stash
        # peeling off per-matmul output cotangents ("taps"), W turns
        # stashed inputs x taps into weight grads as batched outer
        # products. The schedule is `verify.simulate_zb`'s verified
        # placement lowered to static per-round tables
        # (`verify.zb_tables`) — schedule-as-data, exactly how the
        # interleaved engine executes. Memory trades the 1F1B
        # recompute-stash for full residual stashes (the ZB paper's
        # deal); slot counts in the tables are measured peaks.
        #
        # Cost caveat (ADVICE r5): zb_stage_fwd/zb_stage_bwd compute
        # the FULL-VOCAB head NLL (and its vjp) on EVERY stage each F/B
        # round, masked to zero off the last stage — correct and
        # SPMD-uniform, exactly like the 1F1B path. At large vocab the
        # head matmul is a growing constant added to every F and B
        # round, which inflates their unit cost beyond the ZB paper's
        # F≈B≈W assumption that the schedule's zero-bubble accounting
        # relies on: expect the realized bubble win to shrink as
        # vocab/d_model grows (the W rounds carry no head work). Gating
        # the head behind the last-stage predicate would fix the FLOPs
        # but put a cond around stage compute — the same de-sync
        # hazard the 1F1B path documents for its uniform mode — so the
        # cost is documented rather than branched away; benchmark
        # regressions at big vocab start here, not in the schedule.
        if self.schedule == "zb":
            from shallowspeed_tpu.parallel import zb as ZB
            from shallowspeed_tpu.parallel.verify import zb_tables

            tbz = zb_tables(n_mu, pp)
            zb_rows = {
                k: jnp.asarray(getattr(tbz, k))
                for k in ("op", "mu", "act_read", "act_write",
                          "grad_read", "grad_write", "resb_write",
                          "resb_read", "resw_write", "resw_read",
                          "resw_read_b", "tap_write", "tap_read")}
            zb_attn_fwd, zb_attn_bwd = ZB.make_attn_core(self.attn, w)

            def head_sub(params_c):
                hp = {"ln_f": params_c["ln_f"]}
                key = "tok_emb" if cfg.tie_embeddings else "head"
                hp[key] = params_c[key]
                return hp

            def zb_stage_fwd(params_c, x_in, tok_m, tgt_m):
                """F: embed (stage 0), blocks with residual stashes,
                head NLL (last stage). Same masking discipline as
                stage_fwd; no dropout by constructor contract."""
                s = jax.lax.axis_index("pp")
                t = tok_m.shape[-1]
                pos = jnp.arange(t)
                x_own = params_c["tok_emb"][tok_m]
                if not cfg.rope:
                    x_own = x_own + params_c["pos_emb"][pos]
                if cfg.compute_dtype is not None:
                    x_own = x_own.astype(cfg.compute_dtype)
                x0 = jnp.where(s == 0, x_own, x_in)
                h, resb_s, resw_s = ZB.stack_fwd(
                    params_c["blocks"], x0, pos, cfg, zb_attn_fwd)
                hf = T._norm(params_c["ln_f"], h, cfg)
                nll = head_nll(params_c, hf, tgt_m)
                contrib = jnp.where(s == pp - 1, nll, 0.0)
                return h, contrib, {"blocks": resb_s, "h": h}, resw_s

            def zb_stage_bwd(params_c, resb, resw, g_rx, tok_m, tgt_m):
                """B: head seed (last stage, via vjp — its weight grads
                are small and land here, not in W), hand-split chain
                through the blocks (taps out), embed backward (stage
                0). Returns (dx_out, taps, small-grads tree)."""
                s = jax.lax.axis_index("pp")
                t = tok_m.shape[-1]
                pos = jnp.arange(t)
                h = resb["h"]
                hp = head_sub(params_c)

                def head_masked(hp_, h_):
                    hf = T._norm(hp_["ln_f"], h_, cfg)
                    nll = head_nll(hp_, hf, tgt_m)
                    return jnp.where(s == pp - 1, nll, 0.0)

                _, pb = jax.vjp(head_masked, hp, h)
                dhp, dh_head = pb(_pvary(jnp.float32(1.0 / n_mu),
                                         vary_axes))
                dh = dh_head + jnp.where(s == pp - 1,
                                         jnp.zeros_like(g_rx), g_rx)
                dx0, taps, dnorm_s = ZB.stack_bwd_x(
                    params_c["blocks"], resb["blocks"], resw, dh, pos,
                    cfg, zb_attn_bwd)

                def emb_masked(ep):
                    x_own = ep["tok_emb"][tok_m]
                    if not cfg.rope:
                        x_own = x_own + ep["pos_emb"][pos]
                    if cfg.compute_dtype is not None:
                        x_own = x_own.astype(cfg.compute_dtype)
                    return jnp.where(s == 0, x_own, 0.0)

                _, pbe = jax.vjp(
                    emb_masked, {"tok_emb": params_c["tok_emb"],
                                 "pos_emb": params_c["pos_emb"]})
                (demb,) = pbe(dx0)
                dx_out = jnp.where(s == 0, jnp.zeros_like(dx0), dx0)
                z = tree_map(jnp.zeros_like, params_c)
                dsmall = dict(z)
                dsmall["blocks"] = {**z["blocks"],
                                    "ln1": dnorm_s["ln1"],
                                    "ln2": dnorm_s["ln2"]}
                dsmall["ln_f"] = dhp["ln_f"]
                if cfg.tie_embeddings:
                    dsmall["tok_emb"] = (demb["tok_emb"]
                                         + dhp["tok_emb"])
                else:
                    dsmall["tok_emb"] = demb["tok_emb"]
                    dsmall["head"] = dhp["head"]
                dsmall["pos_emb"] = demb["pos_emb"]
                return dx_out, taps, dsmall

            def local_zb(params, tokens, targets, key=None,
                         grad_reduce=None):
                """The compiled ZB-H1 batch step (inside shard_map): a
                scan over the verified schedule's rounds, one op per
                device per round, activations hopping right and
                cotangents left every round (slot-buffered); same
                (loss, grads) contract as local_1f1b."""
                s = jax.lax.axis_index("pp")
                params_c = _pvary(
                    T.cast_params(params, cfg.compute_dtype), vary_axes)
                mubs, t = tokens.shape[1], tokens.shape[2]
                dt = cfg.compute_dtype or cfg.dtype
                act_shape = (mubs, t, cfg.d_model)
                pos0 = jnp.arange(t)

                # stash templates via abstract evaluation of the pure
                # stack fns (no tracing cost — shapes only)
                x0s = jax.ShapeDtypeStruct(act_shape, dt)
                _, resb_sh, resw_sh = jax.eval_shape(
                    lambda bl, x: ZB.stack_fwd(
                        bl, _pvary(x, vary_axes), pos0, cfg,
                        zb_attn_fwd),
                    params_c["blocks"], x0s)
                _, taps_sh, _ = jax.eval_shape(
                    lambda bl, rb, rw, g: ZB.stack_bwd_x(
                        bl, rb, rw, _pvary(g, vary_axes), pos0, cfg,
                        zb_attn_bwd),
                    params_c["blocks"], resb_sh, resw_sh, x0s)
                resb_full_sh = {"blocks": resb_sh,
                                "h": jax.ShapeDtypeStruct(act_shape,
                                                          dt)}

                def zeros_of(sh_tree, slots=None):
                    lead = () if slots is None else (slots,)
                    return tree_map(
                        lambda sh: jnp.zeros(lead + sh.shape, sh.dtype),
                        sh_tree)

                def zeros_act():
                    return jnp.zeros(act_shape, dt)

                def round_fn(carry, row):
                    (act_buf, grad_buf, resb_buf, resw_buf, tap_buf,
                     grads, loss_acc) = carry
                    op = jnp.take(row["op"], s)
                    m = jnp.take(row["mu"], s)
                    tok_m = jax.lax.dynamic_index_in_dim(tokens, m, 0,
                                                         False)
                    tgt_m = jax.lax.dynamic_index_in_dim(targets, m, 0,
                                                         False)
                    x_in = jax.lax.dynamic_index_in_dim(
                        act_buf, jnp.take(row["act_read"], s), 0, False)
                    g_rx = jax.lax.dynamic_index_in_dim(
                        grad_buf, jnp.take(row["grad_read"], s), 0,
                        False)
                    resb_in = tree_map(
                        lambda b: jax.lax.dynamic_index_in_dim(
                            b, jnp.take(row["resb_read"], s), 0, False),
                        resb_buf)
                    resw_in_b = tree_map(
                        lambda b: jax.lax.dynamic_index_in_dim(
                            b, jnp.take(row["resw_read_b"], s), 0,
                            False), resw_buf)
                    resw_in_w = tree_map(
                        lambda b: jax.lax.dynamic_index_in_dim(
                            b, jnp.take(row["resw_read"], s), 0, False),
                        resw_buf)
                    tap_in = tree_map(
                        lambda b: jax.lax.dynamic_index_in_dim(
                            b, jnp.take(row["tap_read"], s), 0, False),
                        tap_buf)

                    def zero_out():
                        return _pvary(
                            (zeros_act(), zeros_act(),
                             tree_map(jnp.zeros_like, params_c),
                             jnp.float32(0.0), zeros_of(resb_full_sh),
                             zeros_of(resw_sh), zeros_of(taps_sh)),
                            vary_axes)

                    def do_idle():
                        return zero_out()

                    def do_f():
                        h, contrib, resb_e, resw_e = zb_stage_fwd(
                            params_c, x_in, tok_m, tgt_m)
                        z = zero_out()
                        return (h, z[1], z[2], contrib, resb_e, resw_e,
                                z[6])

                    def do_b():
                        dx, taps, dsmall = zb_stage_bwd(
                            params_c, resb_in, resw_in_b, g_rx, tok_m,
                            tgt_m)
                        z = zero_out()
                        return (z[0], dx, dsmall, z[3], z[4], z[5],
                                taps)

                    def do_w():
                        dense = ZB.stack_bwd_w(resw_in_w, tap_in, cfg)
                        z = zero_out()
                        dgr = dict(z[2])
                        dgr["blocks"] = {**z[2]["blocks"], **dense}
                        return (z[0], z[1], dgr, z[3], z[4], z[5],
                                z[6])

                    (out_act, out_grad, dgrads, contrib, resb_e,
                     resw_e, tap_e) = jax.lax.switch(
                        op, [do_idle, do_f, do_b, do_w])
                    grads = tree_map(
                        lambda a, g: a + g.astype(jnp.float32), grads,
                        dgrads)
                    loss_acc = loss_acc + contrib
                    x_next = jax.lax.ppermute(out_act, "pp", right)
                    g_next = jax.lax.ppermute(out_grad, "pp", left)
                    act_buf = jax.lax.dynamic_update_index_in_dim(
                        act_buf, x_next, jnp.take(row["act_write"], s),
                        0)
                    grad_buf = jax.lax.dynamic_update_index_in_dim(
                        grad_buf, g_next,
                        jnp.take(row["grad_write"], s), 0)
                    resb_buf = tree_map(
                        lambda b, e: jax.lax.dynamic_update_index_in_dim(
                            b, e, jnp.take(row["resb_write"], s), 0),
                        resb_buf, resb_e)
                    resw_buf = tree_map(
                        lambda b, e: jax.lax.dynamic_update_index_in_dim(
                            b, e, jnp.take(row["resw_write"], s), 0),
                        resw_buf, resw_e)
                    tap_buf = tree_map(
                        lambda b, e: jax.lax.dynamic_update_index_in_dim(
                            b, e, jnp.take(row["tap_write"], s), 0),
                        tap_buf, tap_e)
                    return (act_buf, grad_buf, resb_buf, resw_buf,
                            tap_buf, grads, loss_acc), None

                init = _pvary(
                    (jnp.zeros((tbz.n_act_slots + 1,) + act_shape, dt),
                     jnp.zeros((tbz.n_grad_slots + 1,) + act_shape, dt),
                     zeros_of(resb_full_sh, tbz.n_resb_slots + 1),
                     zeros_of(resw_sh, tbz.n_resw_slots + 1),
                     zeros_of(taps_sh, tbz.n_tap_slots + 1),
                     tree_map(lambda le: jnp.zeros_like(le,
                                                        jnp.float32),
                              params),
                     jnp.float32(0.0)),
                    vary_axes)
                (_, _, _, _, _, grads, loss_sum), _ = jax.lax.scan(
                    round_fn, init, zb_rows)
                grads = (grad_reduce or reduce_plain)(grads)
                loss = jax.lax.psum(loss_sum, "pp") / n_mu
                return loss, grads

            local_1f1b = local_zb

        pspecs, ospecs = self._pspecs, self._opt_specs
        use_1f1b = self.schedule in ("1f1b", "zb")
        seed = self._seed
        health = self.health

        def make_pack(params, grads, grad_specs, param_specs):
            """The health pack for this engine's reduced grads
            (telemetry/health.py): each leaf's statistic psums over the
            axes its spec shards — 'pp' block stacks (incl. the zb /
            interleaved-vpp permuted stacks, which still partition the
            params over 'pp'), '+tp'/'+ep' Megatron/expert shards, and
            '+dp' for the ZeRO-2/fsdp scattered layout — so the pack is
            globally correct in-program on every mesh this engine
            takes."""
            from shallowspeed_tpu.telemetry.health import (grad_health,
                                                           spec_axes)

            return grad_health(params, grads,
                               grad_axes=spec_axes(grad_specs),
                               param_axes=spec_axes(param_specs))
        # data specs: microbatch axis unsharded, rows over dp (and over
        # ep when the mesh has one — ep multiplies the data dimension),
        # sequence over sp when the mesh has one
        dspec = (P(None, "dp", "sp") if self.has_sp else
                 P(None, ("dp", "ep")) if self.has_ep else P(None, "dp"))

        def train_key(step):
            if cfg.dropout == 0.0:
                return None
            return jax.random.fold_in(jax.random.PRNGKey(seed), step)

        def _batch_grads(params, tokens, targets, step):
            """Shared gradient body of BOTH step programs: schedule
            dispatch, dp-mean loss, dp-mean gradient (psum'd sums / dp
            — tiles are equal-sized)."""
            key = train_key(step)
            if use_1f1b:
                loss, grads = local_1f1b(params, tokens, targets, key)
                loss = jax.lax.pmean(loss, data_axes)
            else:
                loss, grads = grads_and_loss(params, tokens, targets, key)
            # psum'd sums / shard count = mean over the dp (x ep) data
            # tiles — equal-sized, so the mean is exact
            grads = tree_map(lambda g: g / (self.dp * self.ep), grads)
            return loss, grads

        step_out = ((pspecs, ospecs, P()) if health == "off"
                    else (pspecs, ospecs, P(), P()))

        @partial(jax.jit, donate_argnums=(0, 1))
        @partial(shard_map, mesh=self.mesh,
                 in_specs=(pspecs, ospecs, dspec, dspec, P()),
                 out_specs=step_out)
        def _step(params, opt_state, tokens, targets, step):
            loss, grads = _batch_grads(params, tokens, targets, step)
            if health == "off":
                params, opt_state = opt.step(params, grads, opt_state)
                return params, opt_state, loss
            from shallowspeed_tpu.telemetry.health import (spec_axes,
                                                           update_health)

            pack = make_pack(params, grads, pspecs, pspecs)
            pax = spec_axes(pspecs)
            if health == "guard":
                # all stages see the same (psum'd) sentinel, so the
                # whole pipeline skips in lockstep, bit-identically
                ok = pack["nonfinite"] == 0
                new_p, new_s = opt.guarded_step(params, grads,
                                                opt_state, ok)
                pack = update_health(pack, params, new_p,
                                     param_axes=pax, skipped=1 - ok)
            else:
                new_p, new_s = opt.step(params, grads, opt_state)
                pack = update_health(pack, params, new_p,
                                     param_axes=pax)
            return new_p, new_s, loss, pack

        # ZeRO-1 x pp: the moments shard over 'dp' ON TOP of their
        # pp-sharded block placement (zero.py adds 'dp' to the first
        # free divisible dim), the gradient program stays this engine's
        # shard_map, and the optimizer update becomes a separate GSPMD
        # program pinned to those shardings — each device updates its
        # 1/dp slice of its pipeline stage and XLA all-gathers the new
        # params over 'dp' only (same split-step recipe as the context
        # engine's zero1 path).
        lg_out = ((P(), pspecs) if health == "off"
                  else (P(), pspecs, P()))

        @jax.jit
        @partial(shard_map, mesh=self.mesh,
                 in_specs=(pspecs, dspec, dspec, P()),
                 out_specs=lg_out)
        def _loss_grads(params, tokens, targets, step):
            loss, grads = _batch_grads(params, tokens, targets, step)
            if health == "off":
                return loss, grads
            return loss, grads, make_pack(params, grads, pspecs, pspecs)

        @jax.jit
        @partial(shard_map, mesh=self.mesh,
                 in_specs=(pspecs, dspec, dspec), out_specs=P())
        def _eval(params, tokens, targets):
            loss, _ = loss_fn(params, tokens, targets, train=False)
            loss = jax.lax.psum(loss,
                                ("pp", "sp") if self.has_sp else "pp")
            return jax.lax.pmean(loss, data_axes)

        if self.zero2 or self.fsdp:
            # ZeRO-2 x pp: grads leave the shard_map dp-SHARDED (one
            # reduce-scatter per leaf instead of the all-reduce), leaf-
            # aligned with the ZeRO-1-placed moments, so the GSPMD
            # update below runs fully local and all-gathers params only.
            # GPipe takes the pvaried-params route (like 1F1B) so the
            # cotangents arrive as per-device partials for us to scatter.
            # fsdp adds the other half of ZeRO-3: params REST dp-sharded
            # (in_specs = the sharded layout) and each step all-gathers
            # the stage's params transiently before computing.
            fsdp = self.fsdp
            scatter_dims_ = scatter_dims

            def _z2_grads(params, tokens, targets, step):
                key = train_key(step)
                if use_1f1b:
                    loss, grads = local_1f1b(
                        params, tokens, targets, key,
                        grad_reduce=self._reduce_scatter_dp)
                else:
                    gpipe_loss = (local_loss_virtual if vpp > 1
                                  else local_loss)
                    (loss, _), raw = jax.value_and_grad(
                        gpipe_loss, has_aux=True)(
                            _pvary(params, vary_axes), tokens, targets,
                            key)
                    grads = self._reduce_scatter_dp(raw)
                    loss = jax.lax.psum(
                        loss, ("pp", "sp") if self.has_sp else "pp")
                loss = jax.lax.pmean(loss, "dp")
                grads = tree_map(lambda g: g / self.dp, grads)
                return loss, grads

            def _gather_params(params):
                leaves, tdef = jax.tree_util.tree_flatten(params)
                full = [jax.lax.all_gather(l, "dp", axis=dim,
                                           tiled=True)
                        if dim is not None else l
                        for l, dim in zip(leaves, scatter_dims_)]
                return jax.tree_util.tree_unflatten(tdef, full)

            in_pspec = self._gspecs2 if fsdp else pspecs
            lg2_out = ((P(), self._gspecs2) if health == "off"
                       else (P(), self._gspecs2, P()))

            @jax.jit
            @partial(shard_map, mesh=self.mesh,
                     in_specs=(in_pspec, dspec, dspec, P()),
                     out_specs=lg2_out)
            def _loss_grads2(params, tokens, targets, step):
                params_in = params
                if fsdp:
                    params = _gather_params(params)
                loss, grads = _z2_grads(params, tokens, targets, step)
                if health == "off":
                    return loss, grads
                # param stats on the RESTING (possibly dp-sharded)
                # layout; grad stats on the dp-scattered ZeRO-2 layout
                return loss, grads, make_pack(params_in, grads,
                                              self._gspecs2, in_pspec)

            self._loss_grads_fn = _loss_grads2

            @jax.jit
            @partial(shard_map, mesh=self.mesh,
                     in_specs=(in_pspec, dspec, dspec), out_specs=P())
            def _eval_z(params, tokens, targets):
                if fsdp:
                    params = _gather_params(params)
                loss, _ = loss_fn(params, tokens, targets, train=False)
                loss = jax.lax.psum(
                    loss, ("pp", "sp") if self.has_sp else "pp")
                return jax.lax.pmean(loss, "dp")

            _eval = _eval_z
        if self.zero1 or self.zero2 or self.fsdp:
            from shallowspeed_tpu.parallel.zero import (
                make_zero1_update, shard_state_zero1)

            if not self.fsdp:  # fsdp moments inherit the placement
                self.opt_state = shard_state_zero1(self.opt_state,
                                                   self.mesh)
            # the GSPMD update uses the CALLER's optimizer (no manual
            # clip axes: the global-norm reduction over pp/dp-sharded
            # leaves is GSPMD's job in this program)
            self._update_fn = make_zero1_update(
                self.optimizer, self.params, self.opt_state,
                health=health)
            if self.zero1:
                self._loss_grads_fn = _loss_grads
            self._step_fn = None
        else:
            self._step_fn = _step
        self._eval_fn = _eval

    # ----------------------------------------------------------------- data

    def _split_mu(self, arr: np.ndarray):
        b, t = arr.shape
        dshard = self.dp * self.ep   # row-sharding degree (ep is data)
        assert b % (dshard * self.n_mu) == 0, (
            f"batch {b} must divide over dp*ep={dshard} x "
            f"n_mubatches={self.n_mu}")
        assert t <= self.cfg.max_seq
        assert t % self.sp == 0, (
            f"sequence length {t} must divide over sp={self.sp}")
        mubs = b // (dshard * self.n_mu)
        spec = (P(None, "dp", "sp") if self.has_sp else
                P(None, ("dp", "ep")) if self.has_ep else P(None, "dp"))
        # (B, T) -> (n_mu, dp*ep*mubs, T): microbatch-major so each row
        # shard of axis 1 holds rows of every microbatch (dp-major then
        # ep, matching the P(('dp','ep')) tuple order). place_global
        # (not a bare device_put) so multi-controller runs stitch each
        # process's host-local piece into the global batch
        # (distributed.py).
        from shallowspeed_tpu.distributed import place_global

        return place_global(
            np.ascontiguousarray(
                arr.reshape(dshard, self.n_mu, mubs, t)
                .transpose(1, 0, 2, 3).reshape(self.n_mu, -1, t)),
            NamedSharding(self.mesh, spec), local=False)

    def place(self, arr) -> jax.Array:
        if isinstance(arr, jax.Array):
            return arr
        return self._split_mu(arr)

    # ---------------------------------------------------------------- steps

    def train_batch_async(self, tokens, targets) -> jax.Array:
        from shallowspeed_tpu.telemetry import tracer

        step = np.uint32(self._step_count)
        self._step_count += 1
        tok, tgt = self.place(tokens), self.place(targets)
        monitored = self.health != "off"
        with tracer().span("step", step=int(step),
                           schedule=self.schedule) as sp:
            if self._step_fn is None:  # zero1: grads + GSPMD update
                with tracer().span("grads", step=int(step)) as g:
                    out = self._loss_grads_fn(
                        self.params, tok, tgt, step)
                    loss, grads = out[0], out[1]
                    g.fence(loss)
                with tracer().span("update", step=int(step)) as u:
                    if self._telemetry_eps is None \
                            and tracer().level != "off":
                        self._record_entrypoints(tok, tgt, grads=grads)
                    if self.health == "guard":
                        self.params, self.opt_state, upd = \
                            self._update_fn(self.params, grads,
                                            self.opt_state,
                                            out[2]["nonfinite"] == 0)
                        _note_step(self, {**out[2], **upd})
                    elif monitored:
                        self.params, self.opt_state, upd = \
                            self._update_fn(self.params, grads,
                                            self.opt_state)
                        _note_step(self, {**out[2], **upd})
                    else:
                        self.params, self.opt_state = self._update_fn(
                            self.params, grads, self.opt_state)
                    u.fence(self.opt_state)
            else:
                out = self._step_fn(
                    self.params, self.opt_state, tok, tgt, step)
                self.params, self.opt_state, loss = out[:3]
                if monitored:
                    _note_step(self, out[3])
                if self._telemetry_eps is None \
                        and tracer().level != "off":
                    self._record_entrypoints(tok, tgt)
            sp.fence(loss)
        return loss

    # ----------------------------------------------- telemetry surface

    _telemetry_eps = None

    def _record_entrypoints(self, tok, tgt, grads=None):
        """One-time (first traced step) skeleton capture for
        telemetry's static accounting (report.py resolves the
        conventional entrypoint attributes); `tok`/`tgt` are already
        microbatch-split and placed, matching what the compiled step
        consumes."""
        from shallowspeed_tpu.telemetry.report import (
            record_engine_entrypoints)

        self._telemetry_eps = record_engine_entrypoints(
            self, tok, tgt, grads=grads)

    def telemetry_entrypoints(self) -> list:
        """(name, fn, SDS args) per compiled entrypoint, step first
        (report.py convention); empty before the first traced step."""
        return list(self._telemetry_eps or ())

    def schedule_info(self) -> dict:
        """What `telemetry.bubble.static_bubble` needs to price this
        engine's schedule (the executed tables' identity)."""
        return {"schedule": self.schedule, "n_mu": self.n_mu,
                "pp": self.pp, "vpp": self.vpp}

    def health_snapshot(self) -> dict | None:
        """The last step's health pack as a host dict (one device_get —
        call at log points); None before the first step or with
        health='off'."""
        from shallowspeed_tpu.telemetry.health import engine_snapshot

        return engine_snapshot(self)

    def make_calibration_twin(self) -> "PipelineLMEngine":
        """A fresh engine at 2x microbatches for the two-point bubble
        measurement (`telemetry.bubble.calibrate_compiled`): fed a
        row-doubled batch it keeps the per-microbatch shape — and hence
        the per-round cost — identical, so the step-time difference is
        exactly n_mu rounds of pipeline work. Fresh params/opt state;
        never touches this engine's training state."""
        return PipelineLMEngine(
            self.cfg, self.optimizer, self.mesh,
            n_mubatches=2 * self.n_mu, seed=self._seed,
            schedule=self.schedule, attn=self.attn,
            virtual_pp=self.vpp, zero1=self.zero1, zero2=self.zero2,
            fsdp=self.fsdp)

    def train_batch(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        return float(self.train_batch_async(tokens, targets))

    def eval_loss(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        return float(self._eval_fn(self.params, self.place(tokens),
                                   self.place(targets)))

    # ------------------------------------------------ pipelined decode

    def _build_generate(self, tp_len: int, max_new: int,
                        temperature: float, top_k: int, top_p: float):
        """Compile decode on the pp-SHARDED params — the round-2 verdict's
        missing path (`generate()` used to require re-gathering a
        pipelined model onto one device's memory, defeating the point of
        pipelining it). One shard_map program:

        - **Pipelined prefill**: pp*vpp phases; in phase ph, device
          ph%pp runs chunk ph//pp (logical stage ph) over the whole
          prompt (capturing K/V into that chunk's rows of its LOCAL
          cache) and the activations hop right. Interleaved layouts
          (vpp > 1, round 5) need no special routing: logical stage
          l = v*pp + d puts consecutive stages on consecutive devices,
          so the single-hop-per-phase chain visits chunks in logical
          order automatically — the ring wrap from device pp-1 to 0 IS
          the chunk boundary.
        - **Decode loop** (`lax.scan` over max_new-1): each token makes
          the same pp*vpp-phase trip; the last logical stage's hidden
          state lands back on stage 0 (the ring hop), which holds the
          replicated head, samples, and `psum`-broadcasts the token to
          all stages for the next step's embedding. Per-token cost is
          the inherent logical-stage latency chain; each hop moves only
          (B, 1, d).

        Stage compute sits behind `lax.cond` (the bubble phases cost
        nothing) — safe here, unlike the sp training path, because
        decode blocks contain NO collectives; the only collectives
        (ppermute hop, token psum) run unconditionally every phase.
        Batch rows shard over 'dp' and decode independently."""
        from shallowspeed_tpu.models.generate import (
            _block_decode, _sample)

        cfg = self.cfg
        pp = self.pp
        s_right = [(i, (i + 1) % pp) for i in range(pp)]
        assert self.tp == 1 and self.sp == 1 and self.ep == 1, (
            "pipelined decode supports ('dp','pp') meshes (tp/sp/ep "
            "size 1; ep decode would need the all-to-all inside "
            "cond-gated phases — restore into an ep=1 pipeline to "
            "sample)")
        assert not self.fsdp, (
            "pipelined decode needs stage-resident params; restore the "
            "checkpoint into a non-fsdp pipeline to sample")
        attn = partial(attention, causal=True, window=cfg.attn_window)
        dt = cfg.compute_dtype or cfg.dtype
        l_local = self.l_local
        vpp = self.vpp
        depth = pp * vpp
        lcv = l_local // vpp  # layers per chunk (== l_local at vpp=1)

        def embed_prompt(params_c, tok):
            x = params_c["tok_emb"][tok]
            if not cfg.rope:
                x = x + params_c["pos_emb"][jnp.arange(tp_len)]
            return x.astype(dt)

        def embed_tok(params_c, tok, pos):
            x = params_c["tok_emb"][tok[:, None]]
            if not cfg.rope:
                x = x + params_c["pos_emb"][pos][None, None]
            return x.astype(dt)

        def head(params_c, x_last):
            return T.head_logits(
                params_c, T._norm(params_c["ln_f"], x_last, cfg),
                cfg).astype(jnp.float32)

        pspec_leaves = tree_map(lambda s_: s_, self._pspecs,
                                is_leaf=lambda x: isinstance(x, P))

        @partial(shard_map, mesh=self.mesh,
                 in_specs=(pspec_leaves, P("dp"), P(), P()),
                 out_specs=P(None, "dp"))
        def _gen(params, prompt, tp_actual, seed):
            s = jax.lax.axis_index("pp")
            params_c = T.cast_params(params, cfg.compute_dtype)
            b = prompt.shape[0]
            # cache sized to the generation (bucket + max_new), not
            # max_seq; `tp_actual` is the traced true prompt length —
            # pad-slot K/V is overwritten before the position mask can
            # admit it (same argument as models.generate). Head-major
            # slot layout (round 5), matching init_kv_cache: each
            # (b, head) decode sweep reads one contiguous (S, hd) block
            cshape = (l_local, b, cfg.kv_heads, tp_len + max_new,
                      cfg.head_dim)
            # zeros are axis-invariant; the filled cache / hopped
            # activations vary over (pp, dp) — pvary so lax.cond
            # branches and scan carries type-match
            cache = _pvary({"k": jnp.zeros(cshape, dt),
                            "v": jnp.zeros(cshape, dt)}, ("pp", "dp"))

            # ------------- pipelined prefill (pp*vpp logical phases)
            def chunk_blocks(v):
                return tree_map(
                    lambda a: jax.lax.dynamic_slice_in_dim(
                        a, v * lcv, lcv), params_c["blocks"])

            def pre_work(h, cache, v):
                x = jnp.where((s == 0) & (v == 0),
                              embed_prompt(params_c, prompt), h)

                def body(x, blk):
                    x, _aux, kv = T._block(blk, x, cfg, attn,
                                           with_kv=True,
                                           pos=jnp.arange(tp_len))
                    return x, kv

                x, (ks, vs) = jax.lax.scan(body, x, chunk_blocks(v))
                # captured K/V arrive token-major (lcv, b, T, kvh, hd);
                # the cache is head-major — transpose once per prefill
                cache = {
                    "k": jax.lax.dynamic_update_slice(
                        cache["k"], jnp.swapaxes(ks, 2, 3).astype(dt),
                        (v * lcv, 0, 0, 0, 0)),
                    "v": jax.lax.dynamic_update_slice(
                        cache["v"], jnp.swapaxes(vs, 2, 3).astype(dt),
                        (v * lcv, 0, 0, 0, 0)),
                }
                return x, cache

            def phase(carry, ph):
                h, cache = carry
                h, cache = jax.lax.cond(
                    ph % pp == s, pre_work,
                    lambda h, c, v: (h, c), h, cache, ph // pp)
                return (jax.lax.ppermute(h, "pp", s_right), cache), None

            h0 = _pvary(jnp.zeros((b, tp_len, cfg.d_model), dt),
                        ("pp", "dp"))
            (h, cache), _ = jax.lax.scan(phase, (h0, cache),
                                         jnp.arange(depth))
            # after depth hops the final stage's output sits on stage 0
            logits = head(params_c, jax.lax.dynamic_index_in_dim(
                h, tp_actual - 1, 1, False))
            # fold the dp coordinate in (dp>1 only — statically gated so
            # dp=1 keeps the replicated path's exact key stream): each
            # dp shard samples its LOCAL (B/dp, V) logit rows, so shards
            # sharing a key would draw identical gumbel noise
            # row-for-row (correlated streams). Sampled (temperature>0)
            # streams therefore match the replicated models.generate
            # path bit-exactly at dp=1 only (categorical derives
            # per-row noise from the batch shape); greedy decode
            # matches at any dp.
            rng0 = jax.random.PRNGKey(seed)
            if self.dp > 1:
                rng0 = jax.random.fold_in(rng0,
                                          jax.lax.axis_index("dp"))
            tok0 = _sample(logits, jax.random.fold_in(rng0, 0),
                           temperature, top_k, top_p)
            tok0 = jax.lax.psum(jnp.where(s == 0, tok0, 0), "pp")

            # ------- decode loop (each token: pp*vpp logical phases)
            def dstep(carry, i):
                tok_prev, cache = carry
                pos = tp_actual + i

                def work(h, cache, v):
                    x = jnp.where((s == 0) & (v == 0),
                                  embed_tok(params_c, tok_prev, pos), h)
                    cache_v = tree_map(
                        lambda a: jax.lax.dynamic_slice_in_dim(
                            a, v * lcv, lcv), cache)

                    def body(x, xs):
                        blk, cblk = xs
                        x, cblk = _block_decode(blk, x, cfg, cblk, pos)
                        return x, cblk

                    x, cache_v = jax.lax.scan(
                        body, x, (chunk_blocks(v), cache_v))
                    cache = tree_map(
                        lambda a, upd: jax.lax.dynamic_update_slice(
                            a, upd, (v * lcv,) + (0,) * (a.ndim - 1)),
                        cache, cache_v)
                    return x, cache

                def phase(carry2, ph):
                    h, cache = carry2
                    h, cache = jax.lax.cond(
                        ph % pp == s, work,
                        lambda h, c, v: (h, c), h, cache, ph // pp)
                    return (jax.lax.ppermute(h, "pp", s_right),
                            cache), None

                h0 = _pvary(jnp.zeros((b, 1, cfg.d_model), dt),
                            ("pp", "dp"))
                (h, cache), _ = jax.lax.scan(phase, (h0, cache),
                                             jnp.arange(depth))
                logits = head(params_c, h[:, 0])
                tok = _sample(logits, jax.random.fold_in(rng0, i + 1),
                              temperature, top_k, top_p)
                tok = jax.lax.psum(jnp.where(s == 0, tok, 0), "pp")
                return (tok, cache), tok

            (_, _), toks = jax.lax.scan(dstep, (tok0, cache),
                                        jnp.arange(max_new - 1))
            return jnp.concatenate([tok0[None], toks], axis=0)

        return jax.jit(_gen)

    def generate(self, prompt: np.ndarray, max_new: int,
                 temperature: float = 1.0, top_k: int = 0,
                 top_p: float = 0.0, seed: int = 0) -> np.ndarray:
        """Sample `max_new` tokens after `prompt` (B, Tp) ON the
        pp-sharded params (no re-gather). Returns (B, max_new) int32.
        Token-stream-identical to `models.generate.generate` on the
        canonical params (same sampling keys; asserted in tests) for
        greedy decode at any dp and for sampled decode at dp=1; under
        dp>1 sampled streams are independent per shard (the dp
        coordinate is folded into the key) but not bit-equal to the
        replicated path's, whose per-row noise depends on the full
        batch shape."""
        from shallowspeed_tpu.models.generate import prompt_bucket_len

        b, tp_len = prompt.shape
        assert tp_len + max_new <= self.cfg.max_seq, (
            f"prompt {tp_len} + max_new {max_new} exceeds "
            f"max_seq={self.cfg.max_seq}")
        pad = (-b) % self.dp
        if pad:  # dp shards batch rows; replicate the last row to fit
            prompt = np.concatenate(
                [prompt, np.repeat(prompt[-1:], pad, axis=0)], axis=0)
        # compile-key on the 64-token prompt BUCKET (true length is a
        # traced argument): same-bucket prompts share one executable
        tp_b = prompt_bucket_len(tp_len, max_new, self.cfg.max_seq)
        if tp_b != tp_len:
            prompt = np.pad(prompt, ((0, 0), (0, tp_b - tp_len)))
        key = (tp_b, max_new, temperature, top_k, top_p)
        cache = getattr(self, "_gen_cache", None)
        if cache is None or cache[0] != key:
            self._gen_cache = (key, self._build_generate(
                tp_b, max_new, temperature, top_k, top_p))
        fn = self._gen_cache[1]
        out = fn(self.params,
                 jax.device_put(prompt.astype(np.int32),
                                NamedSharding(self.mesh, P("dp"))),
                 jnp.int32(tp_len), np.uint32(seed))
        return np.asarray(jax.device_get(out)).T[:b]

    # -------------------------------------------- checkpoint interface

    def _unpermute(self, tree):
        if self.vpp == 1:
            return tree
        return {**tree, "blocks": tree_map(
            lambda l: l[self._inv_perm], tree["blocks"])}

    def _permute(self, tree):
        if self.vpp == 1:
            return tree
        return {**tree, "blocks": tree_map(
            lambda l: l[self._perm], tree["blocks"])}

    def canon_export_tree(self, tree):
        """Params-shaped tree (e.g. Adam moments) -> canonical layout;
        the SAME transform params take into a checkpoint. fetch_global,
        not device_get: in a multi-controller run the pp/ep-sharded
        leaves are not fully addressable (collective — every process
        calls together, like a training step)."""
        from shallowspeed_tpu.distributed import fetch_global

        return unstack_blocks(self._unpermute(fetch_global(tree)),
                              self.cfg.n_layers)

    def canon_import_tree(self, tree):
        """Inverse of `canon_export_tree` (host-side; placement happens
        in `set_opt_state`)."""
        return self._permute(stack_blocks(tree_map(np.asarray, tree)))

    def get_canonical_params(self):
        from shallowspeed_tpu.distributed import fetch_global

        return unstack_blocks(self._unpermute(fetch_global(self.params)),
                              self.cfg.n_layers)

    def set_canonical_params(self, params):
        host = self._permute(stack_blocks(tree_map(np.asarray, params)))
        self.params = jax.device_put(
            host, tree_map(lambda s: NamedSharding(self.mesh, s),
                           self._store_specs,
                           is_leaf=lambda x: isinstance(x, P)))

    def set_opt_state(self, state):
        from shallowspeed_tpu.parallel.zero import replace_opt_state

        self.opt_state = replace_opt_state(self, state)
