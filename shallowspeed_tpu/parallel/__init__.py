from shallowspeed_tpu.parallel.mesh import make_mesh  # noqa: F401
from shallowspeed_tpu.parallel.instructions import *  # noqa: F401,F403
from shallowspeed_tpu.parallel.schedules import (  # noqa: F401
    GPipeSchedule,
    InferenceSchedule,
    NaiveParallelSchedule,
    PipeDreamSchedule,
    Schedule,
)
