"""Shared GSPMD engine base for the transformer family.

Every GSPMD-style engine here follows the same scaling-book recipe: pick a
mesh, annotate parameter shardings, jit one pure `(params, opt_state,
batch) -> (params, opt_state, loss)` step, and let XLA insert the
collectives. Subclasses (`parallel/tensor.py` Megatron TP,
`parallel/expert.py` MoE EP) differ only in the `PartitionSpec` pytree and
their config/mesh validation — everything else (placement, jitted step,
batch sharding, checkpoint interface) lives here once.
"""

from __future__ import annotations

from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from shallowspeed_tpu.models import transformer as T

tree_map = jax.tree_util.tree_map


def _note_step(engine, pack):
    # health.note_step, imported lazily (telemetry stays off the module
    # import path): stores last_health + device-side cumulative counters
    from shallowspeed_tpu.telemetry.health import note_step

    note_step(engine, pack)



class GSPMDEngine:
    """Data x model parallel trainer: batch over 'dp' (the first mesh
    axis), parameters placed per `self.param_specs(cfg)`."""

    # this family's param LAYOUT is the canonical checkpoint layout
    # (sharding is placement, not structure) — so its optimizer state
    # interchanges engine-agnostically as-is (checkpoint.py)
    canonical_opt_identity = True

    # Explicit comm/compute overlap (parallel/overlap.py) needs named-
    # axis collectives to place; a plain GSPMD program has none — its
    # collectives are compiler-inserted and compiler-scheduled, which
    # is exactly the reliance the FSDP subclass's overlapped shard_map
    # step replaces. Subclasses that build one set this True.
    supports_overlap = False

    def __init__(self, cfg: T.TransformerConfig, optimizer, mesh: Mesh,
                 seed: int = 0, zero1: bool = False, zero2: bool = False,
                 health: str = "off", overlap=None):
        from shallowspeed_tpu.telemetry.health import MODES

        assert not (zero1 and zero2), "zero2 subsumes zero1"
        assert health in MODES, health
        if overlap is not None and not self.supports_overlap:
            raise ValueError(
                f"{type(self).__name__} is GSPMD-partitioned — its "
                f"collectives are compiler-inserted and cannot be "
                f"bucketed explicitly; --overlap supports the fsdp, "
                f"context (dense/zero1/zero2), fused-dp, and spmd "
                f"pipeline engines")
        self.cfg = cfg
        self.mesh = mesh
        self.optimizer = optimizer
        self.health = health
        self.last_health = None
        self.overlap = overlap  # parallel.overlap.OverlapConfig | None
        self.validate(cfg, mesh)
        self.dp = mesh.devices.shape[0]
        self._seed = seed

        # one host-side init; exposed to param_specs so shape-dependent
        # placements (FSDP) don't re-run it
        params_host = T.init(cfg, seed)
        self._params_host = params_host
        self._step_count = 0
        self.shardings = tree_map(
            lambda s: NamedSharding(mesh, s), self.param_specs(cfg),
            is_leaf=lambda x: isinstance(x, P))
        self.rep = NamedSharding(mesh, P())
        self.batch = NamedSharding(mesh, self.batch_spec())

        self.params = jax.device_put(params_host, self.shardings)
        self._params_host = None  # free the host copy
        # zeros_like preserves sharding, so optimizer moments inherit the
        # parameter placement with no extra spec bookkeeping; leaves created
        # fresh (e.g. Adam's step counter) get pinned replicated.
        self.opt_state = tree_map(self._mesh_or_replicated,
                                  optimizer.init(self.params))

        opt = optimizer

        def train_key(step):
            """Per-step dropout key (None when the config has no dropout,
            keeping RNG out of the trace); deterministic in (seed, step)."""
            if cfg.dropout == 0.0 and cfg.attn_dropout == 0.0:
                return None
            return jax.random.fold_in(jax.random.PRNGKey(seed), step)

        if zero1 or zero2:
            from shallowspeed_tpu.parallel.zero import (
                make_zero1_update, shard_state_zero1, zero2_grad_specs)

            self.opt_state = shard_state_zero1(self.opt_state, mesh)

            if zero2:
                # pin the grad outputs dp-sharded: XLA's partial-sum
                # propagation lowers the DP all-reduce to reduce-scatter
                # and the persistent grad buffer is 1/dp per device,
                # leaf-aligned with the ZeRO-1-placed moments
                gshard = tree_map(
                    lambda s: NamedSharding(mesh, s),
                    zero2_grad_specs(self.params, mesh),
                    is_leaf=lambda x: isinstance(x, P))
                out_sh = (NamedSharding(mesh, P()), gshard)
            else:
                out_sh = None
            if health != "off" and out_sh is not None:
                out_sh = (*out_sh, None)

            @partial(jax.jit, out_shardings=out_sh)
            def _grads(params, tokens, targets, step):
                loss, grads = jax.value_and_grad(
                    lambda p: T.loss(p, tokens, targets, cfg,
                                     dropout_key=train_key(step)))(params)
                if health == "off":
                    return loss, grads
                # GSPMD program: plain jnp reductions are global (no
                # per-leaf spec axes); the update half of the pack
                # (update_ratio, skipped) rides the update program
                from shallowspeed_tpu.telemetry.health import grad_health

                return loss, grads, grad_health(params, grads)

            self._grads_fn = _grads
            self._update_fn = make_zero1_update(
                opt, self.params, self.opt_state, health=health)
            self._step_fn = None
        else:
            # pin the step's outputs to the DECLARED placements
            # (params: param_specs; moments: their live placement; loss:
            # replicated). Left unpinned, GSPMD is free to emit e.g. a
            # tp-sharded pos_emb when the gradient math makes that
            # locally cheaper — the second step then sees different
            # input shardings than the first and silently recompiles,
            # and the resting placement drifts from param_specs forever
            # after (caught by `analysis`'s retrace rule, round 6).
            out_sh = (self.shardings,
                      tree_map(lambda l: l.sharding, self.opt_state),
                      self.rep)
            if health != "off":
                out_sh = (*out_sh, None)  # + the health pack

            @partial(jax.jit, donate_argnums=(0, 1), out_shardings=out_sh)
            def _step(params, opt_state, tokens, targets, step):
                loss, grads = jax.value_and_grad(
                    lambda p: T.loss(p, tokens, targets, cfg,
                                     dropout_key=train_key(step)))(params)
                if health == "off":
                    params, opt_state = opt.step(params, grads, opt_state)
                    return params, opt_state, loss
                # health pack fused into the one step executable (zero
                # extra entrypoints); under "guard" the update is gated
                # on the nonfinite sentinel — a skipped step leaves
                # params and moments bit-identical (optim.guarded_step)
                from shallowspeed_tpu.telemetry.health import (
                    grad_health, update_health)

                pack = grad_health(params, grads)
                if health == "guard":
                    ok = pack["nonfinite"] == 0
                    new_p, new_s = opt.guarded_step(params, grads,
                                                    opt_state, ok)
                    pack = update_health(pack, params, new_p,
                                         skipped=1 - ok)
                else:
                    new_p, new_s = opt.step(params, grads, opt_state)
                    pack = update_health(pack, params, new_p)
                return new_p, new_s, loss, pack

            self._step_fn = _step
        self._eval_fn = jax.jit(
            lambda p, tok, tgt: T.loss(p, tok, tgt, cfg, train=False))
        self._logits_fn = jax.jit(
            lambda p, tok: T.forward(p, tok, cfg))

    # ------------------------------------------------ subclass surface

    def validate(self, cfg: T.TransformerConfig, mesh: Mesh) -> None:
        raise NotImplementedError

    def param_specs(self, cfg: T.TransformerConfig) -> dict:
        raise NotImplementedError

    def batch_spec(self) -> P:
        """(batch, seq) token sharding: batch over 'dp', and the sequence
        over 'sp' when the subclass's validate() sets `self.sp > 1`
        (composite 3-D, long-context MoE)."""
        if getattr(self, "sp", 1) > 1:
            return P("dp", "sp")
        return P("dp", None)

    # ------------------------------------------------------- training

    def _mesh_or_replicated(self, leaf):
        """Keep a leaf's mesh placement if it has one; replicate otherwise."""
        if isinstance(getattr(leaf, "sharding", None), NamedSharding):
            return leaf
        return jax.device_put(leaf, self.rep)

    def _place(self, arr: np.ndarray):
        # multi-host: arr is this process's local rows; single-process:
        # the global batch (place_global handles both)
        from shallowspeed_tpu.distributed import place_global

        # local rows x processes = global batch; it must divide over dp
        # (single-process: arr IS the global batch — the original invariant)
        assert (arr.shape[0] * jax.process_count()) % self.dp == 0, (
            arr.shape, self.dp)
        sp = getattr(self, "sp", 1)
        assert arr.shape[1] % sp == 0, (arr.shape, sp)
        assert arr.shape[1] <= self.cfg.max_seq
        return place_global(arr, self.batch)

    def place(self, arr) -> jax.Array:
        """Public placement hook (prefetch pipelines place batches ahead of
        the step; already-placed jax.Arrays pass through device_put as
        no-ops)."""
        return self._place(arr)

    def train_batch_async(self, tokens, targets) -> jax.Array:
        """One optimizer step; the loss returns as a LAZY device scalar so
        the dispatch loop never blocks on it (callers `float()` only when
        they actually log — see `data/prefetch.py`)."""
        from shallowspeed_tpu.telemetry import tracer

        step = np.uint32(self._step_count)
        self._step_count += 1
        monitored = self.health != "off"
        with tracer().span("step", step=int(step)) as sp:
            if self._step_fn is None:  # ZeRO-1/2: grad program + update
                with tracer().span("grads", step=int(step)) as g:
                    out = self._grads_fn(
                        self.params, self._place(tokens),
                        self._place(targets), step)
                    loss, grads = out[0], out[1]
                    g.fence(loss)
                with tracer().span("update", step=int(step)) as u:
                    if self._telemetry_eps is None \
                            and tracer().level != "off":
                        self._record_entrypoints(tokens, targets,
                                                 grads=grads)
                    if self.health == "guard":
                        pack = out[2]
                        self.params, self.opt_state, upd = \
                            self._update_fn(self.params, grads,
                                            self.opt_state,
                                            pack["nonfinite"] == 0)
                        _note_step(self, {**pack, **upd})
                    elif monitored:
                        pack = out[2]
                        self.params, self.opt_state, upd = \
                            self._update_fn(self.params, grads,
                                            self.opt_state)
                        _note_step(self, {**pack, **upd})
                    else:
                        self.params, self.opt_state = self._update_fn(
                            self.params, grads, self.opt_state)
                    u.fence(self.opt_state)
            else:
                out = self._step_fn(
                    self.params, self.opt_state,
                    self._place(tokens), self._place(targets), step)
                self.params, self.opt_state, loss = out[:3]
                if monitored:
                    _note_step(self, out[3])
                if self._telemetry_eps is None \
                        and tracer().level != "off":
                    self._record_entrypoints(tokens, targets)
            sp.fence(loss)
        return loss

    # ----------------------------------------------- telemetry surface

    _telemetry_eps = None

    def _record_entrypoints(self, tokens, targets, grads=None):
        """One-time (first traced step) skeleton capture for
        telemetry's static accounting (report.py resolves the
        conventional entrypoint attributes)."""
        from shallowspeed_tpu.telemetry.report import (
            record_engine_entrypoints)

        self._telemetry_eps = record_engine_entrypoints(
            self, tokens, targets, grads=grads)

    def telemetry_entrypoints(self) -> list:
        """(name, jitted fn, SDS args) per compiled entrypoint — first
        entry is THE step program (report.py convention). Empty until
        the first step has run under an active tracer (the skeletons
        come from real batches)."""
        return list(self._telemetry_eps or ())

    def health_snapshot(self) -> dict | None:
        """The last step's health pack as a plain host dict (one
        device_get — call at log points, like every telemetry fetch);
        None before the first step or with health='off'."""
        from shallowspeed_tpu.telemetry.health import engine_snapshot

        return engine_snapshot(self)

    def train_batch(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        return float(self.train_batch_async(tokens, targets))

    def eval_loss(self, tokens: np.ndarray, targets: np.ndarray) -> float:
        return float(self._eval_fn(
            self.params, self._place(tokens), self._place(targets)))

    def logits(self, tokens: np.ndarray) -> jax.Array:
        return self._logits_fn(self.params, self._place(tokens))

    def router_stats(self, tokens) -> dict | None:
        """MoE routing observability on one batch: per-expert fraction of
        (token, k) assignments (pre-drop) and the dropped-assignment
        fraction — the numbers that make `ops/moe.py`'s silent capacity
        drop visible. None for dense configs. Train-mode forward without
        dropout; costs one extra forward, so call at log points only."""
        if self.cfg.n_experts == 0:
            return None
        if not hasattr(self, "_stats_fn"):
            self._stats_fn = jax.jit(lambda p, tok: T.forward_with_aux(
                p, tok, self.cfg, with_stats=True)[2])
        st = jax.device_get(
            self._stats_fn(self.params, self._place(tokens)))
        return {"expert_load": [round(float(x), 4) for x in st["load"]],
                "drop_fraction": round(float(st["drop_fraction"]), 4)}

    # -------------------------------------------- checkpoint interface

    def get_canonical_params(self):
        return self.params

    def set_canonical_params(self, params):
        self.params = jax.device_put(
            jax.device_get(params), self.shardings)

    def set_opt_state(self, state):
        # the live opt_state is the placement template — preserves param-
        # inherited moment placement and ZeRO-1 dp-sharding alike.
        from shallowspeed_tpu.parallel.zero import replace_opt_state

        self.opt_state = replace_opt_state(self, state)
