"""Device-mesh construction — the L3 communication topology.

Replaces the reference's MPI communicator splits (`train.py:87-94`:
`COMM_WORLD.Split(color=rank % PP)` → dp_comm, `Split(color=rank // PP)` →
pp_comm) with a 2-D `jax.sharding.Mesh` over TPU devices. Collectives scoped
to `dp_comm` become collectives over the `'dp'` mesh axis; `pp_comm`
Send/Recv becomes `lax.ppermute` over `'pp'`. On a pod slice both axes ride
ICI; across hosts XLA routes DCN — no MPI/NCCL anywhere.
"""

from __future__ import annotations

import numpy as np
import jax
from jax.sharding import Mesh


def make_mesh(dp: int = 1, pp: int = 1, devices=None) -> Mesh:
    """A (dp, pp) mesh. `dp * pp` must not exceed the device count; with a
    single device both axes are size-1 (sequential training)."""
    if devices is None:
        devices = jax.devices()
    n = dp * pp
    assert n >= 1
    assert n <= len(devices), (
        f"requested dp={dp} x pp={pp} = {n} devices, have {len(devices)}")
    grid = np.array(devices[:n]).reshape(dp, pp)
    return Mesh(grid, axis_names=("dp", "pp"))
