"""ZeRO-1 / ZeRO-2 sharding over the data-parallel axis.

The reference replicates optimizer state on every DP rank (its own SGD is
stateless, `/root/reference/shallowspeed/optimizer.py:4-13`, but its PyTorch
DDP baseline trains with full per-rank Adam state,
`scripts/DDP_PyTorch_MNIST.py`). For stateful optimizers the moments
dominate training memory (Adam: 2x the parameters); ZeRO stage 1
(Rajbhandari et al., ZeRO, 2020) shards them across the DP group so the
per-device optimizer footprint is 1/dp.

TPU-native formulation — no hand-written reduce-scatter / all-gather:

1. *Place* each moment leaf sharded over the 'dp' mesh axis (on its first
   divisible, not-yet-sharded dimension; `shard_state_zero1`).
2. Split the training step: the gradient program stays whatever the engine
   uses (shard_map ring step, GSPMD step, ...); the optimizer update becomes
   a separate jitted pure function whose `out_shardings` pin parameters to
   their original placement and moments to the dp-sharded placement
   (`make_zero1_update`).

GSPMD then partitions the elementwise update where the moments live — each
device updates only its 1/dp slice — and inserts the parameter all-gather
itself. The compiler derives exactly the communication pattern DeepSpeed's
implementation hand-codes, and remains free to fuse/schedule it.

**ZeRO-2** adds gradient sharding on top: the gradient program emits each
grad leaf dp-sharded instead of replicated, so the DP reduction lowers to
a *reduce-scatter* (half an all-reduce's bytes on the wire) and the
persistent grad buffer handed to the update is 1/dp per device, matching
the moments' placement — the update stays fully local, and only the new
parameters are all-gathered. Two equivalent formulations, one per engine
style (`zero2_grad_specs` serves both):

- GSPMD engines: pin the grad outputs' `out_shardings`; XLA's partial-sum
  propagation turns the all-reduce into reduce-scatter on its own.
- shard_map engines: pvary the params so cotangents arrive as per-tile
  partials, then `lax.psum_scatter` each leaf over 'dp' explicitly.

A third formulation composes with both stages (round 8,
`parallel/overlap.py`): with `overlap=OverlapConfig(...)` the shard_map
engines move the reduction INSIDE the backward — ZeRO-1 grads reduce
through per-bucket psum tags, ZeRO-2 grads through per-leaf
`psum_scatter` tags whose scatter dimension is read off
`zero2_grad_dim` exactly like the bulk path, so the sharded update
(`make_zero1_update`) sees an identical 1/dp grad layout whether the
scatter ran after the accumulation scan (bulk oracle) or interleaved
with the backward (overlapped). The leaf-alignment invariant this
module encodes is therefore load-bearing for three reduction
schedules, and `tests/test_overlap.py` pins all of them against the
dense oracle.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

tree_map = jax.tree_util.tree_map


def _spec_axes_used(spec: P) -> set:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def zero2_grad_dim(spec: P, shape, size: int, axis: str = "dp"):
    """The dimension `axis` lands on for a leaf with this spec/shape —
    the first unsharded dimension divisible by `size` — or None if no
    dimension qualifies. THE single encoding of the placement rule:
    `_with_axis` (moment placement) builds on it, so ZeRO-2 grad sharding
    and ZeRO-1 moment sharding can never diverge."""
    if axis in _spec_axes_used(spec):
        return None
    entries = list(spec) + [None] * (len(shape) - len(spec))
    for i, dim in enumerate(shape):
        if entries[i] is None and dim and dim % size == 0:
            return i
    return None


def _with_axis(spec: P, shape, size: int, axis: str) -> P:
    """Add `axis` to the leaf's `zero2_grad_dim` dimension; return the
    spec unchanged if no dimension qualifies (leaf stays at its current —
    typically replicated — placement)."""
    i = zero2_grad_dim(spec, shape, size, axis)
    if i is None:
        return spec
    entries = list(spec) + [None] * (len(shape) - len(spec))
    entries[i] = axis
    return P(*entries)


def shard_state_zero1(opt_state: Any, mesh: Mesh, axis: str = "dp") -> Any:
    """Re-place an optimizer state pytree with every array leaf sharded over
    `axis` (scalars and non-divisible leaves stay replicated / as-placed)."""
    size = mesh.shape[axis]
    rep = NamedSharding(mesh, P())

    def place(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return jax.device_put(leaf, rep)
        sh = getattr(leaf, "sharding", None)
        cur = sh.spec if isinstance(sh, NamedSharding) else P()
        return jax.device_put(
            leaf, NamedSharding(mesh, _with_axis(cur, leaf.shape, size, axis)))

    return tree_map(place, opt_state)


def zero2_grad_specs(params: Any, mesh: Mesh, axis: str = "dp") -> Any:
    """PartitionSpec pytree for dp-sharded gradients: each leaf's current
    spec with `axis` added on its `zero2_grad_dim` (unchanged if none)."""
    size = mesh.shape[axis]

    def spec_of(leaf):
        sh = getattr(leaf, "sharding", None)
        cur = sh.spec if isinstance(sh, NamedSharding) else P()
        return _with_axis(cur, leaf.shape, size, axis)

    return tree_map(spec_of, params)


def make_zero1_update(optimizer, params: Any, opt_state: Any,
                      health: str = "off"):
    """Jitted `(params, grads, state) -> (params, state)` optimizer update.

    `params`/`opt_state` are placement templates: outputs are pinned to
    their shardings, so with a `shard_state_zero1`-placed state the update
    runs dp-sharded and XLA all-gathers the new parameters. Params and
    state are donated (outputs reuse their buffers); grads are not — their
    sharding never matches the dp-sharded outputs, so donating them only
    triggers unusable-donation warnings.

    `health` (telemetry/health.py): at "monitor" the update additionally
    returns {"update_ratio"} — the split-step engines' half of the
    health pack (the grad stats ride the gradient program). At "guard"
    the update takes a fourth `ok` argument (the gradient program's
    `nonfinite == 0` device scalar, no host sync) and gates the whole
    step on it via `optimizer.guarded_step` — a skipped step leaves
    params and state bit-identical — returning {"update_ratio",
    "skipped"}. Same executable count either way: one jit entrypoint."""
    param_sh = tree_map(lambda l: l.sharding, params)
    state_sh = tree_map(lambda l: l.sharding, opt_state)

    def upd_stats(old_p, new_p, skipped=None):
        # the shared health math (one 1e-12/f32-accumulation
        # convention): update_health's ratio over param_l2's norm
        from shallowspeed_tpu.telemetry.health import (param_l2,
                                                       update_health)

        pack = update_health({"param_norm": param_l2(old_p)}, old_p,
                             new_p, skipped=skipped)
        return {k: v for k, v in pack.items()
                if k in ("update_ratio", "skipped")}

    if health == "guard":

        @partial(jax.jit, donate_argnums=(0, 2),
                 out_shardings=(param_sh, state_sh, None))
        def update(params, grads, state, ok):
            new_p, new_s = optimizer.guarded_step(params, grads, state,
                                                  ok)
            return new_p, new_s, upd_stats(params, new_p,
                                           skipped=1 - ok)

        return update
    if health == "monitor":

        @partial(jax.jit, donate_argnums=(0, 2),
                 out_shardings=(param_sh, state_sh, None))
        def update(params, grads, state):
            new_p, new_s = optimizer.step(params, grads, state)
            return new_p, new_s, upd_stats(params, new_p)

        return update

    @partial(jax.jit, donate_argnums=(0, 2),
             out_shardings=(param_sh, state_sh))
    def update(params, grads, state):
        return optimizer.step(params, grads, state)

    return update


def replace_opt_state(engine, state: Any) -> Any:
    """Checkpoint-restore helper shared by the engines: re-place a restored
    state tree using the engine's live opt_state as the placement template
    (preserves ZeRO sharding and param-placement inheritance alike)."""
    rep = engine.rep

    def place(leaf, like):
        sh = getattr(like, "sharding", None)
        sh = sh if isinstance(sh, NamedSharding) else rep
        return jax.device_put(np.asarray(leaf), sh)

    return tree_map(place, state, engine.opt_state)
