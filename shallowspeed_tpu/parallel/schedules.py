"""Pipeline schedules as pure data — the L5 layer.

Capability parity with the reference's schedule framework
(`/root/reference/shallowspeed/pipe.py:141-299`): a `Schedule` ABC with
stage/microbatch predicates and a `steps()` generator yielding lists of
instructions, plus four concrete schedules. Schedules never touch devices or
arrays, so pipeline logic is testable for arbitrary (num_stages, stage_id)
with zero processes (`tests/test_schedules.py` — the reference's single most
reusable testing idea, SURVEY §4.3).

Going beyond the reference: `PipeDreamSchedule` is a *working* 1F1B
PipeDream-Flush implementation (the reference ships a constructor that raises
NotImplementedError, `pipe.py:297-299`, while advertising the flag in its CLI,
`train.py:53,72`). 1F1B caps in-flight activation stashes at
`num_stages - stage_id` instead of GPipe's `num_micro_batches`, which is the
memory headroom that matters on HBM-bound TPUs.
"""

from __future__ import annotations

from abc import ABC, abstractmethod

from shallowspeed_tpu.parallel.instructions import (
    BackwardGradAcc,
    BackwardGradAllReduce,
    Forward,
    LoadMuBatchInput,
    LoadMuBatchTarget,
    OptimizerStep,
    RecvActivations,
    RecvOutputGrad,
    SendActivations,
    SendInputGrad,
    ZeroGrad,
)


class Schedule(ABC):
    """Reference: `pipe.py:141-181`."""

    def __init__(self, num_micro_batches: int, num_stages: int, stage_id: int):
        assert stage_id < num_stages
        self.num_stages = num_stages
        self.stage_id = stage_id
        self.num_micro_batches = num_micro_batches

    @abstractmethod
    def steps(self):
        """Generator of instruction lists covering one full batch."""

    @property
    @abstractmethod
    def num_buffers(self):
        """Comm buffers needed (multiple of 2: input + output buffers)."""

    @property
    def is_first_stage(self):
        return self.stage_id == 0

    @property
    def is_last_stage(self):
        return self.stage_id == self.num_stages - 1

    def is_first_mubatch(self, mubatch_id):
        return mubatch_id == 0

    def is_last_mubatch(self, mubatch_id):
        return mubatch_id == self.num_micro_batches - 1

    def is_valid_stage_id(self, stage_id):
        return 0 <= stage_id < self.num_stages

    # -- shared per-microbatch building blocks ---------------------------

    def _fwd_cmds(self, mubatch_id, buffer_id=0, send=True):
        cmds = []
        if self.is_first_stage:
            cmds.append(LoadMuBatchInput(buffer_id=buffer_id, mubatch_id=mubatch_id))
        else:
            cmds.append(RecvActivations(buffer_id=buffer_id))
        cmds.append(Forward(buffer_id=buffer_id, mubatch_id=mubatch_id))
        if send and not self.is_last_stage:
            # Last stage discards its forward output: backward needs only the
            # targets + stashed activations (`pipe.py:262-264`).
            cmds.append(SendActivations(buffer_id=buffer_id))
        return cmds

    def _bwd_cmds(self, mubatch_id, allreduce, buffer_id=0):
        cmds = []
        if self.is_last_stage:
            cmds.append(LoadMuBatchTarget(buffer_id=buffer_id, mubatch_id=mubatch_id))
        else:
            cmds.append(RecvOutputGrad(buffer_id=buffer_id))
        bwd_cls = BackwardGradAllReduce if allreduce else BackwardGradAcc
        cmds.append(bwd_cls(buffer_id=buffer_id, mubatch_id=mubatch_id))
        if not self.is_first_stage:
            cmds.append(SendInputGrad(buffer_id=buffer_id))
        return cmds


class NaiveParallelSchedule(Schedule):
    """No interleaving: FWD then immediately BWD per microbatch, one stage
    active at a time. Reference: `pipe.py:184-222`."""

    def steps(self):
        yield [ZeroGrad()]
        for mubatch_id in range(self.num_micro_batches):
            yield self.steps_mubatch(mubatch_id)
        yield [OptimizerStep()]

    def steps_mubatch(self, mubatch_id):
        cmds = self._fwd_cmds(mubatch_id)
        if not self.is_last_stage:
            cmds.append(RecvOutputGrad(buffer_id=0))
        else:
            cmds.append(LoadMuBatchTarget(buffer_id=0, mubatch_id=mubatch_id))
        bwd_cls = (BackwardGradAllReduce if self.is_last_mubatch(mubatch_id)
                   else BackwardGradAcc)
        cmds.append(bwd_cls(buffer_id=0, mubatch_id=mubatch_id))
        if not self.is_first_stage:
            cmds.append(SendInputGrad(buffer_id=0))
        return cmds

    @property
    def num_buffers(self):
        return 2


class GPipeSchedule(Schedule):
    """All-FWD phase then all-BWD phase (reversed microbatch order), with the
    DP all-reduce interleaved into the final backward. Reference:
    `pipe.py:225-272`."""

    def steps(self):
        yield [ZeroGrad()]
        for mubatch_id in range(self.num_micro_batches):
            yield self.steps_FWD_mubatch(mubatch_id)
        for mubatch_id in reversed(range(self.num_micro_batches)):
            yield from self.steps_BWD_mubatch(mubatch_id)
        yield [OptimizerStep()]

    def steps_FWD_mubatch(self, mubatch_id):
        return self._fwd_cmds(mubatch_id)

    def steps_BWD_mubatch(self, mubatch_id):
        # AllReduce rides the first-loaded microbatch — the last one processed
        # in the reversed BWD order (`pipe.py:246-248`).
        yield self._bwd_cmds(mubatch_id, allreduce=self.is_first_mubatch(mubatch_id))

    @property
    def num_buffers(self):
        return 2


class InferenceSchedule(Schedule):
    """FWD-only pipeline streaming, used for evaluation. Reference:
    `pipe.py:275-294`."""

    def steps(self):
        for mubatch_id in range(self.num_micro_batches):
            yield self._fwd_cmds(mubatch_id)

    @property
    def num_buffers(self):
        return 2


class PipeDreamSchedule(Schedule):
    """PipeDream-Flush (1F1B, non-interleaved), fully implemented.

    The reference declares this schedule in its CLI and README but ships only
    `raise NotImplementedError` (`pipe.py:297-299`, `train.py:53,72`,
    `README.md:16`). Here it is real: each stage runs
    `min(num_stages - stage_id - 1, n_mu)` warmup forwards, then a steady
    1F1B phase, then drains the remaining backwards, then a flush
    (OptimizerStep) — same synchronous semantics as GPipe (identical final
    grads; verified in tests), but activation stashes are bounded by pipeline
    depth instead of microbatch count.

    BWD consumes microbatches in FIFO order (0,1,2,...), so the DP all-reduce
    rides the *last* microbatch id, unlike GPipe's reversed order where it
    rides microbatch 0.
    """

    def steps(self):
        yield [ZeroGrad()]
        n_mu = self.num_micro_batches
        num_warmup = min(self.num_stages - self.stage_id - 1, n_mu)
        num_steady = n_mu - num_warmup

        for mubatch_id in range(num_warmup):
            yield self._fwd_cmds(mubatch_id)

        for i in range(num_steady):
            fwd_mu = num_warmup + i
            bwd_mu = i
            yield self._fwd_cmds(fwd_mu)
            yield self._bwd_cmds(bwd_mu, allreduce=self.is_last_mubatch(bwd_mu))

        for bwd_mu in range(num_steady, n_mu):
            yield self._bwd_cmds(bwd_mu, allreduce=self.is_last_mubatch(bwd_mu))

        yield [OptimizerStep()]

    @property
    def num_buffers(self):
        return 2

    def max_stashed_mubatches(self):
        """Peak in-flight activation stashes on this stage — the 1F1B memory
        bound: min(num_stages - stage_id, n_mu)."""
        return min(self.num_stages - self.stage_id, self.num_micro_batches)
